"""The leader scheduler: store watches -> planner deltas -> dispatches.

Data flow per cycle (:meth:`step`):

1. drain cmd/group/node watch events into host mirrors (row allocator,
   EligibilityBuilder, schedule-row updates) — the analogue of the
   reference's watchJobs/watchGroups delta handlers (node/node.go:361-421),
   but feeding ONE device table instead of N in-process cron loops;
2. reconcile node capacity/load from the proc registry (crash-safe: derived
   from leased keys, so dead executions age out);
3. push dirty rows to the device (fixed-shape scatters);
4. plan the next window of seconds on device;
5. publish leased execution orders in one bulk write: exclusive jobs
   COALESCE into one key per (node, second) whose value is the node's
   job list (the key doubles as an outstanding-capacity reservation for
   len(jobs) slots); Common jobs get ONE broadcast key per (second, job)
   that every eligible agent picks up via its local IsRunOn (reference
   job kinds job.go:30-34, IsRunOn job.go:616-630).

Leadership: create-if-absent on the leader key under a lease
(client.go:95-109 pattern).  Standby instances keep retrying; on leader
death the lease expires and a standby takes over within ``lease_ttl``.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from operator import itemgetter
from typing import Callable, Dict, List, NamedTuple, Optional, Set, Tuple

import numpy as np

from .. import log
from ..core import Group, Job, Keyspace, TenantQuota
from ..core.models import KIND_ALONE
from ..cron.parser import ParseError, parse
from ..ops.deps import NEVER as DEP_NEVER, POLICY_BY_NAME
from ..ops.eligibility import EligibilityBuilder, NodeUniverse
from ..ops.planner import TickPlanner
from ..ops.schedule_table import DEP_BROKEN, FRAMEWORK_EPOCH, \
    make_dep_row, make_row, _INACTIVE_ROW
from ..store.memstore import CompactedError, DELETE, MemStore, PUT, \
    WatchLost

# ids that serialize into a JSON string verbatim (no escapes needed)
_WIRE_SAFE = re.compile(r"^[A-Za-z0-9_.:-]*$").match


class _BuildItem(NamedTuple):
    """One window handed from the step thread to the build worker:
    matured replan handles (oldest epochs, built first), the window's
    own plan handle, and the publisher submit arguments."""
    replans: list          # [(epoch, handle, fires)] — overflow replans
    handle: object         # plan_window_async handle for [covers_from..)
    lease: int
    hwm: int
    covers_from: int


def _list_prefix(store, prefix):
    """Iterate a prefix listing in bounded pages when the store supports
    it (remote stores): a 1M-key prefix as one reply is hundreds of MB
    whose json parse holds the GIL for seconds, starving every other
    thread in the process (measured: the background anti-entropy
    listing stretched a standby's step to ~30 s)."""
    if hasattr(store, "get_prefix_paged"):
        return store.get_prefix_paged(prefix)
    return store.get_prefix(prefix)


class _Rows:
    """Row allocator: (group, job_id, rule_id) -> schedule-table row."""

    def __init__(self, capacity: int):
        self._free = list(range(capacity - 1, -1, -1))
        self.by_cmd: Dict[Tuple[str, str, str], int] = {}
        self.by_row: Dict[int, Tuple[str, str, str]] = {}
        self.by_job: Dict[Tuple[str, str], Set[str]] = {}

    def acquire(self, group: str, job_id: str, rule_id: str) -> int:
        key = (group, job_id, rule_id)
        row = self.by_cmd.get(key)
        if row is None:
            if not self._free:
                raise RuntimeError("job row capacity exhausted")
            row = self._free.pop()
            self.by_cmd[key] = row
            self.by_row[row] = key
            self.by_job.setdefault((group, job_id), set()).add(rule_id)
        return row

    def release_rule(self, group: str, job_id: str, rule_id: str) -> Optional[int]:
        row = self.by_cmd.pop((group, job_id, rule_id), None)
        if row is not None:
            self._free.append(row)
            self.by_row.pop(row, None)
            rules = self.by_job.get((group, job_id))
            if rules:
                rules.discard(rule_id)
                if not rules:
                    del self.by_job[(group, job_id)]
        return row

    def rules_of(self, group: str, job_id: str) -> Set[str]:
        return set(self.by_job.get((group, job_id), ()))


class SchedulerService:
    def __init__(self, store: MemStore, ks: Optional[Keyspace] = None,
                 job_capacity: int = 4096, node_capacity: int = 256,
                 window_s: int = 4, lease_ttl: float = 10.0,
                 dispatch_ttl: float = 300.0,
                 default_node_cap: int = 1 << 20,
                 node_id: str = "scheduler-1",
                 planner: Optional[TickPlanner] = None,
                 tz=None,
                 publish_lanes: int = 0,
                 sync_publish: Optional[bool] = None,
                 pipelined: Optional[bool] = None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_interval_s: float = 0.0,
                 checkpoint_delta: Optional[bool] = None,
                 delta_max_chain: int = 64,
                 delta_max_bytes: int = 64 << 20,
                 delta_max_events: int = 1_000_000,
                 trace_shift: int = -1,
                 partitions: int = 1,
                 partition: int = 0,
                 acct_exchange_s: float = 2.0,
                 clock: Callable[[], float] = time.time):
        self.store = store
        self.ks = ks or Keyspace()
        self.clock = clock
        self.window_s = window_s
        self.lease_ttl = lease_ttl
        self.dispatch_ttl = dispatch_ttl
        self.default_node_cap = default_node_cap
        self.node_id = node_id

        # ---- partitioned scheduler plane --------------------------------
        # P independent leaders, each owning the job-space slice whose
        # 64-bit FNV job token (the store's own routing token) lands on
        # its index: own leader lease, own watch slice, own HWM, own
        # checkpoint chain.  P=1 is pure passthrough — same keys, same
        # wire bytes as the unpartitioned scheduler (pinned by test).
        self.partitions = max(1, int(partitions))
        self.partition = int(partition)
        if not 0 <= self.partition < self.partitions:
            raise ValueError(
                f"partition {self.partition} out of range for "
                f"{self.partitions} partitions")
        from .partition import pin_partition_map
        # publish-or-verify the topology pin BEFORE any state loads: a
        # mismatched scheduler must refuse, not double-schedule
        pin_partition_map(self.store, self.ks, self.partitions)
        # ownership predicate, bound once: None at P=1 so the per-event
        # filters cost a single None check on the unpartitioned path
        if self.partitions > 1:
            from .partition import job_partition as _jp
            _P, _i = self.partitions, self.partition
            self._owns: Optional[Callable[[str], bool]] = \
                lambda jid: _jp(jid, _P) == _i
        else:
            self._owns = None
        if self.partitions > 1:
            self._leader_key = self.ks.partition_leader_key(self.partition)
            self._hwm_key = self.ks.hwm_partition_key(self.partition)
            # exclusive bundles carry the owning partition in the key
            # (".<p>" epoch suffix): two partitions firing jobs on the
            # same (node, second) must not overwrite each other's
            # reservation, and the suffix scopes each partition's
            # order mirror to its own publishes
            self._bundle_sfx = f".{self.partition}"
        else:
            self._leader_key = self.ks.leader
            self._hwm_key = self.ks.hwm
            self._bundle_sfx = ""
        # foreign partitions' per-node demand (sched/acct/p<j> mirror):
        # key -> {node: (excl_slots, load)}, merged lazily into the
        # flat fold reconcile_capacity subtracts each step
        self.acct_exchange_s = max(0.25, float(acct_exchange_s))
        self._part_foreign: Dict[str, Dict[str, Tuple[int, float]]] = {}
        self._foreign_dirty = False
        self._foreign_excl: Dict[str, int] = {}
        self._foreign_load: Dict[str, float] = {}
        self._acct_lease: Optional[int] = None
        self._acct_next = 0.0
        self._w_acct = None

        planner_kw = {} if tz is None else {"tz": tz}
        self.planner = planner or TickPlanner(
            job_capacity=job_capacity, node_capacity=node_capacity,
            max_fire_bucket=min(65536, job_capacity), **planner_kw)
        self.universe = NodeUniverse(self.planner.N)
        self.builder = EligibilityBuilder(self.universe, self.planner.J)
        self.rows = _Rows(self.planner.J)
        self.jobs: Dict[Tuple[str, str], Job] = {}
        self.groups: Dict[str, Group] = {}
        self.node_caps: Dict[str, int] = {}

        self._table_updates: Dict[int, dict] = {}
        self._meta_updates: Dict[int, Tuple[bool, float]] = {}
        # Per-row dispatch cache: (exclusive, payload-json, group, job_id,
        # kind, "/group/job" key tail, json-quoted "group/job" bundle
        # entry), maintained by the job watch handlers so the per-fire
        # order-build loop is dict-lookup + list-append only — no
        # json.dumps, no Job lookup per fire (the leader's order build is
        # on the dispatch plane's critical path).
        self._row_dispatch: Dict[
            int, Tuple[bool, str, str, str, int, str, str]] = {}
        # the same dispatch cache as PARALLEL per-row ARRAYS, so the
        # vectorized order build fancy-indexes the fired rows instead of
        # doing a Python dict lookup per fire (the herd-second build was
        # 703 ms p50 at 110k fires).  Flags are written LAST on add and
        # cleared FIRST on drop: the build may run on the pipeline
        # worker while a watch drain mutates rows, and a row must never
        # look valid with half-written fields (the surviving race — a
        # fire built from the just-previous revision of a row — is the
        # same one-window staleness the device table already has).
        J = self.planner.J
        self._rd_flags = np.zeros(J, np.uint8)   # 1 valid|2 excl|4 alone
        # plain lists, extracted in batch with operator.itemgetter —
        # measurably faster than object-ndarray fancy indexing (which
        # pays a PyObject alloc+incref per element per array)
        self._rd_payload: list = [None] * J
        self._rd_suffix: list = [None] * J       # "/group/job" key tail
        self._rd_bentry: list = [None] * J       # json-quoted bundle entry
        self._rd_job: list = [None] * J          # (group, job_id)
        # trace plane (fire-lifecycle tracing): per-row FNV-1a partial
        # hash over "<job_id>|" — the per-second trace ids continue it
        # with the epoch string in ONE vectorized pass (O(digits), not
        # O(fires) Python hashing) — plus the per-job force-sample flag.
        # trace_shift < 0 (the default for direct constructions — every
        # bit-identity differential and divergence gate in the repo
        # builds services directly) disables stamping entirely and the
        # order wire stays byte-identical; bin/sched arms it from
        # conf.trace_sample_shift.  CRONSUN_TRACE=off overrides.
        from .. import trace as _trace
        self._trace = _trace
        self.trace_shift = trace_shift if _trace.armed() else -1
        self._rd_tbase = np.zeros(J, np.uint64)
        self._rd_tflag = np.zeros(J, bool)
        # build-time stamp per epoch second, cached so the vectorized
        # build, the reference build and an overflow replan of the same
        # second all stamp ONE value (differentials stay byte-identical)
        self._tb_cache: Dict[int, float] = {}
        # herd smearing: per-row jitter width (seconds, 0 = unsmeared),
        # mirrored from Job.jitter beside the other _rd_* columns.  The
        # smear delta for a fire of row r matched at logical second s is
        # fnv_continue(sbase[r], str(s)) % (jitter[r]+1) — sbase is a
        # cached FNV partial over the GROUP-QUALIFIED id
        # ("<group>/<id>|"), a sibling of the trace plane's tbase (which
        # stays keyed by the bare id: agents re-derive trace ids from
        # it, so sharing the seed would couple a smear re-key to an
        # agent migration), so the whole fired vector smears in one
        # O(digits) numpy pass and same-id jobs in different groups
        # still spread relative to each other.  _jitter_jobs
        # counts registered jobs with jitter > 0: while it is zero and
        # the spill ring is empty, _build_plan_orders dispatches
        # straight to the unsmeared build and the order wire stays
        # byte-identical to the pre-jitter program (the use_deps/
        # use_tenants disarm pattern, host-side edition).
        self._rd_jitter = np.zeros(J, np.int32)
        self._rd_sbase = np.zeros(J, np.uint64)
        self._jitter_jobs = 0
        self._max_jitter_seen = 0     # monotone max of live jitters
        # spill ring: fires whose smeared epoch lands past the window
        # being built wait here for a later window.  target epoch ->
        # {src_epoch: [rows, cols, emitted]} — GROUPED arrays, one
        # group per source second (all of a source's deferred fires for
        # one target share a fate: merged together, late-flushed
        # together, re-marked together), so the herd second's ~J/s
        # deferrals cost <= jitter vectorized slices instead of J dict
        # inserts.  NOT consumed on read (a hole-rewind rebuild must
        # re-emit the same arrivals so the bundle overwrite stays a
        # superset); pruned once the publisher's landed watermark
        # passes the target.  ``emitted`` gates the rare LATE path only
        # (an overflow replan smearing into an already-published
        # second) — those go out as standalone legacy per-job orders,
        # exactly once unless a publish failure clears the marks for a
        # merge-idempotent re-emission.  _smear_lock serializes ring
        # structure + mark writes across the step thread (hole
        # un-marking, takeover recovery) and the WindowBuilder thread
        # (inserts, merges, late flush, prune) — armed-path only, the
        # disarmed gate reads a bare truthiness and never takes it.
        self._smear_ring: Dict[int, Dict[int, list]] = {}
        self._smear_lock = threading.Lock()
        self._smear_ring_n = 0
        self._smear_ring_cap = max(65536, 4 * J)
        self._smear_recovered = False
        self._smear_stats = {"deferred_total": 0, "emitted_total": 0,
                             "merged_dups_total": 0, "late_emits_total": 0,
                             "ring_drops_total": 0, "max_spread_s": 0,
                             "max_second_arrivals": 0}
        # reverse col -> node-id map, maintained on node churn instead of
        # being rebuilt from universe.index every step (+ a bool mask of
        # live columns for the vectorized build)
        self._col_node: List[Optional[str]] = [None] * self.planner.N
        self._col_live = np.zeros(self.planner.N, bool)
        # row -> (timer string, phase anchor): @every phases are anchored at
        # first registration and must survive unrelated job rewrites (pause
        # toggles, avg_time updates) — only a changed timer re-anchors.
        self._row_phase: Dict[int, Tuple[str, int]] = {}
        # bulk-load state (set only inside _load_initial and the
        # checkpoint-chain fold); _fold_ro marks the fold's READ-ONLY
        # phase handling — anchors are prefetched current-store values
        # and never written back or deleted (live application already
        # settled them before the save's barrier)
        self._phase_prefetch: Optional[Dict[str, str]] = None
        self._phase_puts: Optional[list] = None
        self._fold_ro = False
        # compiled-spec cache: fleets reuse timer strings heavily; at
        # 1M rows re-parsing "*/5 * * * * *" a thousand times dominates
        # a cold load for nothing
        self._spec_cache: Dict[str, object] = {}

        # ---- workflow DAG plane host state -----------------------------
        # dep-triggered jobs + the reverse dependency index (upstream ->
        # dependents, for re-resolving dep columns on upstream row churn)
        self._dep_jobs: Dict[Tuple[str, str], object] = {}
        self._dep_rdeps: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        # latest completed round per job, mirrored from the dep/ prefix:
        # (success_rel, fail_rel) framework-relative scheduled epochs
        self._dep_latest: Dict[Tuple[str, str], Tuple[int, int]] = {}
        # table rows currently holding dep-triggered jobs
        self._dep_rows: Set[int] = set()
        # pending device scatters, flushed by _flush_device in order:
        # row resets (release/registration anchors) BEFORE epoch folds,
        # so a reacquired row never keeps a previous tenant's epochs
        self._dep_resets: Dict[int, int] = {}
        self._dep_epoch_updates: Dict[int, Tuple[int, int]] = {}
        self._dep_block_updates: Dict[int, bool] = {}
        # max_in_flight gate: gated jobs (mif > 0), their running-exec
        # counts (procs mirror; the order->proc gap is the same bounded
        # over-commit window every capacity gate here has), and which
        # are currently saturated
        self._dep_gated: Dict[Tuple[str, str], int] = {}
        self._dep_inflight: Dict[Tuple[str, str], int] = {}
        self._dep_blocked: Set[Tuple[str, str]] = set()
        # mesh planners don't evaluate deps yet (dep columns reference
        # global rows across shards): refuse dep rows LOUDLY, keep time
        # triggers working
        self._dep_supported = hasattr(self.planner, "set_dep_epochs")
        self._dep_warned: Set[Tuple[str, str]] = set()

        # ---- multi-tenant control plane host state ---------------------
        # quota registry (tenant/ watch mirror), the small-int tenant id
        # space the device columns key on (0 = default, never limited),
        # and the per-row tenant map the fair-share build reads.  Token
        # buckets need planner support (mesh planners shard rows — like
        # deps, they refuse LOUDLY); fair-share + max_running are pure
        # host paths and work on every planner.
        self._tenant_supported = hasattr(self.planner, "set_row_tenants")
        self._tenant_T = int(getattr(self.planner, "T", 64))
        self._tenants: Dict[str, TenantQuota] = {}
        self._tenant_ids: Dict[str, int] = {"": 0}
        self._tid_name: List[str] = [""]
        self._tenant_ids_exhausted = False
        self._tenant_limit_warned = False
        self._row_tenant = np.zeros(J, np.int32)
        self._tenant_row_updates: Dict[int, int] = {}
        # loud per-tenant admission counters, fed from the build stage
        # via a GIL-atomic deque (the build worker must not write the
        # step thread's dicts)
        self._tenant_counters: Dict[str, Dict[str, int]] = {}
        import collections as _collections
        self._tenant_q: "_collections.deque" = _collections.deque()
        # outstanding EXCLUSIVE work per tenant id (order reservations +
        # running procs), the max_running gate's input; _acct_tid
        # freezes each mirror key's tenant breakdown at entry time so
        # the delete decrements exactly what the add incremented
        self._tenant_excl: Dict[int, int] = {}
        self._acct_tid: Dict[str, dict] = {}
        self._agg_excl_avail = float("inf")

        # watch-fed mirrors of the execution-state prefixes (proc registry,
        # outstanding exclusive orders, Alone lifetime locks).  The hot loop
        # must NOT re-list these every second — at planner fire rates that
        # serializes the whole keyspace over TCP per step; deltas arrive by
        # watch and a periodic anti-entropy re-list bounds drift.
        # Mirror values are (node, cost, exclusive) FROZEN at entry time,
        # and per-node counters advance incrementally with the mirrors —
        # reconcile_capacity is O(nodes), not O(outstanding) (r4 measured
        # 548 ms/step of re-iteration at the 1M scale).
        self._procs: Dict[str, Tuple[str, float, bool]] = {}
        self._orders: Dict[str, Tuple[str, float, bool]] = {}
        self._alone_live: Set[str] = set()
        self._excl_cnt: Dict[str, int] = {}    # node -> reserved slots
        self._load_sum: Dict[str, float] = {}  # node -> running cost
        self.mirror_resync_s = 30.0
        self._mirror_resync_at = 0.0
        self._ae_thread: Optional[threading.Thread] = None
        self._ae_result = None
        self._ae_rekick = False
        self._ae_store = None   # lazy clone for background listings

        # checkpoint plane: periodic/operator-triggered saves of the
        # BUILT state (see checkpoint_save), restored at construction
        # when a checkpoint is present — the warm-takeover path.
        # Single-host MESH planners checkpoint too: their device shards
        # host-gather through the planner's _fetch into the same
        # sched_ckpt format, tagged with the mesh topology (a
        # topology-mismatched restore cold-loads loudly, and set_table/
        # set_eligibility re-pin the canonical shardings on install).
        # Refused HERE (not just in the launcher): proxied multi-host
        # planners (PlannerSyncProxy and its workers' op-log replay) and
        # unknown planner subclasses, whose restore would install
        # arrays with invariants this code cannot vouch for.
        from ..ops.planner import TickPlanner as _PlainPlanner
        if checkpoint_dir and type(self.planner) is not _PlainPlanner:
            ok = False
            try:
                from ..parallel.mesh import _ShardedPlannerBase
                ok = (isinstance(self.planner, _ShardedPlannerBase)
                      and not getattr(self.planner, "_multiprocess",
                                      False))
            except Exception:  # noqa: BLE001 — no mesh support installed
                ok = False
            if not ok:
                log.warnf("checkpoint_dir is not supported with %s "
                          "planners yet; disabling scheduler checkpoints",
                          type(self.planner).__name__)
                checkpoint_dir = None
        # sharded stores checkpoint too: the quiescent barrier runs the
        # PR 5 double watch-barrier PER SHARD (one barrier nonce key
        # mined to route to each shard) and the checkpoint is keyed on
        # the per-shard revision VECTOR — the same resume shape the
        # sharded watch/rev-vector machinery already speaks.  A
        # mismatched vector shape at restore cold-loads loudly.
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_interval_s = checkpoint_interval_s
        self._ckpt_requested = False
        # barrier key -> highest mod_rev seen (one key per shard; the
        # plain ckpt_barrier key against an unsharded store)
        self._ckpt_barrier_seen: Dict[str, int] = {}
        self._ckpt_next_at = (clock() + checkpoint_interval_s
                              if checkpoint_dir and checkpoint_interval_s
                              else float("inf"))
        self._ckpt_stats = {"saves_total": 0, "save_errors_total": 0,
                            "last_save_ms": 0.0, "last_rev": 0,
                            "restored": 0, "restore_ms": 0.0,
                            "delta_saves_total": 0,
                            "last_delta_events": 0,
                            "bg_writes_total": 0,
                            "last_serialize_ms": 0.0}
        # double-buffered full saves: the step thread captures a STABLE
        # state copy; this writer thread serializes it while steps
        # continue (the O(state) pickle was the step-thread stall)
        self._ckpt_writer: Optional[threading.Thread] = None
        # delta checkpoints: record the applied watch events (plus the
        # leader's own-publish order accounting, which the delete-only
        # orders watch never echoes) into a buffer; a delta save writes
        # the buffer as one chain element instead of re-serializing the
        # whole built state.  checkpoint_delta=False (conf) or
        # CRONSUN_CKPT_DELTA=off is the rollback: every save is full.
        if checkpoint_delta is None:
            checkpoint_delta = os.environ.get(
                "CRONSUN_CKPT_DELTA", "on").lower() not in ("off", "0")
        self._delta_on = bool(checkpoint_delta)
        self.delta_max_chain = max(1, int(delta_max_chain))
        self.delta_max_bytes = max(1, int(delta_max_bytes))
        self.delta_max_events = max(1, int(delta_max_events))
        # activated at the END of __init__ (after restore/cold load):
        # events recorded from then on are exactly the state since the
        # restored chain tip / the first full save clears them anyway
        self._delta_buf: Optional[list] = None
        self._delta_valid = True
        self._delta_overflowed = False
        # live chain bookkeeping: {nonce, seq, rev, bytes, path} after a
        # full save or a chain restore; None = no base this process can
        # extend (next save is full)
        self._ckpt_chain: Optional[dict] = None

        # async publisher: lanes are extra connections when the store
        # can clone (networked), else the shared store.  The publish
        # rides OFF the step's critical path (r4: 2.1 s of a 4 s window
        # inside the step); backpressure puts it back on the step —
        # visibly — only when the plane can't keep up.
        #
        # Against a SHARDED store the default is one lane PER SHARD
        # with shard-routed chunking (shard_of): a browned-out shard's
        # writes queue on ITS lane only, so the healthy shards' orders
        # of every second land at healthy latency instead of the last
        # second of each window paying ~2·window_s·delay behind the
        # slow shard (the brownout_dispatch drill's old structural
        # bound).  Explicit publish_lanes (or
        # CRONSUN_PUB_SHARD_LANES=off) keeps the round-robin path —
        # the rollback switch.
        shard_of = None
        nsh = getattr(store, "nshards", 1)
        shard_lanes = (publish_lanes <= 0 and nsh > 1
                       and hasattr(store, "clone")
                       and os.environ.get("CRONSUN_PUB_SHARD_LANES",
                                          "on").lower()
                       not in ("off", "0"))
        if shard_lanes:
            lanes = [store.clone() for _ in range(nsh)]
            self._owned_lanes = lanes
            from ..store.sharded import shard_index
            _pfx = getattr(store, "prefix", self.ks.prefix)

            def shard_of(key, _n=nsh, _p=_pfx):
                return shard_index(key, _n, _p)
        else:
            if publish_lanes <= 0:
                import os as _os
                publish_lanes = max(1, min(4, (_os.cpu_count() or 1) - 1))
            if hasattr(store, "clone"):
                lanes = [store.clone() for _ in range(publish_lanes)]
                self._owned_lanes = lanes
            else:
                lanes = [store]
                self._owned_lanes = []
        from .publisher import OrderPublisher, WindowBuilder
        self.publisher = OrderPublisher(lanes, self._advance_hwm,
                                        shard_of=shard_of)
        # in-process stores (tests, demo) publish synchronously: their
        # put_many is microseconds and callers assert store contents
        # right after step(); the networked path keeps the overlap
        self.sync_publish = (not hasattr(store, "clone")
                             if sync_publish is None else sync_publish)
        # device-plan pipelining: the NEXT window's plan is dispatched
        # before the current one publishes; (start_epoch, handle)
        self._pending_plan: Optional[Tuple[int, object]] = None
        # async overflow replans awaiting their gather: (epoch, handle)
        self._pending_replans: List[Tuple[int, object]] = []
        # two-stage pipelined step: the window's gather+build+publish
        # runs on the WindowBuilder worker while the device plans the
        # next window.  Mesh planners keep the serial path — their plan
        # is a synchronized collective every rank must enter from one
        # thread.  ``pipelined=False`` forces the serial path (bench
        # baseline / rollback switch).
        self.pipelined = (hasattr(self.planner, "plan_window_async")
                          if pipelined is None else pipelined)
        self._builder = WindowBuilder(self._build_window)
        # builder -> step hand-backs (thread-safe via GIL deque ops):
        # completed-window accounting (mirror adds, fire counts, stage
        # spans) and overflow-replan requests (the DEVICE dispatch must
        # stay on the step thread)
        import collections
        self._acct_q: "collections.deque" = collections.deque()
        self._replan_reqs: "collections.deque" = collections.deque()
        # device dispatches ride ONE dedicated thread in pipelined mode:
        # plan_window_async mutates carried planner state, so dispatch
        # order must stay total — and on the CPU backend "dispatch"
        # INLINES much of the compute on the calling thread, which would
        # put the device time right back on the step's critical path
        from concurrent.futures import ThreadPoolExecutor
        self._dispatch_pool = ThreadPoolExecutor(
            1, thread_name_prefix="plan-dispatch")
        self._dispatch_ms: "collections.deque" = collections.deque()
        # pipeline overlap accounting: step-thread wall vs builder busy
        self._pl_step_ms = 0.0
        self._pl_offstep_ms = 0.0
        self._warm_thread: Optional[threading.Thread] = None
        self._warmed = False

        self._leader_lease: Optional[int] = None
        # lease watchdog: wall time of the last keepalive CONFIRM,
        # anchored at the SEND instant (the server refreshed the lease
        # somewhere inside the round trip; the send is the conservative
        # bound).  A keepalive whose round trip exceeds lease_ttl/2 —
        # or a confirm older than lease_ttl — means the leader may be
        # dispatching on a lease it has already lost: resign LOUDLY
        # (revoke, stop publishing, re-elect) instead of risking
        # split-brain.
        self._lease_confirmed_at: float = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._next_epoch: Optional[int] = None
        self.max_catchup_s = 120
        self.stats = {"overflow_drops": 0, "overflow_late_fires": 0,
                      "skipped_seconds": 0,
                      "watch_losses": 0, "dispatches_total": 0,
                      "steps_total": 0, "lease_resigns_total": 0,
                      "acct_exchanges_total": 0}
        # herd gauges, tracked where orders are built: the most
        # EXCLUSIVE (per-node) keys any one second published — bounded
        # by active nodes under coalescing, it was one per fire before —
        # and the most exclusive fires those keys carried
        self.max_second_node_keys = 0
        self.max_second_excl_fires = 0
        # operator metrics: recent device-plan latencies (ring) published
        # via the shared leased-snapshot protocol (a dead scheduler's
        # snapshot expires instead of going stale)
        from ..metrics import LatencyRing, MetricsPublisher
        self._tick_ms = LatencyRing()
        self._step_ms = LatencyRing()        # full step() cycle latencies
        self._step_spans: Dict[str, float] = {}   # last step's phase ms
        # per-span latency distributions (p50/p99 per phase, including
        # the builder-side gather/build/submit stages)
        self._span_hist: Dict[str, LatencyRing] = {}
        self.metrics = MetricsPublisher(
            store, self.ks, "sched", self.node_id, self.metrics_snapshot,
            interval_s=5.0, clock=clock)
        # per-tenant admission counters ride a SECOND leased snapshot
        # under component "tenant" ({tenant: {field: n}}), rendered at
        # /v1/metrics as cronsun_tenant_*{tenant=...}; published only
        # once a tenant exists
        self._tenant_metrics = MetricsPublisher(
            store, self.ks, "tenant", self.node_id,
            self.tenant_snapshot, interval_s=5.0, clock=clock)
        # mesh planners publish a SECOND leased snapshot under component
        # "mesh" (per-tick latency ring, per-phase counters, estimated
        # collective bytes) so /v1/metrics renders cronsun_mesh_tick_*
        # beside the sched gauges
        self._mesh_metrics = None
        mesh_snap = getattr(self.planner, "stats_snapshot", None)
        if callable(mesh_snap):
            self._mesh_metrics = MetricsPublisher(
                store, self.ks, "mesh", self.node_id, mesh_snap,
                interval_s=5.0, clock=clock)

        # warm path first: restore a checkpoint (built state + watch
        # delta replay) when one is present; any mismatch falls back to
        # the cold load, LOUDLY — a checkpoint is an optimization,
        # never an alternate source of truth
        restored = False
        if checkpoint_dir:
            restored = self._checkpoint_restore()
        if not restored:
            self._open_watches()
            self._load_initial()
        # start recording the delta stream only once the slate is known
        # (a restore's chain fold must not re-enter the buffer); the
        # watch tail replayed after a warm restore drains through
        # step() and IS recorded — it is part of the next delta
        if self.checkpoint_dir and self._delta_on:
            self._delta_buf = []

    @property
    def _alone_pfx(self) -> str:
        return self.ks.alone_lock

    def _open_watches(self, start_rev: int = 0):
        """Open every watch; with ``start_rev`` (checkpoint restore),
        resume each stream from that revision so the deltas since the
        checkpointed state replay instead of being re-listed — raises
        CompactedError/WatchLost when the store's bounded history no
        longer reaches back that far (the caller cold-loads).  A partial
        failure closes the watches already opened."""
        opened = []

        def w(prefix, events=""):
            wx = self.store.watch(prefix, start_rev=start_rev,
                                  events=events)
            opened.append(wx)
            return wx
        try:
            self._w_jobs = w(self.ks.cmd)
            self._w_groups = w(self.ks.group)
            self._w_nodes = w(self.ks.node)
            self._w_procs = w(self.ks.proc)
            # delete-only: the leader WRITES this prefix by the tens of
            # thousands per window — watching its own puts meant every
            # publish came straight back as watch pushes to serialize,
            # ship and re-parse (a measured majority of the r4 publish
            # span).  Own publishes are mirrored locally at submit time;
            # consumption/expiry arrives as DELETEs; other-leader writes
            # are covered by anti-entropy.
            self._w_orders = w(self.ks.dispatch, events="delete")
            self._w_alone = w(self._alone_pfx)
            # workflow DAG completion events (agents write one key per
            # job round; the fold into the success-epoch vectors is the
            # dep-trigger edge signal)
            self._w_deps = w(self.ks.dep)
            # tenant quota records (the web/ctl tier writes them; job
            # index markers under the same prefix are ignored here)
            self._w_tenants = w(self.ks.tenant)
            # checkpoint-plane control keys: operator save requests and
            # the save barrier nonces
            self._w_ckpt = w(self.ks.ckpt)
            # partitioned plane: foreign partitions' leased demand
            # summaries (shared node capacity reconciliation)
            self._w_acct = (w(self.ks.sched_acct)
                            if self.partitions > 1 else None)
        except BaseException:
            for wx in opened:
                try:
                    wx.close()
                except Exception:  # noqa: BLE001 — already dead
                    pass
            raise

    def _all_watches(self):
        base = (self._w_jobs, self._w_groups, self._w_nodes,
                self._w_procs, self._w_orders, self._w_alone,
                self._w_deps, self._w_tenants, self._w_ckpt)
        return base + (self._w_acct,) if self._w_acct is not None \
            else base

    # ---- partitioned scheduler plane ------------------------------------

    def owns_job(self, job_id: str) -> bool:
        """True when this partition owns the job's token slice (always
        True unpartitioned)."""
        return self._owns is None or self._owns(job_id)

    def _apply_acct_ev(self, typ: str, key: str, value: str):
        """Fold one foreign partition's demand-summary event into the
        acct mirror (the flat per-node sums recompute lazily at the
        next reconcile).  Own-key echoes are skipped — own demand is
        already exact in the local counters."""
        if key == self.ks.sched_acct_key(self.partition):
            return
        if typ == DELETE:
            if self._part_foreign.pop(key, None) is not None:
                self._foreign_dirty = True
            return
        from .partition import decode_demand
        demand = decode_demand(value)
        if demand is None:
            log.warnf("malformed partition demand summary at %s; "
                      "ignored", key)
            return
        self._part_foreign[key] = demand
        self._foreign_dirty = True

    def _fold_foreign_demand(self):
        """Merge the per-partition demand mirrors into the flat
        {node: excl}/{node: load} sums reconcile_capacity subtracts —
        O(partitions x active nodes), only when a summary changed."""
        if not self._foreign_dirty:
            return
        fex: Dict[str, int] = {}
        fld: Dict[str, float] = {}
        for demand in self._part_foreign.values():
            for node, (e, l) in demand.items():
                if e:
                    fex[node] = fex.get(node, 0) + e
                if l:
                    fld[node] = fld.get(node, 0.0) + l
        self._foreign_excl = fex
        self._foreign_load = fld
        self._foreign_dirty = False

    def _publish_acct(self):
        """Leased per-node demand summary publish (partition leaders,
        every ``acct_exchange_s``): the summary is this partition's
        outstanding exclusive slots + running load per node — the
        exact counters reconcile_capacity trusts locally — so every
        other partition's capacity view converges to the fleet-wide
        truth within one exchange period.  The lease (3x the period)
        ages a dead partition's demand out instead of pinning its
        capacity claim forever."""
        now = self.clock()
        if now < self._acct_next:
            return
        self._acct_next = now + self.acct_exchange_s
        from .partition import encode_demand
        value = encode_demand(self._excl_cnt, self._load_sum)
        try:
            if self._acct_lease is None or \
                    not self.store.keepalive(self._acct_lease):
                self._acct_lease = self.store.grant(
                    max(10.0, 3.0 * self.acct_exchange_s))
            self.store.put(self.ks.sched_acct_key(self.partition),
                           value, lease=self._acct_lease)
            self.stats["acct_exchanges_total"] += 1
        except Exception as e:  # noqa: BLE001 — a missed exchange is
            # bounded staleness (over-commit absorbed by the agents'
            # Parallels gate), never a step failure
            self._acct_lease = None
            log.warnf("partition demand exchange failed: %s", e)

    # ---- bootstrap (reference loadJobs, node/node.go:121-141) ------------

    def _load_initial(self, groups=None, nodes=None, jobs=None):
        """Apply the store's current contents; prefetched KV lists avoid
        re-listing when the caller (resync) already has them.

        Bulk-load fast path: @every phase anchors are prefetched in ONE
        prefix listing and missing ones written back in ONE put_many —
        the per-rule put_if_absent+get pair would cost 2 RPCs x rules at
        boot (minutes of round trips at 1M rows).  The batched
        write-back is last-write-wins instead of create-if-absent; two
        cold-loading standbys racing it can shift a fresh anchor by the
        seconds between their boots, which only matters for @every rules
        never anchored before (existing anchors are honored)."""
        # tenant quotas first (jobs reference tenant ids; ids allocate
        # on demand either way, but quota limits should be armed before
        # the first window plans).  The same listing doubles as the
        # resync liveness diff: quotas deleted during a lost-watch gap
        # are dropped here.
        # partitioned plane: current foreign demand summaries (the acct
        # watch only carries changes from here on)
        if self.partitions > 1:
            for kv in _list_prefix(self.store, self.ks.sched_acct):
                self._apply_acct_ev(PUT, kv.key, kv.value)
        live_quotas = set()
        for kv in _list_prefix(self.store, self.ks.tenant):
            rest = kv.key[len(self.ks.tenant):]
            if rest.endswith("/quota"):
                live_quotas.add(rest[:-len("/quota")])
                self._apply_ev("tenants", PUT, kv.key, kv.value)
        for name in [n for n in self._tenants if n not in live_quotas]:
            self._apply_ev("tenants", DELETE,
                           self.ks.tenant_quota_key(name), "")
        for kv in (groups if groups is not None
                   else _list_prefix(self.store, self.ks.group)):
            self._apply_group(kv.value)
        # nodes are batched: _node_up issues one device capacity scatter
        # per node, which at 10k nodes is 10k dispatches (each paying the
        # host<->device round trip on a tunneled chip) — here it is ONE
        fresh = []
        for kv in (nodes if nodes is not None
                   else _list_prefix(self.store, self.ks.node)):
            node_id = kv.key[len(self.ks.node):]
            if node_id in self.universe.index:
                continue
            self.builder.node_added(node_id)
            col = self.universe.index[node_id]
            self._col_node[col] = node_id
            self._col_live[col] = True
            fresh.append(node_id)
        if fresh:
            # group masks re-derived ONCE per affected group (not once
            # per member node — a 10k-node group must not be re-packed
            # 10k times at boot)
            fresh_set = set(fresh)
            for g in self.groups.values():
                if not fresh_set.isdisjoint(g.node_ids):
                    self.builder.set_group(g.id, g.node_ids)
            cols = np.asarray(list(self.universe.index.values()), np.int32)
            caps = np.asarray(
                [self.node_caps.get(n, self.default_node_cap)
                 for n in self.universe.index], np.int64)
            cols, caps = self._pad_pow2(cols, caps)
            self.planner.set_node_capacity(cols, caps)
        # dep completion events BEFORE jobs: _apply_job seeds each fresh
        # row's success/fail epochs from this mirror, so a cold-loaded
        # scheduler's dep plane reflects rounds completed while it was
        # down (the fold is a monotone max — re-listing is idempotent)
        for kv in _list_prefix(self.store, self.ks.dep):
            self._apply_ev("deps", PUT, kv.key, kv.value)
        self._phase_prefetch = {
            kv.key: kv.value
            for kv in _list_prefix(self.store, self.ks.phase)}
        self._phase_puts = []
        try:
            for kv in (jobs if jobs is not None
                       else _list_prefix(self.store, self.ks.cmd)):
                self._apply_job(kv.key, kv.value)
        finally:
            for i in range(0, len(self._phase_puts), 50_000):
                self.store.put_many(self._phase_puts[i:i + 50_000])
            self._phase_prefetch = None
            self._phase_puts = None
        self._mirror_antientropy()
        self._flush_device()

    # ---- leadership ------------------------------------------------------

    def try_lead(self) -> bool:
        if self._leader_lease is not None:
            t0 = time.monotonic()
            ok = self.store.keepalive(self._leader_lease)
            rtt = time.monotonic() - t0
            if ok:
                # keepalive watchdog: the server refreshed the lease at
                # some instant inside [t0, t0+rtt] — when the round
                # trip exceeds lease_ttl/2 the refresh instant is too
                # uncertain to dispatch on (an injected RPC delay, a
                # pegged host, a stalled link all look identical from
                # here), and a confirm older than a full lease_ttl
                # means the lease may already be expired with a new
                # leader elected.  In both cases: resign LOUDLY and
                # re-elect from scratch instead of risking split-brain.
                stale = self._lease_confirmed_at and \
                    t0 - self._lease_confirmed_at > self.lease_ttl
                if rtt > self.lease_ttl / 2 or stale:
                    self._resign_lease(
                        f"keepalive round trip {rtt * 1e3:.0f} ms vs "
                        f"lease_ttl {self.lease_ttl:.1f}s"
                        if rtt > self.lease_ttl / 2 else
                        f"last confirm {t0 - self._lease_confirmed_at:.1f}"
                        f"s ago (> lease_ttl)")
                else:
                    self._lease_confirmed_at = t0
                    return True
            else:
                self._leader_lease = None
        # anchor the election's confirm BEFORE grant(): the lease's TTL
        # countdown starts server-side when grant is processed, so on a
        # slow store the win can arrive a full election round trip
        # later — anchoring at the win would overstate freshness by
        # exactly the delay regime the watchdog exists for
        t_el = time.monotonic()
        lease = self.store.grant(self.lease_ttl)
        try:
            won = self.store.put_if_absent(self._leader_key,
                                           self.node_id, lease=lease)
        except KeyError:
            # the fresh lease expired before the put landed (pegged
            # host, link stall longer than lease_ttl): not leading this
            # step; the next attempt grants anew
            return False
        if won:
            # the election leg gets the SAME uncertainty bound as the
            # keepalive: if the grant+put round trip exceeded
            # lease_ttl/2, the lease (whose TTL countdown started at
            # the grant) may already be expired with another leader
            # elected by the time this reply arrived — dispatching on
            # it is the split-brain the watchdog exists to prevent
            if time.monotonic() - t_el > self.lease_ttl / 2:
                self.stats["lease_resigns_total"] += 1
                log.errorf(
                    "scheduler %s won election but the round trip took "
                    "%.0f ms (> lease_ttl/2); discarding the win",
                    self.node_id, (time.monotonic() - t_el) * 1e3)
                try:
                    self.store.revoke(lease)
                except Exception:  # noqa: BLE001 — TTL is the backstop
                    pass
                return False
            self._leader_lease = lease
            self._lease_confirmed_at = t_el
            return True
        self.store.revoke(lease)
        return False

    def _resign_lease(self, why: str):
        """Stop leading NOW: drop the lease reference (every dispatch
        path gates on is_leader), log, count, and best-effort revoke so
        the leader key frees for re-election immediately instead of at
        TTL expiry.  The next step's try_lead re-elects from scratch —
        possibly winning again, which is fine: what matters is never
        dispatching across the uncertainty window."""
        lease, self._leader_lease = self._leader_lease, None
        self._lease_confirmed_at = 0.0
        self.stats["lease_resigns_total"] += 1
        log.errorf("scheduler %s resigning leadership: %s (stopped "
                   "publishing; will re-elect)", self.node_id, why)
        if lease is not None:
            try:
                self.store.revoke(lease)
            except Exception as e:  # noqa: BLE001 — the TTL is the
                # backstop; a failed revoke only delays re-election
                log.warnf("lease revoke during resign failed: %s", e)

    @property
    def is_leader(self) -> bool:
        return self._leader_lease is not None

    # ---- watch delta handlers -------------------------------------------

    def _apply_job(self, key: str, value: str):
        rest = key[len(self.ks.cmd):]
        if "/" not in rest:
            return
        group, job_id = rest.split("/", 1)
        if self._owns is not None and not self._owns(job_id):
            return      # another partition's token slice
        try:
            job = Job.from_json(value)
        except (json.JSONDecodeError, TypeError):
            return
        job.group, job.id = group, job_id
        old_rules = self.rows.rules_of(group, job_id)
        new_rules = set()
        prev_reg = self.jobs.get((group, job_id))
        self.jobs[(group, job_id)] = job
        jk = (group, job_id)
        # herd-smear arm counter: registry-level (rows churn through
        # _drop_rule which deliberately leaves stale cells behind flags)
        self._jitter_jobs += ((1 if getattr(job, "jitter", 0) > 0 else 0)
                              - (1 if prev_reg is not None
                                 and getattr(prev_reg, "jitter", 0) > 0
                                 else 0))
        if getattr(job, "jitter", 0) > self._max_jitter_seen:
            self._max_jitter_seen = int(job.jitter)
        tid = self._tenant_id(job.tenant) if job.tenant else 0
        dep_spec = self._dep_spec_apply(jk, job)
        dep_row_dict = None
        if dep_spec is not None:
            dep_row_dict = make_dep_row(
                self._dep_upstream_cols(group, dep_spec),
                POLICY_BY_NAME.get(dep_spec.misfire, 0),
                paused=job.pause, tenant=tid)
        for rule in job.rules:
            if dep_spec is not None:
                # dep-triggered row: no cron parse, no phase anchor —
                # the trigger is the upstream success-epoch test
                new_rules.add(rule.id)
                fresh = (group, job_id, rule.id) not in self.rows.by_cmd
                row = self.rows.acquire(group, job_id, rule.id)
                if fresh or row not in self._dep_rows:
                    # registration anchor: only upstream rounds NEWER
                    # than now fire a just-created chain.  (The row's
                    # OWN epochs — its downstream signal — are seeded
                    # by the uniform end-of-apply reseed below.)
                    self._dep_resets[row] = \
                        int(self.clock()) - FRAMEWORK_EPOCH
                    self._dep_rows.add(row)
                self._row_phase.pop(row, None)
                self._table_updates[row] = dep_row_dict
                if self._row_tenant[row] != tid:
                    self._row_tenant[row] = tid
                    self._tenant_row_updates[row] = tid
                self.builder.set_job(row, rule.nids, rule.gids,
                                     rule.exclude_nids)
                self._meta_updates[row] = (
                    job.exclusive,
                    job.avg_time if job.avg_time > 0 else 1.0)
                self._set_row_dispatch(row, job, rule, group, job_id)
                continue
            spec = self._spec_cache.get(rule.timer)
            if spec is None:
                try:
                    spec = parse(rule.timer)
                except ParseError:
                    continue
                if len(self._spec_cache) > 65536:
                    self._spec_cache.clear()
                self._spec_cache[rule.timer] = spec
            new_rules.add(rule.id)
            row = self.rows.acquire(group, job_id, rule.id)
            self._dep_rows.discard(row)   # dep -> cron transition
            prev = self._row_phase.get(row)
            if prev is not None and prev[0] == rule.timer:
                phase_epoch = prev[1]       # unchanged rule keeps its phase
            else:
                phase_epoch = self._phase_anchor(group, job_id, rule.id,
                                                 rule.timer)
                self._row_phase[row] = (rule.timer, phase_epoch)
            self._table_updates[row] = make_row(
                spec, phase_epoch_s=phase_epoch, paused=job.pause,
                tenant=tid, jitter=getattr(job, "jitter", 0))
            if self._row_tenant[row] != tid:
                self._row_tenant[row] = tid
                self._tenant_row_updates[row] = tid
            self.builder.set_job(row, rule.nids, rule.gids, rule.exclude_nids)
            self._meta_updates[row] = (job.exclusive,
                                       job.avg_time if job.avg_time > 0 else 1.0)
            self._set_row_dispatch(row, job, rule, group, job_id)
        for rule_id in old_rules - new_rules:
            self._drop_rule(group, job_id, rule_id)
        # upstream row set may have changed: re-resolve dependents' dep
        # columns AND re-seed this job's (possibly fresh) rows with its
        # latest completion epochs — rule churn must not lose a round
        # (a dict miss for the overwhelming dep-less majority)
        if self._dep_rdeps.get(jk):
            self._dep_refresh_dependents(group, job_id)
            self._dep_seed_job_rows(group, job_id)

    def _set_row_dispatch(self, row: int, job: Job, rule, group: str,
                          job_id: str):
        """Per-row dispatch cache install (tuple + parallel arrays);
        flags LAST so a concurrently building worker never sees a
        half-set row."""
        if _WIRE_SAFE(rule.id):
            # default ids are next_id() hex: skip the json encoder
            # (measured at 1M-job load scale)
            payload = '{"rule":"%s","kind":%d}' % (rule.id, job.kind)
        else:
            payload = json.dumps({"rule": rule.id, "kind": job.kind},
                                 separators=(",", ":"))
        suffix = f"/{group}/{job_id}"
        bentry = json.dumps(f"{group}/{job_id}")
        self._row_dispatch[row] = (
            job.exclusive, payload,
            group, job_id, job.kind,
            suffix,                 # precomputed key tail: the
                                    # order-build loop is concat-only
            # pre-escaped bundle entry: coalesced (node, second)
            # values are "[" + ",".join(entries) + "]" at build time
            bentry)
        self._rd_payload[row] = payload
        self._rd_suffix[row] = suffix
        self._rd_bentry[row] = bentry
        self._rd_job[row] = (group, job_id)
        self._rd_tbase[row] = np.uint64(
            self._trace.fnv_partial(job_id + "|"))
        self._rd_sbase[row] = np.uint64(
            self._trace.fnv_partial(group + "/" + job_id + "|"))
        self._rd_tflag[row] = bool(getattr(job, "trace", False))
        self._rd_jitter[row] = int(getattr(job, "jitter", 0) or 0)
        self._rd_flags[row] = (1 | (2 if job.exclusive else 0)
                               | (4 if job.kind == KIND_ALONE else 0))

    # ---- multi-tenant control plane -------------------------------------

    def _tenant_id(self, name: str) -> int:
        """Small-int id for a tenant name (allocated on first sight; 0
        is the default tenant).  An exhausted id space maps overflow
        tenants to 0 — UNLIMITED, never silently throttled — and
        complains once."""
        tid = self._tenant_ids.get(name)
        if tid is not None:
            return tid
        if len(self._tid_name) >= self._tenant_T:
            if not self._tenant_ids_exhausted:
                self._tenant_ids_exhausted = True
                log.errorf(
                    "tenant id space exhausted (%d columns); tenant %r "
                    "and later arrivals share the default UNLIMITED "
                    "column — raise the planner's tenant_capacity",
                    self._tenant_T, name)
            self._tenant_ids[name] = 0
            return 0
        tid = len(self._tid_name)
        self._tid_name.append(name)
        self._tenant_ids[name] = tid
        return tid

    def _tname(self, tid: int) -> str:
        return self._tid_name[tid] if 0 <= tid < len(self._tid_name) \
            else f"tid{tid}"

    def _apply_tenant_quota(self, name: str, value: str):
        try:
            q = TenantQuota.from_json(value)
        except (json.JSONDecodeError, TypeError, ValueError):
            return
        q.tenant = name
        try:
            q.validate()
        except Exception as e:  # noqa: BLE001 — operator-written record
            log.warnf("tenant %r quota record invalid (%s); ignored",
                      name, e)
            return
        prev = self._tenants.get(name)
        self._tenants[name] = q
        tid = self._tenant_id(name)
        if prev is not None and \
                (prev.rate, prev.burst, prev.weight) == \
                (q.rate, q.burst, q.weight):
            # the DEVICE-relevant fields are unchanged (resync
            # re-list, duplicate delivery, delta replay, or an edit to
            # the host-only max_jobs/max_running): do NOT touch the
            # planner — set_tenant_quota resets the bucket to FULL,
            # and neither a watch flap nor a max_jobs bump may hand a
            # throttled tenant a free burst
            return
        if not tid and name:
            # the id space is exhausted and this tenant shares the
            # default UNLIMITED column: the scheduler-side planes
            # (fire rate, fair share, max_running) CANNOT enforce this
            # quota — say so per quota, not just once at exhaustion
            # (max_jobs still applies: the web tier reads the record
            # directly)
            log.errorf(
                "quota for tenant %r cannot be enforced by the "
                "scheduler: tenant id space exhausted (%d columns) — "
                "raise the planner's tenant_capacity (max_jobs still "
                "applies at the web tier)", name, self._tenant_T)
            return
        if q.limited and not self._tenant_supported:
            if not self._tenant_limit_warned:
                self._tenant_limit_warned = True
                log.errorf(
                    "tenant %r has a fire-rate quota but planner %s "
                    "does not support token-bucket admission (mesh "
                    "planners shard rows) — rate limits will NOT be "
                    "enforced; fair-share and max_running still apply",
                    name, type(self.planner).__name__)
            return
        if self._tenant_supported and tid:
            self.planner.set_tenant_quota(
                tid, q.rate if q.limited else 0.0, q.burst, q.weight)
            # ANY quota record arms the admission pass: even a weight-
            # only quota buys fair share under capacity scarcity.
            # Tables with no quota at all keep the exact pre-tenancy
            # program (the bit-identity pin).
            if not self.planner.tenants_enabled:
                self.planner.set_tenants_enabled(True)

    def _drop_tenant_quota(self, name: str):
        if self._tenants.pop(name, None) is None:
            return
        tid = self._tenant_ids.get(name, 0)
        if tid and self._tenant_supported:
            self.planner.clear_tenant_quota(tid)

    def _drain_tenant_q(self):
        """Fold build-stage admission/fair-share refusal counts into the
        per-tenant counters (STEP thread: single writer)."""
        q = self._tenant_q
        while q:
            item = q.popleft()
            if item[0] == "adm":
                _tag, thr, shed = item
                for tid in np.flatnonzero(thr):
                    c = self._tenant_counter(self._tname(int(tid)))
                    c["throttled_fires"] += int(thr[tid])
                    c["shed_fires"] += int(shed[tid])
            else:
                _tag, counts = item
                for tid in np.flatnonzero(counts):
                    c = self._tenant_counter(self._tname(int(tid)))
                    n = int(counts[tid])
                    c["throttled_fires"] += n
                    c["shed_fires"] += n
                    c["fair_shed_fires"] += n

    def _tenant_counter(self, name: str) -> Dict[str, int]:
        c = self._tenant_counters.get(name)
        if c is None:
            c = self._tenant_counters[name] = {
                "throttled_fires": 0, "shed_fires": 0,
                "fair_shed_fires": 0}
        return c

    def _fair_filter(self, rows: np.ndarray, xi: np.ndarray,
                     cols: np.ndarray,
                     pending: Optional[Dict[int, int]] = None):
        """max_running clamp over one second's EXCLUSIVE fires
        (vectorized; runs inside the order build, possibly on the
        pipeline worker): tenants with an exec-concurrency quota clamp
        to their remaining headroom against outstanding work (order
        reservations + running procs — host mirror state the device
        can't see) PLUS ``pending`` — admissions from earlier seconds
        of the SAME window build, whose accounting only lands after
        the window completes (without it a window_s-second build would
        admit max_running fires per second, not per window).  Within a
        tenant the FIRST fires in plan order survive; dropped fires
        are shed loudly, and the device-side capacity reservation they
        took self-heals at the next reconcile.  (Capacity fair share —
        weighted max-min when aggregate demand exceeds the fleet's
        slots — runs ON DEVICE in the admission pass, before
        placement: ops/tenancy.py.)"""
        from ..ops.tenancy import select_fair
        T = self._tenant_T
        BIG = np.int64(1) << 40
        caps = None
        capped: List[int] = []
        # list(): this runs on the build worker while the step thread
        # may insert/pop quota records — snapshot, don't iterate live
        for name, quota in list(self._tenants.items()):
            if not quota.max_running:
                continue
            tid = self._tenant_ids.get(name, 0)
            if not tid:
                continue
            if caps is None:
                caps = np.full(T, BIG, np.int64)
            capped.append(tid)
            caps[tid] = max(0, quota.max_running
                            - self._tenant_excl.get(tid, 0)
                            - (pending or {}).get(tid, 0))
        if caps is None:
            return xi, cols
        tids = self._row_tenant[rows[xi]]
        keep = select_fair(tids, caps)
        if pending is not None:
            kept_counts = np.bincount(tids[keep], minlength=T)
            for tid in capped:
                if kept_counts[tid]:
                    pending[tid] = pending.get(tid, 0) + \
                        int(kept_counts[tid])
        if keep.all():
            return xi, cols
        self._tenant_q.append(
            ("fair", np.bincount(tids[~keep], minlength=T)))
        return xi[keep], cols[keep]

    def tenant_snapshot(self) -> dict:
        """{tenant: {field: number}} — the leased "tenant" component
        snapshot /v1/metrics renders as cronsun_tenant_*{tenant=}."""
        out: Dict[str, dict] = {}
        for name, c in self._tenant_counters.items():
            out[name or "default"] = dict(c)
        for name, q in self._tenants.items():
            ent = out.setdefault(name or "default", {})
            ent["rate_quota"] = q.rate
            ent["max_running_quota"] = q.max_running
            tid = self._tenant_ids.get(name, 0)
            ent["running_excl"] = self._tenant_excl.get(tid, 0)
        return out

    def _rebuild_tenant_excl(self, order_tids: Optional[dict] = None):
        """Ground-truth rebuild of the per-tenant exclusive-work
        counters after a mirror install: proc keys derive from the job
        registry; order keys take the listing's parsed breakdown
        (``order_tids``, built by _build_mirrors from the bundle
        values — covering foreign leaders' orders too), falling back
        to the frozen at-entry breakdown (checkpoint restore)."""
        acct: Dict[str, dict] = {}
        excl: Dict[int, int] = {}
        old = self._acct_tid
        for key, (_n, _c, ex) in self._procs.items():
            d = old.get(key)
            if d is None and ex and self._tenants:
                t = self._parse_proc(key)
                job = self.jobs.get((t[1], t[2])) if t else None
                tid = self._tenant_ids.get(job.tenant, 0) \
                    if job and job.tenant else 0
                d = {tid: 1} if tid else None
            if d:
                acct[key] = d
                for tid, n in d.items():
                    excl[tid] = excl.get(tid, 0) + n
        for key in self._orders:
            d = (order_tids or {}).get(key) or old.get(key)
            if d:
                acct[key] = d
                for tid, n in d.items():
                    excl[tid] = excl.get(tid, 0) + n
        self._acct_tid = acct
        self._tenant_excl = excl

    # ---- workflow DAG plane ---------------------------------------------

    def _dep_spec_apply(self, jk: Tuple[str, str], job: Job):
        """Maintain the dep-job registry + reverse index for one applied
        job; returns the effective DepSpec (None = time-triggered, or
        deps unsupported on this planner)."""
        old = self._dep_jobs.get(jk)
        new = job.deps if (job.deps is not None
                           and getattr(job.deps, "on", None)) else None
        if new is not None and not self._dep_supported:
            if jk not in self._dep_warned:
                self._dep_warned.add(jk)
                log.errorf(
                    "job %s/%s has a deps spec but planner %s does not "
                    "support dep triggers (mesh planners shard rows "
                    "across devices) — the job will NOT fire",
                    jk[0], jk[1], type(self.planner).__name__)
            new = None
        if new is not None and self._owns is not None:
            # cross-partition dep edges: an upstream in another token
            # slice has no rows in THIS partition's table, so its
            # completion epochs have nowhere to scatter — the same
            # shape as the mesh planners' dep refusal (a replicated
            # success-epoch exchange / co-sharded dep layout is the
            # named remainder).  Refuse LOUDLY: the dependent holds.
            foreign = [u for u in new.on if not self._owns(u)]
            if foreign:
                if jk not in self._dep_warned:
                    self._dep_warned.add(jk)
                    log.errorf(
                        "job %s/%s depends on %s owned by other "
                        "scheduler partition(s) — cross-partition dep "
                        "edges are not supported (dep columns "
                        "reference this partition's rows); the job "
                        "will NOT fire until the chain co-locates",
                        jk[0], jk[1], foreign)
                new = None
        if old is None and new is None:
            return None
        group = jk[0]
        if old is not None:
            for u in old.on:
                s = self._dep_rdeps.get((group, u))
                if s:
                    s.discard(jk)
                    if not s:
                        del self._dep_rdeps[(group, u)]
        if new is not None:
            self._dep_jobs[jk] = new
            for u in new.on:
                fresh_edge = not self._dep_rdeps.get((group, u))
                self._dep_rdeps.setdefault((group, u), set()).add(jk)
                if fresh_edge:
                    # the upstream's completion scatters were skipped
                    # while nothing depended on it: seed its rows from
                    # the mirror now (monotone — idempotent)
                    self._dep_seed_job_rows(group, u)
            if new.max_in_flight > 0:
                newly_gated = jk not in self._dep_gated
                self._dep_gated[jk] = new.max_in_flight
                if newly_gated:
                    # the incremental counter only tracks gated jobs:
                    # recount this one from the procs mirror now (rare
                    # operator action; O(procs) once)
                    n = 0
                    for k in self._procs:
                        t = self._parse_proc(k)
                        if t and (t[1], t[2]) == jk:
                            n += 1
                    if n:
                        self._dep_inflight[jk] = n
                    else:
                        self._dep_inflight.pop(jk, None)
            else:
                self._dep_gated.pop(jk, None)
                self._dep_inflight.pop(jk, None)
                self._dep_blocked.discard(jk)
            if not self.planner.dep_enabled:
                self.planner.set_dep_enabled(True)
        else:
            self._dep_jobs.pop(jk, None)
            self._dep_gated.pop(jk, None)
            self._dep_inflight.pop(jk, None)
            self._dep_blocked.discard(jk)
        return new

    def _dep_seed_job_rows(self, group: str, job_id: str):
        """Queue the job's latest completion epochs onto every row it
        holds (fresh rows after rule churn, or an upstream gaining its
        first dependent).  Monotone device fold — re-seeding is
        idempotent."""
        if not self._dep_supported:
            return
        latest = self._dep_latest.get((group, job_id))
        if latest is None:
            return
        by_cmd = self.rows.by_cmd
        for rid in self.rows.by_job.get((group, job_id), ()):
            row = by_cmd.get((group, job_id, rid))
            if row is not None:
                self._dep_epoch_updates[row] = latest

    def _dep_upstream_cols(self, group: str, spec) -> List[int]:
        """Upstream job ids -> table-row anchors.  A job with several
        rules holds several rows, all carrying the same success epochs
        (completion events scatter to every row of the job) — the
        anchor is the smallest.  Missing/row-less upstreams resolve to
        DEP_BROKEN: the dependent HOLDS (never fires dep-less) until
        the upstream (re)appears and re-resolution runs."""
        by_cmd = self.rows.by_cmd
        cols = []
        for u in spec.on:
            rids = self.rows.by_job.get((group, u))
            if not rids:
                cols.append(DEP_BROKEN)
                continue
            cols.append(min(by_cmd[(group, u, rid)] for rid in rids))
        return cols

    def _dep_refresh_dependents(self, group: str, job_id: str):
        """An upstream's row set changed (applied/dropped): rebuild every
        dependent's dep-column block."""
        for dk in list(self._dep_rdeps.get((group, job_id), ())):
            spec = self._dep_jobs.get(dk)
            job = self.jobs.get(dk)
            if spec is None or job is None:
                continue
            row_dict = make_dep_row(
                self._dep_upstream_cols(dk[0], spec),
                POLICY_BY_NAME.get(spec.misfire, 0), paused=job.pause)
            by_cmd = self.rows.by_cmd
            for rid in self.rows.rules_of(dk[0], dk[1]):
                row = by_cmd.get((dk[0], dk[1], rid))
                if row is not None:
                    self._table_updates[row] = row_dict

    def _dep_refresh_blocks(self):
        """Recompute the max_in_flight saturation gate and queue device
        scatters for rows whose blocked state flipped.  O(gated jobs)
        per flush."""
        if not self._dep_gated or not self._dep_supported:
            return
        by_cmd = self.rows.by_cmd
        for jk, mif in self._dep_gated.items():
            blocked = self._dep_inflight.get(jk, 0) >= mif
            if blocked == (jk in self._dep_blocked):
                continue
            if blocked:
                self._dep_blocked.add(jk)
            else:
                self._dep_blocked.discard(jk)
            for rid in self.rows.rules_of(jk[0], jk[1]):
                row = by_cmd.get((jk[0], jk[1], rid))
                if row is not None:
                    self._dep_block_updates[row] = blocked

    def _phase_anchor(self, group: str, job_id: str, rule_id: str,
                      timer: str) -> int:
        """First-registration anchor for a rule's @every phase, persisted so
        it survives leader failover (an in-memory anchor would re-anchor
        every @every rule to the new leader's start time, delaying the next
        fire by up to a full period).  A changed timer re-anchors."""
        key = self.ks.phase_key(group, job_id, rule_id)
        now = int(self.clock())
        if self._phase_prefetch is not None:
            # bulk-load path: one prefix prefetch + one batched
            # write-back instead of 2 RPCs per rule (see _load_initial)
            val = self._phase_prefetch.get(key)
            if val is not None:
                t, _, e = val.rpartition("|")
                if t == timer:
                    try:
                        return int(e)
                    except ValueError:
                        pass
            fresh = f"{timer}|{now}"
            self._phase_prefetch[key] = fresh
            self._phase_puts.append((key, fresh))
            return now
        self.store.put_if_absent(key, f"{timer}|{now}")
        kv = self.store.get(key)
        if kv is not None:
            t, _, e = kv.value.rpartition("|")
            if t == timer:
                try:
                    return int(e)
                except ValueError:
                    pass
        self.store.put(key, f"{timer}|{now}")   # timer changed: re-anchor
        return now

    def _drop_rule(self, group: str, job_id: str, rule_id: str):
        row = self.rows.release_rule(group, job_id, rule_id)
        if row is not None:
            if self._dep_supported:
                # released rows hand a clean dep slate to the next
                # tenant: epochs back to NEVER, anchor 0; pending
                # scatters for the row are superseded by the reset
                self._dep_rows.discard(row)
                self._dep_epoch_updates.pop(row, None)
                self._dep_block_updates.pop(row, None)
                self._dep_resets[row] = 0
            # invalidate the flags ONLY — the object cells keep their
            # stale values on purpose: the build worker reads flags and
            # the field lists at different instants, and a None-ed cell
            # could tear a concurrent build (valid flag, None payload).
            # Stale values are harmless — a fire that read the flag
            # before this clear builds the dropped row's LAST order,
            # exactly what the atomic-tuple loop produced, and agents
            # re-fetch the job (gone -> skipped).  The cells are
            # overwritten when the row is reacquired (_apply_job writes
            # fields first, flags last).
            self._rd_flags[row] = 0
            if self._row_tenant[row]:
                self._row_tenant[row] = 0
                self._tenant_row_updates[row] = 0
            self._table_updates[row] = dict(_INACTIVE_ROW)
            self.builder.del_job(row)
            self._meta_updates.pop(row, None)
            self._row_phase.pop(row, None)
            self._row_dispatch.pop(row, None)
            if not self._fold_ro:
                # a checkpoint-chain fold must not touch stored phase
                # anchors: live application already deleted this one —
                # and possibly re-created it for a later event in the
                # chain, which this delete would destroy fleet-wide
                self.store.delete(self.ks.phase_key(group, job_id,
                                                    rule_id))

    def _drop_job(self, group: str, job_id: str):
        for rule_id in self.rows.rules_of(group, job_id):
            self._drop_rule(group, job_id, rule_id)
        dropped = self.jobs.pop((group, job_id), None)
        if dropped is not None and getattr(dropped, "jitter", 0) > 0:
            self._jitter_jobs -= 1
        jk = (group, job_id)
        spec = self._dep_jobs.pop(jk, None)
        if spec is not None:
            for u in spec.on:
                s = self._dep_rdeps.get((group, u))
                if s:
                    s.discard(jk)
                    if not s:
                        del self._dep_rdeps[(group, u)]
        self._dep_gated.pop(jk, None)
        self._dep_inflight.pop(jk, None)
        self._dep_blocked.discard(jk)
        if self._dep_rdeps.get(jk):
            # a dropped upstream breaks its dependents' columns
            # (DEP_BROKEN: they hold, loudly visible in dag show)
            self._dep_refresh_dependents(group, job_id)

    def _apply_group(self, value: str):
        try:
            g = Group.from_json(value)
        except (json.JSONDecodeError, TypeError):
            return
        self.groups[g.id] = g
        self.builder.set_group(g.id, g.node_ids)

    def _drop_group(self, gid: str):
        self.groups.pop(gid, None)
        self.builder.del_group(gid)

    def _node_up(self, node_id: str):
        if node_id in self.universe.index:
            return
        self.builder.node_added(node_id)
        for g in self.groups.values():         # re-derive group masks
            if node_id in g.node_ids:
                self.builder.set_group(g.id, g.node_ids)
        col = self.universe.index[node_id]
        self._col_node[col] = node_id
        self._col_live[col] = True
        cap = self.node_caps.get(node_id, self.default_node_cap)
        self.planner.set_node_capacity([col], [cap])

    def _node_down(self, node_id: str):
        col = self.universe.index.get(node_id)
        if col is None:
            return
        self.builder.node_removed(node_id)
        self._col_live[col] = False
        self._col_node[col] = None
        self.planner.set_node_capacity([col], [0])

    def drain_watches(self):
        try:
            self._drain_watches_once()
        except WatchLost as e:
            log.warnf("scheduler watch lost (%s); resynchronizing", e)
            self.stats["watch_losses"] += 1
            self.resync()

    def resync(self):
        """Anti-entropy: rebuild watchers and reconcile device state with
        the store's current contents.  Run after a lost watch stream
        (overflow / compacted reconnect) — re-applying is idempotent and
        rows whose job/group vanished during the gap are dropped."""
        for w in self._all_watches():
            try:
                w.close()
            except Exception:   # noqa: BLE001 — already-dead watchers
                pass
        # a lost watch stream dropped events the delta buffer never saw:
        # the recorded stream is no longer the complete change set since
        # the last save — the next checkpoint must be a full rebase
        if self._delta_buf is not None:
            self._delta_buf.clear()
            self._delta_valid = False
        self._open_watches()
        # one listing per prefix serves both the liveness diff and the
        # reload (recovery runs when the scheduler is already behind)
        job_kvs = self.store.get_prefix(self.ks.cmd)
        group_kvs = self.store.get_prefix(self.ks.group)
        node_kvs = self.store.get_prefix(self.ks.node)
        live_jobs = set()
        for kv in job_kvs:
            rest = kv.key[len(self.ks.cmd):]
            if "/" in rest:
                live_jobs.add(tuple(rest.split("/", 1)))
        # diff against self.jobs (every applied job, including row-less
        # ones whose rules never parsed), not just rows.by_job
        for (group, job_id) in [k for k in list(self.jobs)
                                if k not in live_jobs]:
            self._drop_job(group, job_id)
        live_groups = {kv.key[len(self.ks.group):] for kv in group_kvs}
        for gid in [g for g in list(self.groups) if g not in live_groups]:
            self._drop_group(gid)
        live_nodes = {kv.key[len(self.ks.node):] for kv in node_kvs}
        for nid in [n for n in list(self.universe.index)
                    if n not in live_nodes]:
            self._node_down(nid)
        self._load_initial(groups=group_kvs, nodes=node_kvs, jobs=job_kvs)

    def _drain_watches_once(self):
        # every stream's events flow through ONE dispatcher (_apply_ev)
        # shared with the delta-checkpoint fold, and — when a delta
        # buffer is live — get RECORDED before application, in exactly
        # the order they were applied (the fold replays the same order)
        rec = self._delta_buf if self._delta_valid else None
        for sid, w in (("tenants", self._w_tenants),
                       ("groups", self._w_groups),
                       ("nodes", self._w_nodes),
                       ("jobs", self._w_jobs),
                       ("deps", self._w_deps),
                       ("procs", self._w_procs),
                       ("orders", self._w_orders),
                       ("alone", self._w_alone)):
            for ev in w.drain():
                if rec is not None:
                    rec.append((sid, ev.type, ev.kv.key, ev.kv.value))
                self._apply_ev(sid, ev.type, ev.kv.key, ev.kv.value)
        if rec is not None and len(rec) > self.delta_max_events:
            # a buffer past the bound means the next delta would rival
            # a full save anyway — drop it and force a rebase
            rec.clear()
            self._delta_valid = False
            if not self._delta_overflowed:
                self._delta_overflowed = True
                log.warnf("checkpoint delta buffer exceeded %d events; "
                          "next save will be a full rebase",
                          self.delta_max_events)
        # checkpoint-plane control: operator save requests + the save
        # barrier (checkpoint_save proves mirror quiescence by watching
        # its own nonce come back through this stream).  NOT recorded
        # into the delta buffer — barrier nonces and save requests are
        # transient control flow, and replaying a request on fold would
        # trigger a spurious save.
        # partitioned plane: foreign demand summaries (transient leased
        # control state, like the ckpt stream NOT recorded into the
        # delta buffer — a restore re-mirrors live summaries within one
        # exchange period anyway)
        if self._w_acct is not None:
            for ev in self._w_acct.drain():
                self._apply_acct_ev(ev.type, ev.kv.key, ev.kv.value)
        for ev in self._w_ckpt.drain():
            if ev.type == DELETE:
                continue
            if ev.kv.key == self.ks.ckpt_req:
                self._ckpt_requested = True
            elif ev.kv.key == self.ks.ckpt_barrier or \
                    ev.kv.key.startswith(self.ks.ckpt_barrier + "/"):
                if ev.kv.mod_rev > \
                        self._ckpt_barrier_seen.get(ev.kv.key, 0):
                    self._ckpt_barrier_seen[ev.kv.key] = ev.kv.mod_rev

    def _apply_ev(self, sid: str, typ: str, key: str, value: str):
        """Apply ONE watch event to the host mirrors — the shared body
        of the live drain and the delta-checkpoint fold (a delta IS the
        recorded (sid, type, key, value) stream, so both paths must be
        the same code).  ``ordmirror`` is the synthetic stream for the
        leader's own-publish order accounting, which never arrives by
        watch (the orders watch is delete-only)."""
        if sid == "groups":
            gid = key[len(self.ks.group):]
            if typ == DELETE:
                self._drop_group(gid)
            else:
                self._apply_group(value)
        elif sid == "nodes":
            node_id = key[len(self.ks.node):]
            if typ == DELETE:
                self._node_down(node_id)
            else:
                self._node_up(node_id)
        elif sid == "jobs":
            if typ == DELETE:
                rest = key[len(self.ks.cmd):]
                if "/" in rest:
                    group, job_id = rest.split("/", 1)
                    self._drop_job(group, job_id)
            else:
                self._apply_job(key, value)
        elif sid == "tenants":
            # tenant quota records only; the web tier's per-tenant job
            # index markers share the prefix and are not ours to mirror
            rest = key[len(self.ks.tenant):]
            if not rest.endswith("/quota"):
                return
            name = rest[:-len("/quota")]
            if not name or "/" in name:
                return
            if typ == DELETE:
                self._drop_tenant_quota(name)
            else:
                self._apply_tenant_quota(name, value)
        elif sid == "deps":
            # workflow DAG completion events: fold the round's scheduled
            # epoch into the job's (success, fail) pair and queue the
            # device scatter for every row the job occupies.  Monotone
            # max host-side AND device-side, so duplicate deliveries,
            # multi-node Common completions and delta-chain replays are
            # all idempotent.
            rest = key[len(self.ks.dep):]
            if "/" not in rest:
                return
            group, job_id = rest.split("/", 1)
            if self._owns is not None and not self._owns(job_id):
                return      # foreign slice (cross-partition dep edges
                            # are refused at registration — see
                            # _dep_spec_apply)
            jk = (group, job_id)
            if typ == DELETE:
                # an operator wiped the key: forget the host mirror (a
                # later row acquire seeds from scratch); device epochs
                # stay — they are monotone and rows reset on release
                self._dep_latest.pop(jk, None)
                return
            epoch_s, _, status = value.partition("|")
            try:
                rel = int(float(epoch_s)) - FRAMEWORK_EPOCH
            except ValueError:
                return
            succ, fail = self._dep_latest.get(jk, (DEP_NEVER, DEP_NEVER))
            if status == "fail":
                fail = max(fail, rel)
            else:
                succ = max(succ, rel)
            self._dep_latest[jk] = (succ, fail)
            # device scatters only for jobs something DEPENDS ON: a
            # dep-free fleet's completion stream must cost the mirror
            # fold alone, not a padded device scatter per flush (the
            # mirror re-seeds rows if a dependent registers later)
            if self._dep_supported and self._dep_rdeps.get(jk):
                by_cmd = self.rows.by_cmd
                for rid in self.rows.by_job.get(jk, ()):
                    row = by_cmd.get((group, job_id, rid))
                    if row is not None:
                        self._dep_epoch_updates[row] = (succ, fail)
        # execution-state mirrors: proc registry (leased keys expire ->
        # DELETE events age dead executions out), outstanding exclusive
        # orders (delete-only watch: own puts mirrored at submit), Alone
        # lifetime locks
        elif sid == "procs":
            if typ == DELETE:
                self._acct_del(self._procs, key)
            else:
                t = self._parse_proc(key)
                if t and (self._owns is None or self._owns(t[2])):
                    self._acct_add(self._procs, key, *t)
        elif sid == "orders":
            if typ == DELETE:
                self._acct_del(self._orders, key)   # no-op for keys a
                # partitioned mirror never held (foreign partitions')
            else:       # defensive: the delete-only filter should
                t = self._parse_order(key)             # suppress these
                if t and (self._owns is None or self._owns(t[2])):
                    self._acct_add(self._orders, key, *t)
        elif sid == "alone":
            jid = key[len(self._alone_pfx):]
            if self._owns is not None and not self._owns(jid):
                return
            if typ == DELETE:
                self._alone_live.discard(jid)
            else:
                self._alone_live.add(jid)
        elif sid == "ordmirror":
            try:
                node, jobs = value
            except (TypeError, ValueError):
                return
            self._acct_add_order(key, node,
                                 [tuple(j) for j in jobs])

    def _parse_proc(self, key: str) -> Optional[Tuple[str, str, str]]:
        rest = key[len(self.ks.proc):].split("/")
        if len(rest) != 4:
            return None
        node_id, group, job_id, _pid = rest
        return node_id, group, job_id

    def _parse_order(self, key: str) -> Optional[Tuple[str, str, str]]:
        """Legacy per-(node, second, job) order keys only.  Coalesced
        (node, second) bundle keys need their VALUE for accounting and
        are handled by _acct_add_order / _build_mirrors; broadcast
        (Common) orders reserve no exclusive capacity — their load lands
        via proc keys once running."""
        rest = key[len(self.ks.dispatch):].split("/")
        if len(rest) != 4 or rest[0] == Keyspace.BROADCAST:
            return None
        node_id, _epoch, group, job_id = rest
        return node_id, group, job_id

    # -- incremental execution-state accounting ---------------------------

    def _acct_add(self, mirror: Dict[str, Tuple[str, float, bool]],
                  key: str, node_id: str, group: str, job_id: str):
        """Mirror + counter add.  Cost/exclusivity are FROZEN at entry
        time (the matching delete must decrement what the add
        incremented, not whatever the job's EWMA says later); drift from
        later job edits washes out at the next anti-entropy."""
        if key in mirror:
            return
        job = self.jobs.get((group, job_id))
        cost = job.avg_time if job and job.avg_time > 0 else 1.0
        excl = bool(job and job.exclusive)
        mirror[key] = (node_id, cost, excl)
        self._load_sum[node_id] = self._load_sum.get(node_id, 0.0) + cost
        if excl:
            self._excl_cnt[node_id] = self._excl_cnt.get(node_id, 0) + 1
            if self._tenants and job and job.tenant:
                tid = self._tenant_ids.get(job.tenant, 0)
                if tid:
                    self._acct_tid[key] = {tid: 1}
                    self._tenant_excl[tid] = \
                        self._tenant_excl.get(tid, 0) + 1
        if mirror is self._procs and (group, job_id) in self._dep_gated:
            jk = (group, job_id)
            self._dep_inflight[jk] = self._dep_inflight.get(jk, 0) + 1

    def _acct_add_order(self, key: str, node_id: str, jobs: list):
        """Mirror + counter add for one COALESCED order key: the bundle
        reserves len(jobs) exclusive slots and the summed cost until its
        per-job proc keys exist (the agent's claim_bundle converts the
        reservation to proc accounting atomically).  The mirror's third
        element is the slot COUNT — _acct_del decrements exactly what
        this added, so partial drift from later job edits washes out at
        anti-entropy like every other mirror entry."""
        if key in self._orders:
            return
        if self._delta_buf is not None and self._delta_valid:
            # own publishes never echo back through the delete-only
            # orders watch, so the delta stream records them HERE (a
            # restored standby's mirrors then match the live leader's
            # without waiting on the anti-entropy listing).  The value
            # stays a raw (node, jobs) tuple — this append rides the
            # step thread's publish accounting, and serialization
            # belongs to save time, not the hot path.
            self._delta_buf.append(
                ("ordmirror", PUT, key, (node_id, list(jobs))))
        cost = 0.0
        tids: Optional[dict] = {} if self._tenants else None
        for group, job_id in jobs:
            job = self.jobs.get((group, job_id))
            cost += job.avg_time if job and job.avg_time > 0 else 1.0
            if tids is not None and job and job.tenant:
                t = self._tenant_ids.get(job.tenant, 0)
                if t:
                    tids[t] = tids.get(t, 0) + 1
        if tids:
            self._acct_tid[key] = tids
            for t, n in tids.items():
                self._tenant_excl[t] = self._tenant_excl.get(t, 0) + n
        slots = len(jobs)
        self._orders[key] = (node_id, cost, slots)
        self._load_sum[node_id] = self._load_sum.get(node_id, 0.0) + cost
        if slots:
            self._excl_cnt[node_id] = \
                self._excl_cnt.get(node_id, 0) + slots

    def _acct_del(self, mirror: Dict[str, Tuple[str, float, bool]],
                  key: str):
        ent = mirror.pop(key, None)
        if ent is None:
            return
        tids = self._acct_tid.pop(key, None)
        if tids:
            for t, n in tids.items():
                left = self._tenant_excl.get(t, 0) - n
                if left > 0:
                    self._tenant_excl[t] = left
                else:
                    self._tenant_excl.pop(t, None)
        if mirror is self._procs and self._dep_gated:
            t = self._parse_proc(key)
            if t is not None and (t[1], t[2]) in self._dep_gated:
                jk = (t[1], t[2])
                n = self._dep_inflight.get(jk, 0) - 1
                if n > 0:
                    self._dep_inflight[jk] = n
                else:
                    self._dep_inflight.pop(jk, None)
        node_id, cost, excl = ent
        s = self._load_sum.get(node_id, 0.0) - cost
        if s > 1e-9:
            self._load_sum[node_id] = s
        else:
            self._load_sum.pop(node_id, None)
        if excl:
            # excl is a slot COUNT for coalesced order keys (bool for
            # proc entries and legacy per-job orders; bool is int)
            n = self._excl_cnt.get(node_id, 0) - excl
            if n > 0:
                self._excl_cnt[node_id] = n
            else:
                self._excl_cnt.pop(node_id, None)

    def _ae_conn(self):
        """Connection for background anti-entropy listings: a dedicated
        clone when the store supports it — a multi-hundred-MB get_prefix
        reply on the MAIN connection would serialize ahead of every live
        step RPC on that socket."""
        if self._ae_store is None:
            self._ae_store = (self.store.clone()
                              if hasattr(self.store, "clone")
                              else self.store)
        return self._ae_store

    def _build_mirrors(self, store=None):
        """List the execution-state prefixes into FRESH mirror + counter
        structures (no live state touched — safe off-thread)."""
        store = store or self.store
        procs: Dict[str, Tuple[str, float, bool]] = {}
        orders: Dict[str, Tuple[str, float, bool]] = {}
        excl: Dict[str, int] = {}
        load: Dict[str, float] = {}
        # per-key tenant breakdown of exclusive order slots, parsed
        # from the bundle values while we have them (the mirrors only
        # keep counts) — feeds _rebuild_tenant_excl
        order_tids: Dict[str, dict] = {}
        want_tids = bool(self._tenants)

        def add(mirror, key, node_id, group, job_id):
            job = self.jobs.get((group, job_id))
            cost = job.avg_time if job and job.avg_time > 0 else 1.0
            mirror[key] = (node_id, cost, bool(job and job.exclusive))
            load[node_id] = load.get(node_id, 0.0) + cost
            if job and job.exclusive:
                excl[node_id] = excl.get(node_id, 0) + 1

        for kv in _list_prefix(store, self.ks.proc):
            t = self._parse_proc(kv.key)
            if t and (self._owns is None or self._owns(t[2])):
                add(procs, kv.key, *t)
        for kv in _list_prefix(store, self.ks.dispatch):
            rest = kv.key[len(self.ks.dispatch):].split("/")
            if rest[0] == Keyspace.BROADCAST:
                # broadcast (Common) orders reserve no exclusive
                # capacity; their load lands via proc keys once running
                continue
            if len(rest) == 2:
                # coalesced (node, second) bundle: value is the node's
                # job list; the key reserves len(jobs) exclusive slots.
                # Partitioned: the ".<p>" epoch suffix scopes the key —
                # only OWN bundles enter the mirror (foreign demand
                # arrives via the acct exchange, never double-counted);
                # an unsuffixed leftover from an unpartitioned past is
                # attributed per entry by job token below.
                parsed = Keyspace.split_bundle_epoch(rest[1])
                if parsed is None:
                    continue
                if self._owns is not None and parsed[1] is not None \
                        and parsed[1] != self.partition:
                    continue
                try:
                    entries = json.loads(kv.value)
                except (json.JSONDecodeError, TypeError):
                    continue
                if not isinstance(entries, list):
                    continue
                node_id = rest[0]
                cost = 0.0
                slots = 0
                per_entry = self._owns is not None and parsed[1] is None
                tids: Dict[int, int] = {}
                for e in entries:
                    if not isinstance(e, str) or "/" not in e:
                        continue
                    group, _, job_id = e.partition("/")
                    if per_entry and not self._owns(job_id):
                        continue
                    job = self.jobs.get((group, job_id))
                    cost += job.avg_time if job and job.avg_time > 0 \
                        else 1.0
                    slots += 1
                    if want_tids and job and job.tenant:
                        t = self._tenant_ids.get(job.tenant, 0)
                        if t:
                            tids[t] = tids.get(t, 0) + 1
                if per_entry and not slots:
                    continue    # bundle entirely foreign-owned
                if tids:
                    order_tids[kv.key] = tids
                orders[kv.key] = (node_id, cost, slots)
                load[node_id] = load.get(node_id, 0.0) + cost
                if slots:
                    excl[node_id] = excl.get(node_id, 0) + slots
                continue
            t = self._parse_order(kv.key)
            if t and (self._owns is None or self._owns(t[2])):
                add(orders, kv.key, *t)
        alone = {kv.key[len(self._alone_pfx):]
                 for kv in _list_prefix(store, self._alone_pfx)
                 if self._owns is None
                 or self._owns(kv.key[len(self._alone_pfx):])}
        return procs, orders, alone, excl, load, order_tids

    def _install_mirrors(self, built):
        order_tids = None
        if len(built) == 6:
            *built, order_tids = built
        self._procs, self._orders, self._alone_live, \
            self._excl_cnt, self._load_sum = built
        # ground-truth rebuild of the dep in-flight counters from the
        # fresh procs mirror (the incremental counters drift with the
        # same bounded windows the load/excl counters do)
        infl: Dict[Tuple[str, str], int] = {}
        if self._dep_gated:
            for k in self._procs:
                t = self._parse_proc(k)
                if t is not None and (t[1], t[2]) in self._dep_gated:
                    jk = (t[1], t[2])
                    infl[jk] = infl.get(jk, 0) + 1
        self._dep_inflight = infl
        if self._tenants or self._acct_tid or order_tids:
            self._rebuild_tenant_excl(order_tids)
        self._mirror_resync_at = self.clock() + self.mirror_resync_s

    def _mirror_antientropy(self):
        """Ground-truth re-list of the execution-state mirrors + their
        counters.  Runs synchronously at boot and on watch loss (via
        resync -> _load_initial) — between runs the mirrors advance
        purely on watch deltas plus the leader's own publishes, so
        steady-state step() issues O(delta) store ops instead of
        re-serializing every outstanding key per second."""
        self._install_mirrors(self._build_mirrors())

    def _maybe_antientropy_bg(self):
        """Periodic anti-entropy WITHOUT stalling the step: the listing
        (seconds at scale when millions of leased orders are
        outstanding) runs on a helper thread; the step installs the
        finished snapshot on a later iteration.  Deltas that land while
        the listing runs can be missed by the snapshot — bounded drift,
        healed by the next round (and every key involved is leased, so
        errors also age out by TTL)."""
        if self._ae_result is not None:
            built, self._ae_result = self._ae_result, None
            self._ae_thread = None
            self._install_mirrors(built)
            if self._ae_rekick:
                # the installed snapshot was listed before a takeover:
                # schedule a fresh listing immediately, not in 30 s
                self._ae_rekick = False
                self._mirror_resync_at = 0.0
            return
        if self._ae_thread is not None or \
                self.clock() < self._mirror_resync_at:
            return

        def run():
            try:
                self._ae_result = self._build_mirrors(self._ae_conn())
            except Exception as e:  # noqa: BLE001 — retry next period
                log.warnf("anti-entropy listing failed: %s", e)
                self._ae_thread = None
                self._mirror_resync_at = self.clock() + 5.0
        self._ae_thread = threading.Thread(target=run, daemon=True,
                                           name="sched-antientropy")
        self._ae_thread.start()

    # ---- checkpoint plane ------------------------------------------------

    @property
    def checkpoint_restored(self) -> bool:
        """True when this instance booted from a checkpoint (warm)
        rather than the cold store load."""
        return bool(self._ckpt_stats["restored"])

    def _checkpoint_path(self) -> str:
        from ..checkpoint.sched_ckpt import FILE_NAME
        if not self.checkpoint_dir:
            raise RuntimeError("no checkpoint_dir configured")
        return os.path.join(self.checkpoint_dir, FILE_NAME)

    def _barrier_keys(self) -> List[str]:
        """One barrier nonce key per shard.  Against a plain store this
        is the bare ckpt_barrier key (byte-identical to the scalar
        protocol); against N shards, suffixes are MINED so each key
        hashes to a distinct shard (suffixed keys route by full-key
        token, so the mapping is deterministic across processes) — all
        under the watched ckpt prefix."""
        n = getattr(self.store, "nshards", 1)
        base = self.ks.ckpt_barrier
        if n <= 1:
            return [base]
        from ..store.sharded import shard_index
        prefix = getattr(self.store, "prefix", self.ks.prefix)
        keys: List[Optional[str]] = [None] * n
        found = j = 0
        while found < n:
            k = f"{base}/{j}"
            i = shard_index(k, n, prefix)
            if keys[i] is None:
                keys[i] = k
                found += 1
            j += 1
        return keys

    def _checkpoint_barrier(self, timeout: float = 30.0):
        """Quiesce point for a checkpoint: returns a store revision R
        such that every watch event with mod_rev <= R has been applied
        to the host mirrors — a scalar against a plain store, a
        per-shard revision VECTOR against a sharded one (each entry
        quiescent for ITS shard's stream; there is no global revision
        to quiesce on).

        Protocol, per shard: write a barrier nonce under the watched
        ckpt prefix and drain watches until its revision comes back,
        TWICE.  Watch events reach this process through one connection
        per shard whose server batches frames per watcher, so a frame
        carrying the first barrier can overtake an older event's frame
        within the same send batch — but the second barrier is only
        written after the first was OBSERVED, i.e. after that whole
        batch was on the wire; seeing barrier #2 therefore proves every
        event at or before barrier #1's revision is in the client-side
        queues, and one final drain applies them.  R is barrier #1's
        revision (per shard)."""
        keys = self._barrier_keys()
        deadline = time.monotonic() + timeout
        revs = [0] * len(keys)
        for i in (1, 2):
            for ki, key in enumerate(keys):
                r = self.store.put(key, f"{self.node_id}/{i}")
                if i == 1:
                    revs[ki] = r
                while self._ckpt_barrier_seen.get(key, 0) < r:
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"checkpoint barrier timed out after "
                            f"{timeout}s (key {key})")
                    self._drain_watches_once()
                    if self._ckpt_barrier_seen.get(key, 0) >= r:
                        break
                    time.sleep(0.005)
        self._drain_watches_once()
        return revs[0] if len(keys) == 1 else revs

    def _delta_possible(self, path: str) -> bool:
        """A delta save extends the live chain iff one exists for this
        path, the event buffer is complete (no watch loss / overflow
        since the last save), and the auto-rebase knobs aren't hit."""
        ch = self._ckpt_chain
        return (self._delta_on and ch is not None
                and ch.get("path") == path
                and self._delta_buf is not None and self._delta_valid
                and ch["seq"] < self.delta_max_chain
                and ch["bytes"] < self.delta_max_bytes)

    def _ckpt_join(self, timeout: Optional[float] = None):
        """Wait out an in-flight background full-save serialization
        (saves serialize against each other: a delta element must not
        race the base writer's clear-then-rename)."""
        t = self._ckpt_writer
        if t is not None:
            t.join(timeout)
            if not t.is_alive():
                self._ckpt_writer = None

    def checkpoint_save(self, path: Optional[str] = None,
                        kind: str = "auto", wait: bool = True) -> dict:
        """Persist a restore point keyed by the store revision (scalar,
        or the per-shard vector on a sharded store) the barrier proves
        quiescent.  ``kind``: "auto" writes a small DELTA chain element
        (the watch events applied since the last save) when a live
        chain allows it and a full base save otherwise — save cost
        proportional to CHANGE, not state; "full" forces a rebase;
        "delta" forces a delta (raises when no chain is extendable).
        STEP-THREAD (or quiesced-service) only: the mirrors have a
        single writer and the barrier drains watches inline.

        Full saves are DOUBLE-BUFFERED: the step thread captures a
        stable state copy (shallow dict/array copies + device fetches),
        and the O(state) pickle serialization runs on a background
        writer so steps continue while the bytes land (``wait=False``,
        the periodic cadence's path; ``wait=True`` joins the writer
        before returning — the synchronous contract tests and operator
        triggers rely on).  The returned/recorded ``ms`` is the
        STEP-THREAD portion (barrier + capture) — the lease-health
        number; the serialize span lands in
        ``checkpoint_last_serialize_ms``.

        Accounting for builds still in flight on the pipeline worker
        lands after their windows complete; a restore therefore may
        under-count the leader's own most-recent order reservations —
        the same bounded over-commit a fresh leadership has, healed by
        the anti-entropy listing the restore kicks immediately."""
        from ..checkpoint import (clear_delta_chain, save_checkpoint,
                                  save_delta)
        if path is None:
            path = self._checkpoint_path()
        from ..checkpoint.sched_ckpt import gc_paused
        # serialize saves: a previous base's writer must finish before
        # this save touches the chain files
        self._ckpt_join()
        t0 = time.perf_counter()
        rev = self._checkpoint_barrier()
        as_delta = self._delta_possible(path) and kind != "full"
        if kind == "delta" and not as_delta:
            raise RuntimeError(
                "delta checkpoint not possible: no extendable chain "
                "(no base saved this process, buffer invalidated, or "
                "rebase knobs hit)")
        if as_delta:
            ch = self._ckpt_chain
            events = list(self._delta_buf)
            seq = ch["seq"] + 1
            p = save_delta(path, ch["nonce"], seq, ch["rev"], rev,
                           events)
            try:
                ch["bytes"] += os.path.getsize(p)
            except OSError:
                pass
            ch["seq"] = seq
            ch["rev"] = rev
            self._delta_buf.clear()
            self._ckpt_stats["delta_saves_total"] += 1
            self._ckpt_stats["last_delta_events"] = len(events)
            out_kind = "delta"
        else:
            # the barrier's drains may have queued table/eligibility
            # updates not yet scattered to the device: flush BEFORE
            # capturing, or the saved device arrays lag the saved host
            # dicts and a restore dispatches stale rows until those
            # jobs next change (latent in the PR 5 save; the delta
            # fold's explicit replay made it visible)
            self._flush_device()
            with gc_paused():
                state = self._checkpoint_state(rev)
            # a fresh base starts a fresh chain: stale elements are
            # unlinked (descending seq — a crash mid-way leaves a
            # contiguous, still-valid OLD chain) BEFORE the rename
            # publishes the new base
            state["chain"] = nonce = (
                f"{self.node_id}-{os.getpid()}-"
                f"{int(time.time() * 1e3):x}")
            # chain bookkeeping at CAPTURE time: the delta stream
            # restarts from this instant whether or not the bytes have
            # landed yet (saves serialize via _ckpt_join, so no delta
            # element can precede the base on disk)
            self._ckpt_chain = {"nonce": nonce, "seq": 0, "rev": rev,
                                "bytes": 0, "path": path}
            if self._delta_buf is not None:
                self._delta_buf.clear()
            self._delta_valid = True
            self._delta_overflowed = False

            def write():
                ts = time.perf_counter()
                try:
                    with gc_paused():
                        clear_delta_chain(path)
                        save_checkpoint(path, state)
                except Exception as e:  # noqa: BLE001 — a failed base
                    # leaves no extendable chain (the next save rebases)
                    self._ckpt_chain = None
                    self._ckpt_stats["save_errors_total"] += 1
                    log.errorf("checkpoint serialization failed: %s", e)
                finally:
                    self._ckpt_stats["last_serialize_ms"] = round(
                        (time.perf_counter() - ts) * 1e3, 3)
            if wait:
                write()
            else:
                self._ckpt_stats["bg_writes_total"] += 1
                self._ckpt_writer = threading.Thread(
                    target=write, daemon=True, name="sched-ckpt-write")
                self._ckpt_writer.start()
            out_kind = "full"
        ms = (time.perf_counter() - t0) * 1e3
        self._ckpt_stats["saves_total"] += 1
        self._ckpt_stats["last_save_ms"] = round(ms, 3)
        self._ckpt_stats["last_rev"] = (max(rev) if isinstance(rev, list)
                                        else rev)
        log.infof("scheduler checkpoint saved (%s): rev %s, %.0f ms, %s",
                  out_kind, rev, ms, path)
        return {"rev": rev, "ms": ms, "path": path, "kind": out_kind}

    def _mesh_topology(self) -> Optional[dict]:
        """Mesh-planner topology tag for checkpoints: a checkpoint of
        device shards is only restorable onto the SAME mesh shape (the
        fetched host arrays are shape-complete, but a different split
        changes placement determinism and the per-rank re-pin layout) —
        a mismatch cold-loads loudly.  None for the plain planner, so
        pre-mesh checkpoints (no "mesh" field) keep restoring."""
        if getattr(self.planner, "mesh", None) is None:
            return None
        return {"kind": type(self.planner).__name__,
                "dj": int(getattr(self.planner, "Dj", 1)),
                "dn": int(getattr(self.planner, "Dn", 1)),
                "devices": int(self.planner.mesh.devices.size)}

    def _checkpoint_state(self, rev: int) -> dict:
        """Capture the BUILT state as a STABLE copy: every mutable host
        structure is shallow-copied (and the in-place-scattered builder
        arrays deep-copied), so the serialization can run on a
        background thread while steps keep mutating the originals (the
        double-buffered full save).  Device arrays fetch into fresh
        host buffers by construction."""
        import dataclasses
        import jax
        from ..checkpoint.sched_ckpt import pack_jobs
        table = self.planner.table
        # device state materializes through the planner's _fetch when it
        # has one (mesh planners: host-gathers the per-rank shards — on
        # multihost meshes that is a cross-process allgather); the plain
        # planner's arrays are a direct device read
        fetch = getattr(self.planner, "_fetch",
                        lambda a: np.asarray(jax.device_get(a)))
        dep = dict(latest=dict(self._dep_latest))
        if self._dep_supported:
            # the mutable dep vectors — last_fire especially: a restore
            # without it would re-fire every chain's last round
            dep.update(self.planner.dep_state())
        # tenancy: the quota registry, the id space, the row map and
        # the per-tenant counters; plus the DYNAMIC token columns — a
        # restore without them would hand every bucket a free burst
        tenant = dict(
            T=self._tenant_T,
            quotas={n: q.to_dict() for n, q in self._tenants.items()},
            ids=dict(self._tenant_ids), names=list(self._tid_name),
            row_tenant=np.array(self._row_tenant),
            counters={n: dict(c)
                      for n, c in self._tenant_counters.items()},
            acct_tid={k: dict(v) for k, v in self._acct_tid.items()},
            state=(self.planner.tenant_state()
                   if self._tenant_supported else {}))
        return dict(
            rev=rev, saved_at=time.time(), node_id=self.node_id,
            prefix=self.ks.prefix, J=self.planner.J, N=self.planner.N,
            # partitioned plane: a checkpoint is ONE partition's chain
            # — restoring it under a different slice would install a
            # foreign job-space (absent fields = pre-partition saves,
            # restorable on the unpartitioned scheduler only)
            partitions=self.partitions, partition=self.partition,
            mesh=self._mesh_topology(),
            # device state materialized to host numpy: the packed
            # schedule table (no cron re-parse on restore), eligibility
            # matrix, job meta.  load/rem_cap are NOT checkpointed —
            # reconcile_capacity rewrites both absolutely from the
            # mirrors every leading step.
            table={f.name: np.asarray(fetch(getattr(table, f.name)))
                   for f in dataclasses.fields(table)},
            elig=np.asarray(fetch(self.planner.elig)),
            exclusive=np.asarray(fetch(self.planner.exclusive)),
            cost=np.asarray(fetch(self.planner.cost)),
            dep=dep, tenant=tenant,
            # jobs ride columnar (pack_jobs); the builder's per-row rule
            # inputs and reverse group index are DERIVED from them at
            # restore (set_job aliases the rules' own lists, so the
            # derivation reproduces both the data and the sharing)
            jobs=pack_jobs(self.jobs), groups=dict(self.groups),
            node_caps=dict(self.node_caps),
            rows=dict(by_cmd=dict(self.rows.by_cmd),
                      free=list(self.rows._free)),
            universe=dict(index=dict(self.universe.index),
                          free=list(self.universe._free)),
            builder=dict(group_mask=dict(self.builder.group_mask),
                         matrix=np.array(self.builder.matrix)),
            row_phase=dict(self._row_phase),
            row_dispatch=dict(self._row_dispatch),
            rd=dict(flags=np.array(self._rd_flags),
                    payload=list(self._rd_payload),
                    suffix=list(self._rd_suffix),
                    bentry=list(self._rd_bentry),
                    job=list(self._rd_job)),
            col_node=list(self._col_node),
            col_live=np.array(self._col_live),
            mirrors=dict(procs=dict(self._procs),
                         orders=dict(self._orders),
                         alone=set(self._alone_live),
                         excl=dict(self._excl_cnt),
                         load=dict(self._load_sum)),
        )

    def _checkpoint_restore(self) -> bool:
        """Warm takeover: load the checkpoint, open every watch at
        ``rev + 1`` (replaying exactly the delta since the checkpointed
        state), and install the built state host- and device-side.
        Any mismatch — missing/torn file, version or shape skew, or a
        revision that fell out of the store's bounded watch history —
        falls back to the cold load, LOUDLY.  Validation happens before
        any state mutates, so a refused checkpoint leaves a clean slate
        for the cold path.  The whole restore runs with the cyclic GC
        paused: it allocates ~1M live objects, and the gen-2
        collections that triggers scan the entire heap for nothing
        (measured as the majority of the takeover time at 50k jobs)."""
        from ..checkpoint.sched_ckpt import gc_paused
        with gc_paused():
            return self._checkpoint_restore_inner()

    def _checkpoint_restore_inner(self) -> bool:
        from ..checkpoint import CheckpointError, load_checkpoint
        import jax.numpy as jnp
        from ..ops.schedule_table import ScheduleTable
        from ..checkpoint import load_delta_chain
        path = self._checkpoint_path()
        t0 = time.perf_counter()
        try:
            st = load_checkpoint(path)
            # the delta chain validates WHOLE before anything mutates:
            # torn element, seq gap, foreign nonce, rev mismatch all
            # refuse here (cold load), never a half-folded scheduler
            deltas = load_delta_chain(path, st)
            # every key the install below dereferences, validated HERE:
            # a version-valid pickle missing a field (hand-edited,
            # foreign build) must cold-load, not crash-loop the
            # constructor on a KeyError with the bad file still on disk
            missing = [k for k in (
                "rev", "prefix", "J", "N", "table", "elig", "exclusive",
                "cost", "dep", "jobs", "groups", "node_caps", "rows",
                "universe", "builder", "row_phase", "row_dispatch",
                "rd", "col_node", "col_live", "mirrors") if k not in st]
            for outer, subkeys in (
                    ("rows", ("by_cmd", "free")),
                    ("universe", ("index", "free")),
                    ("builder", ("group_mask", "matrix")),
                    ("dep", ("latest",)),
                    ("rd", ("flags", "payload", "suffix", "bentry",
                            "job")),
                    ("mirrors", ("procs", "orders", "alone", "excl",
                                 "load"))):
                if not isinstance(st.get(outer), dict):
                    missing.append(outer)
                else:
                    missing += [f"{outer}.{k}" for k in subkeys
                                if k not in st[outer]]
            if missing:
                raise CheckpointError(
                    f"checkpoint missing fields {missing}")
            if st.get("prefix") != self.ks.prefix:
                raise CheckpointError(
                    f"keyspace prefix {st.get('prefix')!r} != "
                    f"{self.ks.prefix!r}")
            # per-partition chains: the slice must match exactly (a
            # pre-partition checkpoint carries no fields and defaults
            # to the unpartitioned identity)
            if (int(st.get("partitions", 1) or 1),
                    int(st.get("partition", 0) or 0)) != \
                    (self.partitions, self.partition):
                raise CheckpointError(
                    f"checkpoint is partition "
                    f"{st.get('partition', 0)} of "
                    f"{st.get('partitions', 1)}; this scheduler is "
                    f"partition {self.partition} of {self.partitions}")
            if st.get("J") != self.planner.J \
                    or st.get("N") != self.planner.N:
                raise CheckpointError(
                    f"planner shape J={st.get('J')}/N={st.get('N')} != "
                    f"J={self.planner.J}/N={self.planner.N}")
            # tenant id space must match like J/N: restored tids index
            # the [T] bucket columns and the fair-share cap arrays (an
            # unstamped/absent blob predates the stamp — its ids were
            # bounded by the old default and install tolerates it)
            ten_blob = st.get("tenant")
            if isinstance(ten_blob, dict):
                saved_t = int(ten_blob.get("T", 0) or 0)
                if saved_t and saved_t != self._tenant_T:
                    raise CheckpointError(
                        f"tenant id space T={saved_t} != planner "
                        f"tenant_capacity {self._tenant_T}")
            # mesh topology must match exactly (absent field == plain
            # planner, so pre-mesh checkpoints stay restorable on plain
            # planners and nothing else)
            if st.get("mesh") != self._mesh_topology():
                raise CheckpointError(
                    f"mesh topology {st.get('mesh')} != this planner's "
                    f"{self._mesh_topology()}")
            # effective revision = the chain TIP's (the last delta's,
            # or the base's when the base stands alone): a scalar
            # against a plain store, a per-shard VECTOR against a
            # sharded one.  Shape must match the store's topology — a
            # 2-shard checkpoint against a 3-shard (or unsharded) store
            # is a different deployment, cold load.
            rev = deltas[-1]["rev"] if deltas else st["rev"]
            nsh = getattr(self.store, "nshards", 1)
            if isinstance(rev, (list, tuple)):
                rev = [int(r) for r in rev]
                if nsh <= 1 or len(rev) != nsh:
                    raise CheckpointError(
                        f"revision vector shape {len(rev)} != store "
                        f"shard count {nsh}")
            else:
                rev = int(rev)
                if nsh > 1:
                    raise CheckpointError(
                        f"scalar checkpoint revision against a "
                        f"{nsh}-shard store")
            try:
                tbl = dict(st["table"])
                # pre-tenancy checkpoints predate the tenant column:
                # default it (all rows on the unlimited default tenant)
                # instead of refusing — the restore contract keeps old
                # saves loading across the upgrade
                if "tenant" not in tbl and "sec_lo" in tbl:
                    tbl["tenant"] = np.zeros(
                        len(tbl["sec_lo"]), np.int32)
                # pre-jitter checkpoints predate the jitter column:
                # default it (no smear) under the same contract
                if "jitter" not in tbl and "sec_lo" in tbl:
                    tbl["jitter"] = np.zeros(
                        len(tbl["sec_lo"]), np.int32)
                table = ScheduleTable(**{k: jnp.asarray(v)
                                         for k, v in tbl.items()})
                elig = jnp.asarray(st["elig"])
                excl = jnp.asarray(st["exclusive"])
                cost = jnp.asarray(st["cost"])
            except Exception as e:  # noqa: BLE001 — torn/foreign payload
                raise CheckpointError(f"device payload malformed: {e}")
            # the store must be the SAME incarnation the checkpoint was
            # cut from: a rev-regressed store (wiped/lost WAL, fresh
            # store) would accept watch(start_rev=rev+1) silently —
            # past-the-end watches register without error — and the
            # restored scheduler would dispatch ghost state forever
            try:
                store_rev = self.store.rev()
            except Exception as e:  # noqa: BLE001 — server predates
                # the rev op: cannot prove incarnation, cold-load
                raise CheckpointError(
                    f"store revision unverifiable ({e})")
            if isinstance(rev, list):
                if not isinstance(store_rev, (list, tuple)) \
                        or len(store_rev) != len(rev):
                    raise CheckpointError(
                        f"store revision {store_rev!r} is not a "
                        f"{len(rev)}-entry vector")
                behind = any(s < r for s, r in zip(store_rev, rev))
            else:
                behind = store_rev < rev
            if behind:
                raise CheckpointError(
                    f"store revision {store_rev} is BEHIND checkpoint "
                    f"rev {rev} — different store incarnation")
            # the delta since the checkpoint must still be replayable
            # from the store's watch history, or the checkpoint is too
            # stale to be safe — cold load instead
            resume = ([r + 1 for r in rev] if isinstance(rev, list)
                      else rev + 1)
            try:
                self._open_watches(start_rev=resume)
            except (CompactedError, WatchLost) as e:
                raise CheckpointError(
                    f"rev {rev} fell out of the store's watch history "
                    f"({e})")
        except CheckpointError as e:
            log.warnf("scheduler checkpoint restore from %s failed: %s "
                      "— falling back to COLD load", path, e)
            return False
        except (KeyError, TypeError, ValueError) as e:
            # malformed-but-version-valid payload the explicit checks
            # missed: same contract — cold load, loudly, never a
            # constructor crash-loop with the bad file still on disk
            log.warnf("scheduler checkpoint restore from %s failed "
                      "(malformed payload: %r) — falling back to COLD "
                      "load", path, e)
            return False
        # install host state (plain assignments: nothing here can fail
        # and leave a half-restored scheduler)
        from ..checkpoint.sched_ckpt import unpack_jobs
        st_rows = st["rows"]
        self.rows.by_cmd = st_rows["by_cmd"]
        self.rows._free = st_rows["free"]
        self.rows.by_row = {row: key
                            for key, row in st_rows["by_cmd"].items()}
        by_job: Dict[Tuple[str, str], Set[str]] = {}
        for (g, j, rid), _row in st_rows["by_cmd"].items():
            by_job.setdefault((g, j), set()).add(rid)
        self.rows.by_job = by_job
        self.jobs = unpack_jobs(st["jobs"])
        self.groups = st["groups"]
        self.node_caps = st["node_caps"]
        u = st["universe"]
        self.universe.index = u["index"]
        self.universe._free = u["free"]
        b = st["builder"]
        self.builder.group_mask = b["group_mask"]
        self.builder.matrix = b["matrix"]
        self.builder._dirty = set()
        # per-row rule inputs + reverse group index, derived from the
        # restored jobs exactly as _apply_job builds them — including
        # the ownership-transfer aliasing (the builder's lists ARE the
        # rules' lists, never copies)
        job_rules: Dict[int, dict] = {}
        group_jobs: Dict[str, set] = {}
        for (g, jid, rid), row in st_rows["by_cmd"].items():
            job = self.jobs.get((g, jid))
            rule = None
            if job is not None:
                for r in job.rules:
                    if r.id == rid:
                        rule = r
                        break
            if rule is None:
                continue
            job_rules[row] = dict(nids=rule.nids, gids=rule.gids,
                                  ex=rule.exclude_nids)
            for gid in rule.gids:
                group_jobs.setdefault(gid, set()).add(row)
        self.builder.job_rules = job_rules
        self.builder.group_jobs = group_jobs
        self._row_phase = st["row_phase"]
        self._row_dispatch = st["row_dispatch"]
        rd = st["rd"]
        self._rd_flags = rd["flags"]
        self._rd_payload = rd["payload"]
        self._rd_suffix = rd["suffix"]
        self._rd_bentry = rd["bentry"]
        self._rd_job = rd["job"]
        # trace-plane and smear-plane row caches are NOT checkpointed
        # (pre-trace / pre-jitter checkpoints must keep restoring):
        # re-derive them from the restored rows.  The jitter registry
        # counters come from the restored jobs either way — they gate
        # the smear arm and cost nothing when zero.
        self._jitter_jobs = 0
        self._max_jitter_seen = 0
        for job in self.jobs.values():
            jw = int(getattr(job, "jitter", 0) or 0)
            if jw > 0:
                self._jitter_jobs += 1
                if jw > self._max_jitter_seen:
                    self._max_jitter_seen = jw
        self._rd_jitter = np.zeros(len(self._rd_flags), np.int32)
        self._rd_sbase = np.zeros(len(self._rd_flags), np.uint64)
        if self.trace_shift >= 0 or self._jitter_jobs:
            self._rd_tbase = np.zeros(len(self._rd_flags), np.uint64)
            self._rd_tflag = np.zeros(len(self._rd_flags), bool)
            for row, gj in enumerate(self._rd_job):
                if gj is None or not (self._rd_flags[row] & 1):
                    continue
                self._rd_tbase[row] = np.uint64(
                    self._trace.fnv_partial(gj[1] + "|"))
                self._rd_sbase[row] = np.uint64(
                    self._trace.fnv_partial(gj[0] + "/" + gj[1] + "|"))
                job = self.jobs.get((gj[0], gj[1]))
                self._rd_tflag[row] = bool(job and
                                           getattr(job, "trace", False))
                self._rd_jitter[row] = int(
                    getattr(job, "jitter", 0) or 0) if job else 0
        self._col_node = st["col_node"]
        self._col_live = st["col_live"]
        m = st["mirrors"]
        self._procs = m["procs"]
        self._orders = m["orders"]
        self._alone_live = m["alone"]
        self._excl_cnt = m["excl"]
        self._load_sum = m["load"]
        # workflow DAG state: the completion mirror + device vectors
        # land from the checkpoint; the registries (dep jobs, reverse
        # index, gated set, row set) are DERIVED from the restored jobs
        # exactly as _apply_job builds them, and the in-flight counters
        # from the restored procs mirror
        dep = st["dep"]
        self._dep_latest = dep["latest"]
        self._dep_jobs = {}
        self._dep_rdeps = {}
        self._dep_gated = {}
        self._dep_rows = set()
        for k, job in self.jobs.items():
            spec = job.deps
            if spec is None or not spec.on:
                continue
            self._dep_jobs[k] = spec
            for u in spec.on:
                self._dep_rdeps.setdefault((k[0], u), set()).add(k)
            if spec.max_in_flight > 0:
                self._dep_gated[k] = spec.max_in_flight
            for rid in self.rows.rules_of(*k):
                row = self.rows.by_cmd.get((k[0], k[1], rid))
                if row is not None:
                    self._dep_rows.add(row)
        infl: Dict[Tuple[str, str], int] = {}
        if self._dep_gated:
            for pk in self._procs:
                t = self._parse_proc(pk)
                if t is not None and (t[1], t[2]) in self._dep_gated:
                    infl[(t[1], t[2])] = infl.get((t[1], t[2]), 0) + 1
        self._dep_inflight = infl
        self._dep_blocked = set()
        if self._dep_supported and "succ" in dep:
            self.planner.set_dep_state(dep["succ"], dep["fail"],
                                       dep["last_fire"], dep["block"])
            # the saved block array may carry saturated rows; the host
            # gate recomputes from scratch — force a full re-scatter so
            # device and host agree from the first flush
            for jk, mif in self._dep_gated.items():
                blocked = self._dep_inflight.get(jk, 0) >= mif
                if blocked:
                    self._dep_blocked.add(jk)
                for rid in self.rows.rules_of(*jk):
                    row = self.rows.by_cmd.get((jk[0], jk[1], rid))
                    if row is not None:
                        self._dep_block_updates[row] = blocked
        if self._dep_rows and self._dep_supported:
            self.planner.set_dep_enabled(True)
        # tenancy: registry + id space + row map + counters land from
        # the checkpoint; quotas re-scatter into the planner's bucket
        # columns, then the DYNAMIC token state overrides the full-
        # bucket reset set_tenant_quota performs.  Absent field = a
        # pre-tenancy checkpoint (empty registry) — still restorable.
        ten = st.get("tenant")
        if ten:
            self._tenants = {}
            for n, qd in ten["quotas"].items():
                try:
                    q = TenantQuota(**qd)
                    q.validate()
                    self._tenants[n] = q
                except Exception:  # noqa: BLE001 — skip a bad record
                    pass
            self._tenant_ids = dict(ten["ids"])
            self._tid_name = list(ten["names"])
            self._row_tenant = np.asarray(ten["row_tenant"], np.int32)
            self._tenant_counters = {n: dict(c)
                                     for n, c in ten["counters"].items()}
            if self._tenant_supported:
                self.planner.set_row_tenants(
                    np.arange(self.planner.J, dtype=np.int32),
                    self._row_tenant)
                any_limited = False
                for n, q in self._tenants.items():
                    tid = self._tenant_ids.get(n, 0)
                    if tid:
                        self.planner.set_tenant_quota(
                            tid, q.rate if q.limited else 0.0, q.burst,
                            q.weight)
                        any_limited |= q.limited
                tok = (ten.get("state") or {}).get("tokens")
                if tok is not None:
                    self.planner.set_tenant_state(tok)
                if any_limited or self._tenants:
                    self.planner.set_tenants_enabled(True)
            self._acct_tid = {k: dict(v) for k, v in
                              (ten.get("acct_tid") or {}).items()}
            self._rebuild_tenant_excl()
        # device state: table + eligibility + job meta land whole; node
        # capacities as at a cold load's end (reconcile_capacity
        # rewrites load/rem_cap from the mirrors every leading step).
        # Mesh planners install through their setters so every array is
        # re-pinned to the canonical sharding (set_table already is the
        # polymorphic re-pin point for both planner kinds).
        self.planner.set_table(table)
        if hasattr(self.planner, "set_eligibility"):
            self.planner.set_eligibility(elig)
            self.planner.set_job_meta_full(excl, cost)
        else:
            self.planner.elig = elig
            self.planner.exclusive = excl
            self.planner.cost = cost
        if self.universe.index:
            cols = np.asarray(list(self.universe.index.values()),
                              np.int32)
            caps = np.asarray(
                [self.node_caps.get(n, self.default_node_cap)
                 for n in self.universe.index], np.int64)
            cols, caps = self._pad_pow2(cols, caps)
            self.planner.set_node_capacity(cols, caps)
        # fold the delta chain through the SAME handlers that applied
        # the events live (validated upfront: shape-complete tuples,
        # contiguous seqs, matching nonce) — base + fold reproduces the
        # saver's exact host state; the device flush pushes the folded
        # rows so the first window plans against the chain tip, not the
        # base.  Phase anchors are PREFETCHED in one get_many and the
        # fold runs read-only against them: the live applier wrote
        # every anchor synchronously before its save's barrier, so the
        # store's current values are authoritative — per-rule anchor
        # RPCs would serialize thousands of round trips into the
        # takeover (measured: they dominated the 50k warm path), and a
        # replayed phase delete could destroy an anchor a later chain
        # event re-created.
        n_ev = 0
        if deltas:
            pf_keys: List[str] = []
            seen_pk: Set[str] = set()
            for d in deltas:
                for sid, typ, key, value in d["events"]:
                    if sid != "jobs" or typ == DELETE:
                        continue
                    rest = key[len(self.ks.cmd):]
                    if "/" not in rest:
                        continue
                    group, job_id = rest.split("/", 1)
                    try:
                        doc = json.loads(value)
                    except ValueError:
                        continue
                    for r in (doc.get("rules") or []):
                        rid = r.get("id", "") if isinstance(r, dict) \
                            else ""
                        pk = self.ks.phase_key(group, job_id, rid)
                        if pk not in seen_pk:
                            seen_pk.add(pk)
                            pf_keys.append(pk)
            prefetch: Dict[str, str] = {}
            if pf_keys:
                for pk, kv in zip(pf_keys, self.store.get_many(pf_keys)):
                    if kv is not None:
                        prefetch[pk] = kv.value
            self._phase_prefetch = prefetch
            self._phase_puts = []
            self._fold_ro = True
            try:
                for d in deltas:
                    for sid, typ, key, value in d["events"]:
                        self._apply_ev(sid, typ, key, value)
                    n_ev += len(d["events"])
            finally:
                self._phase_prefetch = None
                self._phase_puts = None
                self._fold_ro = False
            self._flush_device()
        # a restored chain stays extendable: later delta saves continue
        # from its tip (events recorded from the replayed watch tail on)
        if st.get("chain"):
            from ..checkpoint.sched_ckpt import delta_path
            nbytes = 0
            for d in deltas:
                try:
                    nbytes += os.path.getsize(
                        delta_path(path, d["seq"]))
                except OSError:
                    pass
            self._ckpt_chain = {"nonce": st["chain"],
                                "seq": len(deltas), "rev": rev,
                                "bytes": nbytes, "path": path}
        # own-publish reservations between the checkpoint's barrier and
        # the previous leader's death aren't in the mirrors (the orders
        # watch is delete-only): kick anti-entropy from post-restore
        # ground truth immediately — same bounded over-commit window as
        # any fresh leadership
        self._mirror_resync_at = 0.0
        ms = (time.perf_counter() - t0) * 1e3
        self._ckpt_stats["restored"] = 1
        self._ckpt_stats["restore_ms"] = round(ms, 3)
        self._ckpt_stats["last_rev"] = (max(rev) if isinstance(rev, list)
                                        else rev)
        log.infof("scheduler checkpoint RESTORED: rev %s, %d jobs, "
                  "%d deltas folded (%d events), %.0f ms (watch delta "
                  "replays from rev+1)",
                  rev, len(self.jobs), len(deltas), n_ev, ms)
        return True

    def _maybe_checkpoint(self):
        """Periodic / operator-requested checkpoint saves (step
        thread; leaders and warm standbys both run it — every instance
        with a checkpoint_dir keeps its own restore point fresh)."""
        due = self.clock() >= self._ckpt_next_at
        req = self._ckpt_requested
        if not (due or req):
            return
        self._ckpt_requested = False
        if self.checkpoint_interval_s:
            self._ckpt_next_at = self.clock() + self.checkpoint_interval_s
        if not self.checkpoint_dir:
            if req:
                log.warnf("checkpoint requested but no checkpoint_dir "
                          "configured on %s; ignoring", self.node_id)
            return
        try:
            # periodic saves serialize in the background (the step
            # thread pays barrier + capture only); operator-REQUESTED
            # saves stay synchronous — the done-key ack must mean the
            # bytes are on disk
            out = self.checkpoint_save(wait=bool(req))
            # the save ran inline on the step thread: a leader's lease
            # got no keepalive for its whole duration — refresh it NOW
            # rather than a step later, and tell the operator when the
            # save is eating a dangerous share of the ttl (at that
            # point the checkpoint cadence belongs on a standby)
            if self._leader_lease is not None:
                if not self.store.keepalive(self._leader_lease):
                    self._leader_lease = None
            if out["ms"] > self.lease_ttl * 500:    # ms vs s: ttl/2
                log.warnf("checkpoint save took %.0f ms — more than "
                          "half of lease_ttl (%.0fs); run the "
                          "checkpoint cadence on a standby or raise "
                          "the ttl", out["ms"], self.lease_ttl)
            if req:
                # ack the operator trigger so `cronsun-ctl checkpoint`
                # has something observable beyond the metrics gauges
                self.store.put(
                    self.ks.ckpt_done_key(self.node_id),
                    json.dumps({"rev": out["rev"],
                                "ms": round(out["ms"], 1),
                                "path": out["path"]},
                               separators=(",", ":")))
        except Exception as e:  # noqa: BLE001 — a failed save must
            # never take down the scheduler loop
            self._ckpt_stats["save_errors_total"] += 1
            log.errorf("scheduler checkpoint save failed: %s", e)

    @staticmethod
    def _pad_pow2(rows: np.ndarray, *arrays):
        """Pad a scatter batch to the next power-of-two length by
        REPEATING the last (row, value) pair — duplicate indices with
        identical values are semantically inert, and the padded shapes
        bound the number of XLA executables to ~log2(J) variants.
        Without this every distinct update size compiles its own scatter
        (measured: 29 s of a 35 s cold load was backend_compile)."""
        n = len(rows)
        want = 1 << max(0, (n - 1).bit_length())
        if want == n:
            return (rows, *arrays)
        pad = want - n
        out = [np.concatenate([rows, np.repeat(rows[-1:], pad)])]
        for a in arrays:
            if isinstance(a, list):
                out.append(a + [a[-1]] * pad)
            else:
                out.append(np.concatenate(
                    [a, np.repeat(a[-1:], pad, axis=0)]))
        return tuple(out)

    def _flush_device(self):
        if self._tenant_row_updates:
            if self._tenant_supported:
                rows = np.fromiter(self._tenant_row_updates, np.int32,
                                   len(self._tenant_row_updates))
                tids = np.array([self._tenant_row_updates[int(r)]
                                 for r in rows], np.int32)
                # host-only snapshot update (the device tenant column
                # rides the normal table scatters below); marks the
                # admission permutation dirty for the next dispatch
                self.planner.set_row_tenants(rows, tids)
            self._tenant_row_updates.clear()
        if self._table_updates:
            rows = np.array(sorted(self._table_updates), dtype=np.int32)
            vals = [self._table_updates[int(r)] for r in rows]
            rows, vals = self._pad_pow2(rows, vals)
            self.planner.update_table_rows(rows, vals)
            self._table_updates.clear()
        dirty, mat = self.builder.dirty_rows()
        if len(dirty):
            dirty, mat = self._pad_pow2(dirty, mat)
            self.planner.set_eligibility_rows(dirty, mat)
        if self._meta_updates:
            rows = np.array(sorted(self._meta_updates), dtype=np.int32)
            excl = np.array([self._meta_updates[int(r)][0] for r in rows])
            cost = np.array([self._meta_updates[int(r)][1] for r in rows],
                            dtype=np.float32)
            rows, excl, cost = self._pad_pow2(rows, excl, cost)
            self.planner.set_job_meta(rows, excl, cost)
            self._meta_updates.clear()
        # workflow DAG scatters, strictly ordered: row RESETS first (a
        # released row's clean slate must not be re-poisoned by a stale
        # queued fold), then the monotone epoch folds, then the
        # max_in_flight gate
        self._dep_refresh_blocks()
        if self._dep_resets:
            rows = np.array(sorted(self._dep_resets), dtype=np.int32)
            anchors = np.array([self._dep_resets[int(r)] for r in rows],
                               dtype=np.int32)
            rows, anchors = self._pad_pow2(rows, anchors)
            self.planner.reset_dep_rows(rows, anchors)
            self._dep_resets.clear()
        if self._dep_epoch_updates:
            rows = np.array(sorted(self._dep_epoch_updates),
                            dtype=np.int32)
            succ = np.array([self._dep_epoch_updates[int(r)][0]
                             for r in rows], dtype=np.int32)
            fail = np.array([self._dep_epoch_updates[int(r)][1]
                             for r in rows], dtype=np.int32)
            rows, succ, fail = self._pad_pow2(rows, succ, fail)
            self.planner.set_dep_epochs(rows, succ, fail)
            self._dep_epoch_updates.clear()
        if self._dep_block_updates:
            rows = np.array(sorted(self._dep_block_updates),
                            dtype=np.int32)
            vals = np.array([self._dep_block_updates[int(r)]
                             for r in rows])
            rows, vals = self._pad_pow2(rows, vals)
            self.planner.set_dep_block(rows, vals)
            self._dep_block_updates.clear()

    def _start_warm(self):
        """Background compile of the plan executables this process will
        need under pressure: the windowed plan (a standby's takeover
        must not pay XLA compilation as dispatch outage — r4 measured
        34 s) and the single-second escalation bucket a cron-herd
        minute boundary requests (r5 measured ~20 s p99 inside the
        first burst step).  Runs once; leaders warm while leading, the
        step loop never blocks on it."""
        if self._warmed or self._warm_thread is not None:
            return
        if not (hasattr(self.planner, "warm_window")
                and hasattr(self.planner, "warm_escalation")):
            self._warmed = True
            return

        def run():
            try:
                now = int(self.clock())
                self.planner.warm_window(now + 1, max(1, self.window_s))
                k = self.planner.warm_escalation(now + 1)
                log.infof("plan executables warmed (window + "
                          "escalation bucket %d)", k)
            except Exception as e:  # noqa: BLE001 — degraded, not down
                log.warnf("background plan warm failed: %s", e)
            finally:
                self._warmed = True
                self._warm_thread = None
        self._warm_thread = threading.Thread(
            target=run, daemon=True, name="sched-plan-warm")
        self._warm_thread.start()

    # ---- capacity reconciliation ----------------------------------------

    def reconcile_capacity(self):
        """Refresh per-node capacity/load on device from the incremental
        counters the mirrors maintain: proc registry (running) PLUS
        still-outstanding dispatch orders (written but not yet picked
        up / started — agents keep the order key until the proc key
        exists), so a node at capacity can't be over-committed during the
        dispatch->spawn gap.  Crash-safe by construction: procs of dead
        nodes expire with their lease (reference proc.go:21-35 ProcTtl),
        orders with the dispatch lease — both expirations arrive as watch
        DELETEs that decrement the counters.  O(nodes) per step; the
        old O(outstanding) re-iteration was 548 ms/step at 1M (r4)."""
        running_excl = self._excl_cnt
        running_load = self._load_sum
        # partitioned plane: fold the other partitions' published
        # demand into this view — their reservations/procs are
        # invisible to this partition's watch slice, but they consume
        # the same nodes.  Bounded staleness (one exchange period);
        # the over-commit inside it is absorbed by the agents'
        # Parallels gate, exactly like the order->proc gap.
        self._fold_foreign_demand()
        fex = self._foreign_excl
        fld = self._foreign_load
        cols, caps = [], []
        avail = 0
        loads = np.zeros(self.planner.N, np.float32)
        for node_id, col in self.universe.index.items():
            cap = self.node_caps.get(node_id, self.default_node_cap)
            cols.append(col)
            c = max(0, cap - running_excl.get(node_id, 0)
                    - fex.get(node_id, 0))
            caps.append(c)
            avail += c
            loads[col] = running_load.get(node_id, 0.0) \
                + fld.get(node_id, 0.0)
        # the fleet's remaining exclusive-slot budget — the fair-share
        # build clamps tenants to weighted max-min shares of this when
        # a second's aggregate demand exceeds it
        self._agg_excl_avail = avail if cols else float("inf")
        if cols:
            pc, pk = self._pad_pow2(np.asarray(cols, np.int32),
                                    np.asarray(caps, np.int64))
            self.planner.set_node_capacity(pc, pk)
        self.planner.set_load(loads)

    # ---- planning + dispatch --------------------------------------------

    def step(self, now: Optional[int] = None) -> int:
        """One full cycle; returns the number of dispatches submitted
        (pipelined mode: dispatches whose build COMPLETED since the
        last call — the step hands its own window to the build stage
        and returns without waiting for it).

        If planning fell behind wall-clock (leader failover, a recompile
        stall), the missed seconds are planned late rather than skipped —
        the reference fires late too, never never (cron.go:212-215) — up to
        ``max_catchup_s`` back; anything older is dropped and counted in
        ``stats['skipped_seconds']``.

        The pipelined step (default off-mesh) is a TWO-STAGE pipeline:

            step thread:   drain | reconcile | flush | dispatch N+1 | hand off N
            build worker:       gather N | build N | submit N -> publisher
            publisher:               put_many N (sharded lanes) | advance HWM

        The device computes window N+1 WHILE the worker strings and
        ships window N, so the step's latency tends to max(stage) rather
        than the sum of every span, and a minute-boundary herd second no
        longer stacks device latency on top of the 700 ms order build.
        Ordering invariants survive by construction: one FIFO worker
        feeds the publisher's FIFO (seconds never reorder), the HWM
        still only advances when the overlapped window actually LANDS
        (the publisher owns write-then-mark), and a hole still rewinds
        the cursor — a window that dies before submit records the hole
        itself.  When the publisher falls behind, the builder's depth
        cap blocks the step (``pipeline_stall_*``), stalling the next
        plan instead of reordering.  Job/capacity updates take effect
        one window later than they land — the same latency class as the
        planning horizon itself.  Mesh planners keep the serial path
        (their plan is a synchronized collective).
        """
        now = int(now if now is not None else self.clock())
        t_step = time.perf_counter()
        spans = {}

        def span(name, since):
            t = time.perf_counter()
            spans[name] = (t - since) * 1e3
            return t
        # WARM STANDBY: watches drain and mirrors/device state stay
        # current whether or not we lead — a standby that only started
        # syncing after winning the lease would pay the full cold load
        # (minutes at 1M jobs) as dispatch outage; a warm one takes over
        # within one step (VERDICT r3 #3)
        self.drain_watches()
        t = span("drain", t_step)
        # build-stage hand-backs: completed-window accounting (mirror
        # adds + fire counts) and overflow-replan dispatch requests (the
        # device dispatch stays on this thread)
        n_done = self._drain_build_acct()
        self._drain_replan_reqs()
        self._drain_tenant_q()
        self._maybe_antientropy_bg()
        self._maybe_checkpoint()
        led_before = self.is_leader
        if not self.try_lead():
            self._next_epoch = None
            self._pending_plan = None
            self._builder.flush()
            n_done += self._drain_build_acct()
            self._drain_replan_reqs()
            self._drain_replans()
            self._flush_device()
            self._start_warm()   # standby warms in the background
            # standbys still publish (throttled): "is my failover target
            # alive" is an operator question too
            self.metrics.maybe_publish()
            if self._mesh_metrics is not None:
                self._mesh_metrics.maybe_publish()
            if self._tenants:
                self._tenant_metrics.maybe_publish()
            return 0
        if self.stats["steps_total"]:
            # escalation sizes warm while leading — but only after the
            # first window is out the door: on a small host the warm
            # compiles race the first plan's own compile for the same
            # cores and stretch the cold start past the catch-up budget
            self._start_warm()
        if not led_before:
            # fresh leadership: the delete-only orders watch never
            # echoed the PREVIOUS leader's publishes, so kick an
            # anti-entropy listing now.  Until it installs (a step or
            # two), outstanding foreign orders may be under-counted —
            # bounded over-commit the agent-side Parallels gate absorbs
            # (skip-not-queue, reference job.go:165-187); exactly-once
            # is fence-guaranteed regardless.  A listing already in
            # flight may predate the takeover: flag a re-kick so the
            # NEXT listing starts from post-takeover ground truth.
            self._mirror_resync_at = 0.0
            if self._ae_thread is not None:
                self._ae_rekick = True
            self._maybe_antientropy_bg()
        if not led_before:
            # herd smearing: the spill ring is planning-derived state
            # and never checkpointed — a fresh leadership (cold or warm)
            # re-derives the in-flight deferred fires from a bounded
            # lookback once the cursor is known (below)
            self._smear_recovered = False
        self.reconcile_capacity()
        if self.partitions > 1:
            # leaders announce their per-node demand so every OTHER
            # partition's next reconcile subtracts it (O(active nodes)
            # JSON once per exchange period, not per step)
            self._publish_acct()
        t = span("reconcile", t)
        self._flush_device()
        t = span("flush", t)
        start = self._next_epoch
        fresh_cursor = start is None
        had_hwm = False
        if start is None:
            # fresh leadership: resume from the persisted high-water mark so
            # seconds the previous leader already dispatched aren't planned
            # twice (Common jobs have no per-second fence)
            start = now + 1
            hwm_kv = self.store.get(self._hwm_key)
            had_hwm = hwm_kv is not None
            if hwm_kv is not None:
                try:
                    # never ahead of a sane bound; the catch-up clamp below
                    # bounds how far back we re-plan
                    start = min(int(hwm_kv.value), start + 3600)
                except ValueError:
                    pass
        fe = self.publisher.take_failed_epoch()
        if fe is not None and self._smear_ring:
            # spill entries emitted by windows at/after the hole are
            # unconfirmed: clear their marks so the rebuild (or the
            # next window's late flush) re-emits them — idempotent
            # downstream (bundle re-read is the same superset; legacy/
            # broadcast keys are per-fire puts behind fences).  Locked:
            # in pipelined mode the WindowBuilder inserts/prunes ring
            # entries concurrently with this step-thread walk.
            with self._smear_lock:
                for bucket in self._smear_ring.values():
                    for g in bucket.values():
                        if g[2] is not None and g[2] >= fe:
                            g[2] = None
        if fe is not None and fe < start:
            # a window's publish failed after retries: the HWM stopped
            # there, and so must the in-memory cursor — rewind and
            # re-plan from the hole (late, never lost; re-published
            # duplicates are absorbed by fences/broadcast dedup)
            log.warnf("publish hole at epoch %d; rewinding plan cursor "
                      "from %d", fe, start)
            start = fe
        if start < now + 1 - self.max_catchup_s:
            self.stats["skipped_seconds"] += (now + 1 - self.max_catchup_s
                                              - start)
            start = now + 1 - self.max_catchup_s
            # if the clamp just moved the cursor PAST an outstanding
            # publish hole, that hole's seconds are now skipped-and-
            # counted, not re-planned — clear it, or no future window
            # ever satisfies covers_from <= failed_epoch and the
            # publisher abandons every window forever (a silent
            # permanent dispatch stall; ADVICE r5 high)
            if self.publisher.clear_failed_epoch_below(start):
                log.warnf("publish hole aged past max_catchup_s; its "
                          "seconds were skipped and the hole cleared")
        if self._jitter_jobs and not self._smear_recovered:
            self._smear_recovered = True
            if fresh_cursor and had_hwm:
                # a previous leader dispatched up to the HWM: re-derive
                # whatever it smeared past that point.  A fresh cluster
                # (no HWM) has no in-flight spill — and must not invent
                # fires for seconds older than its own birth.
                self._smear_recover(start)
        window = max(1, self.window_s)
        if self.pipelined:
            n_dispatch = n_done + self._step_pipelined(start, window,
                                                       spans)
        else:
            n_dispatch = n_done + self._step_serial(start, window, spans,
                                                    span)
        # full-cycle latency distribution: everything a real tick pays
        # on the STEP thread (watch drain + reconcile + device flush +
        # plan dispatch + build or hand-off + stall/backpressure)
        spans["total"] = (time.perf_counter() - t_step) * 1e3
        self._step_spans = spans
        self._step_ms.add(spans["total"])
        self._pl_step_ms += spans["total"]
        for k, v in spans.items():
            self._span_ring(k).add(v)
        self.stats["steps_total"] += 1
        self._drain_tenant_q()
        self.metrics.maybe_publish()
        if self._mesh_metrics is not None:
            self._mesh_metrics.maybe_publish()
        if self._tenants:
            self._tenant_metrics.maybe_publish()
        return n_dispatch

    def _step_serial(self, start: int, window: int, spans: dict,
                     span) -> int:
        """The serial plan->build->submit body (mesh planners, and the
        ``pipelined=False`` baseline/rollback switch)."""
        t_plan = time.perf_counter()
        if self._pending_plan is not None and self._pending_plan[0] == start:
            plans = self.planner.gather_window(
                self._resolve_handle(self._pending_plan[1]))
        else:
            plans = self.planner.plan_window(start, window)
        self._pending_plan = None
        self._tick_ms.add((time.perf_counter() - t_plan) * 1e3)
        t = span("plan", t_plan)
        self._next_epoch = start + window
        # prefetch: next window's plan on device while THIS window's
        # orders are built and shipped (duck-typed: the mesh planners'
        # collective plan is a synchronized call and stays one)
        if hasattr(self.planner, "plan_window_async"):
            self._pending_plan = (
                self._next_epoch,
                self.planner.plan_window_async(self._next_epoch, window))
        lease = self.store.grant(self.dispatch_ttl)
        seconds: List[Tuple[int, list]] = []
        excl_acct: List[Tuple[str, str, list]] = []
        wpend: Dict[int, int] = {}    # this window's admitted-excl
        n_dispatch = 0
        # matured ASYNC overflow replans from the previous step publish
        # first (they are the oldest epochs); their full fire sets were
        # computed while the last window built and shipped
        build_list: List[Tuple[object, bool]] = []
        if self._pending_replans:
            pending, self._pending_replans = self._pending_replans, []
            for _ep, handle, _fires in pending:
                # _resolve_handle: the replan may have been dispatched
                # as a Future by the PIPELINED path before a toggle to
                # the serial one (bench baseline / rollback switch)
                build_list.append(
                    (self.planner.gather_window(
                        self._resolve_handle(handle))[0], False))
        build_list += [(p, True) for p in plans]
        if self._smear_ring:
            self._smear_begin(
                min([start] + [p.epoch_s for p, _ in build_list]),
                seconds, excl_acct)
        for plan, may_replan in build_list:
            if plan.overflow:
                # never drop a fire: re-plan this second with a bucket
                # sized for the TRUE fire count — overflow becomes
                # latency, not loss (the reference fires late, never
                # never, cron.go:212-215).  The replan runs ASYNC on
                # the device while this window's orders build and ship
                # (one step of added latency for the over-bucket tail;
                # a synchronous replan was the last device wait inside
                # burst steps — measured seconds of p99 at cron-herd
                # scale); the truncated head publishes NOW and its
                # re-dispatch next step is deduplicated downstream
                # (fences / broadcast dedup), exactly as the sync
                # replan's head re-fire was.  Mesh planners (no async
                # surface) keep the in-step replan.
                if may_replan and hasattr(self.planner,
                                          "plan_window_async"):
                    self._queue_replan(plan)
                elif may_replan:
                    plan = self._replan_overflow(plan)
                else:
                    # a replan STILL over its escalated bucket: only
                    # possible past the structural cap J
                    self.stats["overflow_drops"] += plan.overflow
                    log.errorf("%d fires over the escalated bucket at "
                               "t=%d — dropped", plan.overflow,
                               plan.epoch_s)
            n_dispatch += self._build_plan_orders(plan, seconds,
                                                  excl_acct,
                                                  pending_excl=wpend)
        t = span("build", t)
        # hand the window to the async publisher: oldest second first,
        # HWM advanced after each second lands (the publisher owns the
        # write-then-mark ordering: a crash in between re-plans the
        # unpublished tail — a rare double fire beats silently missing
        # it; the mark itself is a monotone CAS so a deposed leader
        # can't regress the new one's progress)
        wait_s = self.publisher.submit(seconds, lease, self._next_epoch,
                                       covers_from=start)
        if self.sync_publish:
            self.publisher.flush()
        # mirror own publishes locally (the orders watch is delete-only:
        # our puts are not echoed back at us)
        for key, node, jobs in excl_acct:
            self._acct_add_order(key, node, jobs)
        spans["publish"] = wait_s * 1e3   # backpressure only; the wire
                                          # time is publish_window_ms in
                                          # the metrics snapshot
        self.stats["dispatches_total"] += n_dispatch
        return n_dispatch

    def _step_pipelined(self, start: int, window: int,
                        spans: dict) -> int:
        """The pipelined body: dispatch this window's plan (usually
        already in flight from the previous step — the double buffer),
        dispatch the NEXT window's plan, and hand the current handle to
        the build worker.  The gather, the order build and the publisher
        submit all run OFF this thread; the only blocking here is the
        builder's depth cap (``stall`` span) when the plane is behind."""
        t0 = time.perf_counter()
        if self._pending_plan is not None and \
                self._pending_plan[0] == start:
            handle = self._pending_plan[1]
        else:
            # cold start / hole rewind / clamp moved the cursor: the
            # prefetched plan covers the wrong seconds — drop it and
            # dispatch the right one (the wasted device work is the
            # rewind's price, not the steady state's)
            handle = self._dispatch_plan(start, window)
        self._pending_plan = None
        self._next_epoch = start + window
        self._pending_plan = (
            self._next_epoch,
            self._dispatch_plan(self._next_epoch, window))
        spans["dispatch"] = (time.perf_counter() - t0) * 1e3
        lease = self.store.grant(self.dispatch_ttl)
        # matured replan handles ride in FRONT of the window (oldest
        # epochs first), exactly as on the serial path
        replans, self._pending_replans = self._pending_replans, []
        stall_s = self._builder.submit(_BuildItem(
            replans=replans, handle=handle, lease=lease,
            hwm=self._next_epoch, covers_from=start))
        spans["stall"] = stall_s * 1e3
        n_dispatch = 0
        if self.sync_publish:
            # in-process stores: callers assert store contents right
            # after step() — run the pipeline to completion (the same
            # code path, without the overlap)
            self._builder.flush()
            self.publisher.flush()
            n_dispatch = self._drain_build_acct()
            self._drain_replan_reqs()
        return n_dispatch

    # ---- pipeline plan-dispatch stage ------------------------------------

    def _dispatch_plan(self, epoch_s: int, window_s: int, sla=None):
        """Submit a device plan dispatch to the single dispatch thread;
        returns a Future resolving to the plan handle.  Keeps the total
        dispatch order (windows, then any replans, in submission order)
        while moving the dispatch cost — which the CPU backend partly
        executes INLINE — off the step thread.  The planner state the
        dispatch reads may be one flush older than the step that
        requested it: the same one-window staleness the prefetched
        ``_pending_plan`` already had."""
        def run():
            t0 = time.perf_counter()
            try:
                return self.planner.plan_window_async(epoch_s, window_s,
                                                      sla_bucket=sla)
            finally:
                self._dispatch_ms.append(
                    (time.perf_counter() - t0) * 1e3)
        return self._dispatch_pool.submit(run)

    @staticmethod
    def _resolve_handle(handle):
        """A plan handle, or the Future of one (pipelined dispatch)."""
        return handle.result() if hasattr(handle, "result") else handle

    # ---- pipeline build stage (runs on the WindowBuilder worker) ---------

    def _build_window(self, item: _BuildItem):
        """Gather + build + submit ONE window — the body of the
        pipeline's build stage, invoked on the WindowBuilder thread
        while the device already computes the next window.

        Reads of the row-dispatch arrays / alone mirror may race a
        concurrent watch drain on the step thread; every such race is
        the same one-window staleness the device table itself has
        (plans were dispatched a window ago), and the flags-last write
        discipline keeps rows atomic.  Mirror/counter WRITES never
        happen here: the accounting rides ``_acct_q`` back to the step
        thread, as do overflow-replan requests (device dispatches stay
        single-threaded)."""
        t0 = time.perf_counter()
        acct = {"fires": 0, "drops": 0, "excl": [], "gather_ms": 0.0,
                "build_ms": 0.0, "submit_ms": 0.0, "busy_ms": 0.0}
        try:
            t = time.perf_counter()
            build_list: List[Tuple[object, bool]] = []
            for _ep, handle, _fires in item.replans:
                build_list.append(
                    (self.planner.gather_window(
                        self._resolve_handle(handle))[0], False))
            build_list += [(p, True) for p in self.planner.gather_window(
                self._resolve_handle(item.handle))]
            acct["gather_ms"] = (time.perf_counter() - t) * 1e3
            t = time.perf_counter()
            seconds: List[Tuple[int, list]] = []
            wpend: Dict[int, int] = {}
            if self._smear_ring:
                self._smear_begin(
                    min([item.covers_from]
                        + [p.epoch_s for p, _ in build_list]),
                    seconds, acct["excl"])
            for plan, may_replan in build_list:
                if plan.overflow:
                    if may_replan:
                        # escalated replans are REQUESTED here and
                        # dispatched by the step thread next cycle —
                        # late, never lost, one step of extra latency
                        # for the over-bucket tail
                        self._replan_reqs.append(
                            (plan.epoch_s, plan.total_fired,
                             plan.overflow))
                    else:
                        acct["drops"] += plan.overflow
                        log.errorf("%d fires over the escalated bucket "
                                   "at t=%d — dropped", plan.overflow,
                                   plan.epoch_s)
                acct["fires"] += self._build_plan_orders(
                    plan, seconds, acct["excl"], pending_excl=wpend)
            acct["build_ms"] = (time.perf_counter() - t) * 1e3
            t = time.perf_counter()
            # publisher backpressure lands HERE, which fills this
            # stage's depth cap, which stalls the step's next plan —
            # backpressure propagates without ever reordering seconds
            self.publisher.submit(seconds, item.lease, item.hwm,
                                  covers_from=item.covers_from)
            acct["submit_ms"] = (time.perf_counter() - t) * 1e3
        except Exception as e:  # noqa: BLE001 — the window never
            # reached the publisher: record a hole at its oldest second
            # so the next step REWINDS and re-plans it (late, never
            # lost — same contract as a failed publish)
            hole = min([item.covers_from]
                       + [ep for ep, _h, _f in item.replans])
            self.publisher.record_hole(hole)
            log.errorf("pipelined window build failed (hole at %d): %s",
                       hole, e)
        finally:
            acct["busy_ms"] = (time.perf_counter() - t0) * 1e3
            self._acct_q.append(acct)

    def _drain_build_acct(self) -> int:
        """Apply completed-window accounting handed back by the build
        worker (STEP thread only: the mirrors/counters have a single
        writer).  Returns the fires those windows built."""
        n = 0
        while self._acct_q:
            a = self._acct_q.popleft()
            for key, node, jobs in a["excl"]:
                self._acct_add_order(key, node, jobs)
            n += a["fires"]
            self.stats["dispatches_total"] += a["fires"]
            if a["drops"]:
                self.stats["overflow_drops"] += a["drops"]
            self._pl_offstep_ms += a["busy_ms"]
            # pipelined mode: tick_* tracks the RESIDUAL device wait the
            # gather paid (the dispatch itself is async) — the honest
            # "how long did the step stage actually wait on the device"
            self._tick_ms.add(a["gather_ms"])
            for k in ("gather_ms", "build_ms", "submit_ms"):
                self._span_ring(k[:-3]).add(a[k])
        # the dispatch thread's work (the CPU backend executes much of
        # the plan INLINE at dispatch) is serial-path step time that now
        # runs off the step thread: count it as overlapped, under the
        # same "plan" span name the serial path reports it in
        while self._dispatch_ms:
            dt = self._dispatch_ms.popleft()
            self._pl_offstep_ms += dt
            self._span_ring("plan").add(dt)
        return n

    def _drain_replan_reqs(self):
        """Dispatch escalated overflow replans the build worker
        requested (STEP thread: device dispatch is single-threaded).
        The handles mature into the NEXT window's build item."""
        while self._replan_reqs:
            ep, total_fired, overflow = self._replan_reqs.popleft()
            want = self._escalation_want(total_fired)
            self.stats["overflow_late_fires"] += overflow
            log.warnf("%d fires over the bucket SLA at t=%d; "
                      "re-planning async with bucket %d (late, never "
                      "lost)", overflow, ep, want)
            self._pending_replans.append(
                (ep, self._dispatch_plan(ep, 1, sla=want), overflow))

    def _span_ring(self, name: str):
        ring = self._span_hist.get(name)
        if ring is None:
            from ..metrics import LatencyRing
            ring = self._span_hist[name] = LatencyRing()
        return ring

    def reset_latency_stats(self):
        """Drop the accumulated latency distributions and overlap
        accounting (benches: exclude the compile-paying first step from
        the reported p50/p99 and from ``pipeline_overlap_ratio``)."""
        self._step_ms.clear()
        self._tick_ms.clear()
        for ring in self._span_hist.values():
            ring.clear()
        self._pl_step_ms = 0.0
        self._pl_offstep_ms = 0.0
        self._dispatch_ms.clear()
        self._builder.stats["stalls_total"] = 0
        self._builder.stats["stall_ms_total"] = 0.0

    def _tb_stamp(self, epoch_s: int) -> float:
        """Order-build wall stamp for one planned second, cached so the
        vectorized build, the reference build and an overflow replan of
        the SAME second stamp one value (the build differentials and
        the re-publish-overwrites contract stay byte-identical).  The
        first build of a second wins — a replan's bundle overwrite
        keeps the original plan-build time, which is the stage the
        waterfall measures."""
        t = self._tb_cache.get(epoch_s)
        if t is None:
            t = round(self.clock(), 3)
            self._tb_cache[epoch_s] = t
            if len(self._tb_cache) > 256:
                for k in sorted(self._tb_cache)[:-128]:
                    self._tb_cache.pop(k, None)
        return t

    def _build_plan_orders(self, plan, seconds: List[Tuple[int, list]],
                           excl_acct: List[Tuple[str, str, list]],
                           pending_excl: Optional[Dict[int, int]] = None
                           ) -> int:
        """Emission dispatch: while no registered job sets jitter and
        the spill ring is empty, run the unsmeared vectorized build
        directly — zero per-plan overhead, order wire byte-identical to
        the pre-jitter program (the host-side analogue of the
        use_deps/use_tenants disarm).  Armed, the smear pass splits the
        plan at the deterministic per-fire deltas first."""
        if self._jitter_jobs or self._smear_ring:
            return self._build_plan_orders_smeared(
                plan, seconds, excl_acct, pending_excl=pending_excl)
        return self._build_plan_orders_native(
            plan, seconds, excl_acct, pending_excl=pending_excl)

    def _build_plan_orders_smeared(self, plan,
                                   seconds: List[Tuple[int, list]],
                                   excl_acct: List[Tuple[str, str, list]],
                                   pending_excl: Optional[Dict[int, int]]
                                   = None) -> int:
        """Herd-smearing emission pass.  A fire of row r matched at
        logical second s is scheduled at s + fnv_continue(sbase[r],
        str(s)) % (jitter[r]+1): the delta vector is ONE vectorized FNV
        continuation over the fired rows (a cached per-row partial hash
        over the group-qualified "<group>/<id>|", sibling of the trace
        plane's bare-id tbase — O(digits) numpy ops per second, no
        per-fire Python hashing) — deterministic, so every
        leader/restore smears a given (job, second) to the SAME epoch.

        delta == 0 fires stay native.  delta > 0 fires enter the spill
        ring keyed by their smeared target second; when the build
        reaches that second (same window, a later window, or a
        hole-rewind rebuild) the target's arrivals are PREPENDED to its
        native fires — oldest source second first — and
        the merged plan runs through the unsmeared vectorized build, so
        coalescing, the KindAlone live-lock skip, the tenancy
        max_running clamp, the herd gauges and trace sampling all apply
        at the EMISSION second.  Fences, (node, second) bundle keys and
        dedup therefore key on the smeared epoch with no downstream
        change, and agents derive trace ids from the order-key epoch
        exactly as before.

        The ring is NOT consumed on read: a rebuilt window re-reads the
        same arrivals, keeping the bundle-overwrite-is-a-superset
        contract; entries are pruned once the publisher's landed
        watermark passes both the target second and the second that
        emitted them (see _smear_begin, which also flushes the rare
        LATE arrivals an overflow replan smears into already-published
        seconds)."""
        ep = int(plan.epoch_s)
        rows = np.asarray(plan.fired)
        keep = None
        if rows.size:
            jit = self._rd_jitter[rows]
            if jit.any():
                tids = self._trace.fnv_continue_vec(
                    self._rd_sbase[rows], str(ep))
                delta = (tids % (jit.astype(np.uint64) + np.uint64(1))
                         ).astype(np.int64)
                defer = np.flatnonzero(delta > 0)
                if defer.size:
                    cols_all = np.asarray(plan.assigned)
                    st = self._smear_stats
                    st["deferred_total"] += int(defer.size)
                    spread = int(delta.max())
                    if spread > st["max_spread_s"]:
                        st["max_spread_s"] = spread
                    drops = 0
                    d_rows = rows[defer].astype(np.int64)
                    d_cols = cols_all[defer].astype(np.int64)
                    d_del = delta[defer]
                    # one grouped insert per distinct delta (<= jitter
                    # of them): the herd second's ~J deferrals are a
                    # handful of array slices, not J dict entries
                    order = np.argsort(d_del, kind="stable")
                    uniq, starts = np.unique(d_del[order],
                                             return_index=True)
                    bounds = np.append(starts, order.size)
                    with self._smear_lock:
                        ring = self._smear_ring
                        for u in range(uniq.size):
                            sl = order[bounds[u]:bounds[u + 1]]
                            tgt = ep + int(uniq[u])
                            bucket = ring.get(tgt)
                            if bucket is None:
                                bucket = ring[tgt] = {}
                            g = bucket.get(ep)
                            if g is not None:
                                # the group exists: a plain window
                                # rebuild re-derives the SAME rows
                                # (deterministic smear) — but an
                                # OVERFLOW REPLAN of ep re-fires the
                                # FULL set, and deltas the truncated
                                # head build already inserted must
                                # UNION the replanned tail in, or
                                # those fires are never dispatched
                                new_m = ~np.isin(d_rows[sl], g[0])
                                if not new_m.any():
                                    continue
                                sl = sl[new_m]
                                room = (self._smear_ring_cap
                                        - self._smear_ring_n)
                                if room <= 0:
                                    drops += sl.size
                                    continue
                                if sl.size > room:
                                    drops += sl.size - room
                                    sl = sl[:room]
                                g[0] = np.concatenate(
                                    [g[0], d_rows[sl]])
                                g[1] = np.concatenate(
                                    [g[1], d_cols[sl]])
                                if g[2] is not None:
                                    # the head rows already emitted
                                    # with a second this leader may
                                    # never rebuild: clear the mark so
                                    # the target's rebuild or the late
                                    # flush re-emits the grown group —
                                    # the head twins are idempotent
                                    # downstream (fences / bundle
                                    # overwrite superset / per-fire
                                    # legacy keys)
                                    g[2] = None
                                self._smear_ring_n += int(sl.size)
                                continue
                            room = (self._smear_ring_cap
                                    - self._smear_ring_n)
                            if room <= 0:
                                drops += sl.size
                                continue
                            if sl.size > room:
                                drops += sl.size - room
                                sl = sl[:room]
                            bucket[ep] = [d_rows[sl], d_cols[sl], None]
                            self._smear_ring_n += int(sl.size)
                    if drops:
                        st["ring_drops_total"] += drops
                        log.errorf("smear spill ring full (cap %d): "
                                   "dropped %d deferred fires of second "
                                   "%d", self._smear_ring_cap, drops, ep)
                    keep = delta == 0
        with self._smear_lock:
            bucket = self._smear_ring.get(ep)
            comb_r = comb_c = None
            if bucket:
                gr: List[np.ndarray] = []
                gc: List[np.ndarray] = []
                for _src, g in sorted(bucket.items()):
                    g[2] = ep   # emitted with (and re-marked by any
                    #             rebuild of) this second; un-marked on
                    #             publish holes
                    gr.append(g[0])
                    gc.append(g[1])
                # concatenate INSIDE the lock: the copies are this
                # build's consistent snapshot even if a replan union
                # grows a group concurrently
                comb_r = np.concatenate(gr)
                comb_c = np.concatenate(gc)
        if comb_r is None and keep is None:
            # nothing smears away and nothing arrives: the native build
            # byte-identically (the common case for off-herd seconds)
            return self._build_plan_orders_native(
                plan, seconds, excl_acct, pending_excl=pending_excl)
        nat_rows = rows if keep is None else rows[keep]
        if keep is not None:
            nat_cols = np.asarray(plan.assigned)[keep]
        else:
            nat_cols = np.asarray(plan.assigned)
        if comb_r is not None:
            st = self._smear_stats
            # one (job, second) fire: keep each row's FIRST arrival
            # (oldest source), drop rows that also fire natively at the
            # target — the fence would absorb the twin anyway, don't
            # publish it twice in one bundle
            _, first = np.unique(comb_r, return_index=True)
            keep_m = np.zeros(comb_r.size, bool)
            keep_m[first] = True
            if nat_rows.size:
                keep_m &= ~np.isin(comb_r, nat_rows)
            arr_rows = comb_r[keep_m]
            arr_cols = comb_c[keep_m]
            dups = int(comb_r.size - arr_rows.size)
            if dups:
                st["merged_dups_total"] += dups
            st["emitted_total"] += int(arr_rows.size)
            if arr_rows.size > st["max_second_arrivals"]:
                st["max_second_arrivals"] = int(arr_rows.size)
            fired = np.concatenate(
                [arr_rows, np.asarray(nat_rows, np.int64)])
            assigned = np.concatenate(
                [arr_cols, np.asarray(nat_cols, np.int64)])
        else:
            fired = nat_rows
            assigned = nat_cols
        from ..ops.planner import TickPlan
        synth = TickPlan(epoch_s=ep, fired=fired, assigned=assigned,
                         overflow=0, total_fired=int(fired.size),
                         tenant_throttled=plan.tenant_throttled,
                         tenant_shed=plan.tenant_shed)
        return self._build_plan_orders_native(
            synth, seconds, excl_acct, pending_excl=pending_excl)

    def _smear_begin(self, cover_from: int,
                     seconds: List[Tuple[int, list]],
                     excl_acct: List[Tuple[str, str, list]]):
        """Spill-ring window prologue (build thread, before the plan
        loop): flush LATE arrivals and prune landed targets.

        LATE: an overflow replan re-plans second s a step after s's
        window shipped; fires it smears to (s, s+jitter] may target
        seconds this build no longer covers.  Those can't ride their
        target's (node, second) bundle — it may already be published,
        and overwriting it with a reconstruction is exactly the
        non-superset hazard the ring exists to avoid — so they go out
        as standalone seconds entries on the LEGACY per-(node, second,
        job) order keys (agents keep that parser for rollout
        tolerance); Common fires reuse their idempotent per-(job,
        second) broadcast key.  Entries are marked with the second that
        emitted them rather than removed: a publish hole >= that mark
        clears it (step()) and the re-emission is idempotent
        downstream.

        PRUNE: a target drops once the landed watermark has passed both
        the target and every entry's emitting second — nothing can
        rewind to re-build it anymore."""
        ring = self._smear_ring
        if not ring:
            return
        n_late = 0
        late_orders = []
        with self._smear_lock:
            for t in sorted(k for k in ring if k < cover_from):
                bucket = ring[t]
                if all(g[2] is not None for g in bucket.values()):
                    continue
                orders: List[Tuple[str, str]] = []
                ep = str(t)
                for _src, g in sorted(bucket.items()):
                    if g[2] is not None:
                        continue
                    g[2] = cover_from
                    # per-fire loop is fine here: LATE arrivals are the
                    # rare overflow-replan tail, never the herd
                    for row, col in zip(g[0].tolist(), g[1].tolist()):
                        flags = self._rd_flags[row]
                        if not flags & 1:
                            continue   # job dropped since the source
                        if flags & 4 and self._alone_live and \
                                self._rd_job[row][1] in self._alone_live:
                            continue   # KindAlone lifetime lock is live
                        if flags & 2:
                            if not (0 <= col < len(self._col_node)
                                    and self._col_live[col]):
                                continue   # placed node left the fleet
                            node = self._col_node[col]
                            key = (self.ks.dispatch + node + "/" + ep
                                   + self._rd_suffix[row])
                            orders.append((key, self._rd_payload[row]))
                            excl_acct.append((key, node,
                                              [self._rd_job[row]]))
                        else:
                            orders.append((self.ks.dispatch_all + ep
                                           + self._rd_suffix[row],
                                           self._rd_payload[row]))
                        n_late += 1
                if orders:
                    late_orders.append((t, orders))
            pt = self.publisher.published_through
            if pt:
                for t in [t for t in ring if t < pt]:
                    bucket = ring[t]
                    if all(g[2] is not None and g[2] < pt
                           for g in bucket.values()):
                        self._smear_ring_n -= sum(
                            int(g[0].size) for g in bucket.values())
                        del ring[t]
        if late_orders:
            # oldest first, ahead of this window's native seconds
            seconds.extend(late_orders)
            self._smear_stats["late_emits_total"] += n_late
            log.warnf("smear: %d late fire(s) across %d second(s) "
                      "published on legacy order keys (overflow replan "
                      "smeared past its window)", n_late,
                      len(late_orders))

    def _smear_recover(self, start: int):
        """Fresh-leadership spill reconstruction.  The ring is
        deliberately NOT checkpointed (delta chains record watch
        events; planning-derived state must be derivable), but fires a
        dead leader smeared PAST its final window still owe dispatch:
        any entry targeting second >= start has its source in
        [start - max_jitter, start).  Re-plan that lookback, compute
        ONLY the smear deltas (no emission, no admission hand-backs —
        throttle state replay would double-count), and insert targets
        >= start; targets below start were the dead leader's to publish
        and fences absorb whatever both of us emit.  Runs once per
        leadership, only while some job arms jitter; planner-state
        perturbation from re-planning old seconds is the same class a
        hole rewind already causes and reconcile_capacity self-heals
        it."""
        look = min(300, int(self._max_jitter_seen))
        if look <= 0:
            return
        t0 = time.perf_counter()
        window = max(1, self.window_s)
        inserted = 0
        drops = 0
        s0 = start - look
        while s0 < start:
            w = min(window, start - s0)
            try:
                plans = self.planner.plan_window(s0, w)
            except Exception as e:  # noqa: BLE001 — lookback is best
                # effort: a failed replay loses only already-published
                # seconds' spill, which fences would have absorbed
                log.errorf("smear lookback plan failed at %d: %s", s0, e)
                break
            for plan in plans:
                ep = int(plan.epoch_s)
                if plan.overflow:
                    # a replayed herd second over the adaptive bucket:
                    # a truncated replay would re-derive an INCOMPLETE
                    # spill set and silently lose the tail's deferred
                    # fires — re-plan it with the escalated bucket,
                    # exactly as the live path does
                    try:
                        full = self.planner.plan_window(
                            ep, 1, sla_bucket=self._escalation_want(
                                plan.total_fired))[0]
                        if full.overflow:
                            log.errorf(
                                "smear lookback: %d fires still over "
                                "the escalated bucket at t=%d — their "
                                "spill is lost", full.overflow, ep)
                        plan = full
                    except Exception as e:  # noqa: BLE001 — keep the
                        # truncated head: partial spill beats none
                        log.errorf("smear lookback escalation failed "
                                   "at %d: %s", ep, e)
                rows = np.asarray(plan.fired)
                if not rows.size:
                    continue
                jit = self._rd_jitter[rows]
                if not jit.any():
                    continue
                tids = self._trace.fnv_continue_vec(
                    self._rd_sbase[rows], str(ep))
                delta = (tids % (jit.astype(np.uint64) + np.uint64(1))
                         ).astype(np.int64)
                cols = np.asarray(plan.assigned)
                defer = np.flatnonzero(delta > 0)
                if not defer.size:
                    continue
                d_rows = rows[defer].astype(np.int64)
                d_cols = cols[defer].astype(np.int64)
                d_del = delta[defer]
                order = np.argsort(d_del, kind="stable")
                uniq, starts = np.unique(d_del[order],
                                         return_index=True)
                bounds = np.append(starts, order.size)
                with self._smear_lock:
                    for u in range(uniq.size):
                        tgt = ep + int(uniq[u])
                        if tgt < start:
                            continue
                        sl = order[bounds[u]:bounds[u + 1]]
                        bucket = self._smear_ring.setdefault(tgt, {})
                        if ep in bucket:
                            continue
                        room = (self._smear_ring_cap
                                - self._smear_ring_n)
                        if room <= 0:
                            drops += sl.size
                            continue
                        if sl.size > room:
                            drops += sl.size - room
                            sl = sl[:room]
                        bucket[ep] = [d_rows[sl], d_cols[sl], None]
                        self._smear_ring_n += int(sl.size)
                        inserted += int(sl.size)
            s0 += w
        if drops:
            # the recovery obeys the same LOUD-drop contract the live
            # insert path does: a full ring turns takeover spill into
            # counted, paged loss — never silent loss
            self._smear_stats["ring_drops_total"] += drops
            log.errorf("smear takeover recovery: spill ring full (cap "
                       "%d) — dropped %d re-derived deferred fire(s)",
                       self._smear_ring_cap, drops)
        if inserted:
            log.infof("smear takeover recovery: re-derived %d in-flight "
                      "deferred fire(s) from a %ds lookback in %.0f ms",
                      inserted, look,
                      (time.perf_counter() - t0) * 1e3)

    def _build_plan_orders_native(self, plan,
                                  seconds: List[Tuple[int, list]],
                                  excl_acct: List[Tuple[str, str, list]],
                                  pending_excl: Optional[Dict[int, int]]
                                  = None) -> int:
        """Build one TickPlan's dispatch orders into ``seconds`` (and
        the exclusive-accounting list) — the leader's share of the
        dispatch plane, VECTORIZED: the herd-second build was 703 ms
        p50 at 110k fires as a per-fire Python loop; here the fired
        rows fancy-index precomputed per-row arrays, a stable argsort
        groups exclusive fires by node column, and each coalesced
        (node, second) value is ONE join over precomputed JSON entry
        strings.  Python-level work is O(nodes + alone-fires), not
        O(fires).

        Semantics are byte-identical to :meth:`_build_plan_orders_ref`
        (the retired loop, kept as the differential-test reference):
        routing branches on the ROW's exclusive flag, not the plan's
        bucket split (mesh planners don't populate n_excl, and a flag
        mismatch must never turn a placed exclusive fire into a
        broadcast); KindAlone fires whose lifetime lock is live
        anywhere are skipped (reference job.go:87-123) via the
        watch-fed mirror; exclusive fires COALESCE into one key per
        (node, second) — nodes in first-fire order, entries in plan
        order — whose re-publish (overflow replan, hole rewind)
        OVERWRITES the bundle; Common fires stay one broadcast key per
        (job, second).  Returns the number of FIRES built (not keys),
        keeping dispatches_total comparable across formats."""
        rows = np.asarray(plan.fired)
        orders: List[Tuple[str, str]] = []
        n_fires = 0
        n_bundles = 0
        n_excl = 0
        # trace plane: vectorized head-sampling verdicts for this
        # second's fires (per-row partial hash continued with the epoch
        # string — O(digits) vector ops, not O(fires) Python hashing).
        # A coalesced bundle with >= 1 sampled member gets ONE trailing
        # {"tb": <build ts>} element; agents re-derive the per-member
        # verdict from the same hash.  trace_shift < 0: samp stays None
        # and the wire is byte-identical to the pre-trace format.
        samp = None
        if self.trace_shift >= 0 and rows.size:
            tids = self._trace.fnv_continue_vec(
                self._rd_tbase[rows], str(plan.epoch_s))
            mask = np.uint64((1 << self.trace_shift) - 1)
            samp = ((tids & mask) == np.uint64(0)) | self._rd_tflag[rows]
        if plan.tenant_throttled is not None and \
                (plan.tenant_throttled.any() or plan.tenant_shed.any()):
            # device-side admission refusals: hand the per-tenant counts
            # back to the step thread (this may run on the build worker)
            self._tenant_q.append(("adm", plan.tenant_throttled,
                                   plan.tenant_shed))
        if rows.size:
            flags = self._rd_flags[rows]
            live = (flags & 1) != 0
            # only the (typically few) KindAlone fires pay a Python
            # set lookup against the lifetime-lock mirror
            if self._alone_live:
                al = np.flatnonzero(live & ((flags & 4) != 0))
                if al.size:
                    alone_live = self._alone_live
                    rd_job = self._rd_job
                    drop = [int(i) for i in al
                            if rd_job[rows[i]][1] in alone_live]
                    if drop:
                        live[drop] = False
            is_excl = (flags & 2) != 0
            ep = str(plan.epoch_s)
            # Common fan-out, in plan order: ONE broadcast order per
            # fire; eligible agents each pick it up via their local
            # IsRunOn — the host never walks the [J, N] matrix per
            # fire.  map/zip keep the per-fire tuple assembly in C.
            com = np.flatnonzero(live & ~is_excl)
            if com.size:
                crows = rows[com].tolist()
                pfx = f"{self.ks.dispatch_all}{ep}"
                getter = itemgetter(*crows)
                if len(crows) == 1:
                    orders.append((pfx + getter(self._rd_suffix),
                                   getter(self._rd_payload)))
                else:
                    orders += zip(map(pfx.__add__,
                                      getter(self._rd_suffix)),
                                  getter(self._rd_payload))
                n_fires += len(crows)
            xi = np.flatnonzero(live & is_excl)
            if xi.size:
                cols = np.asarray(plan.assigned)[xi]
                ok = (cols >= 0) & (cols < len(self._col_node))
                ok &= self._col_live[np.where(ok, cols, 0)]
                xi = xi[ok]
                cols = cols[ok]
            if xi.size and self._tenants:
                # max_running clamp (vectorized — see _fair_filter;
                # the capacity fair share runs on device)
                xi, cols = self._fair_filter(rows, xi, cols,
                                             pending=pending_excl)
            if xi.size:
                order = np.argsort(cols, kind="stable")
                sx = xi[order]
                sc = cols[order]
                cuts = np.flatnonzero(np.diff(sc)) + 1
                starts = [0] + cuts.tolist()
                ends = cuts.tolist() + [int(sx.size)]
                # stable sort => each group's first element carries the
                # smallest original fire index; ordering groups by it
                # reproduces the loop's first-fire node order exactly
                gorder = np.argsort(sx[np.asarray(starts, np.int64)],
                                    kind="stable").tolist()
                # ONE itemgetter batch-extract per list up front; per
                # node the work is then list slices, one C-level join
                # per coalesced value, and C-level tuple assembly
                srows = rows[sx].tolist()
                if len(srows) == 1:
                    bent_l = [self._rd_bentry[srows[0]]]
                    rj_l = [self._rd_job[srows[0]]]
                else:
                    getter = itemgetter(*srows)
                    bent_l = getter(self._rd_bentry)
                    rj_l = getter(self._rd_job)
                sc_l = sc.tolist()
                col_node = self._col_node
                starts_g = [starts[g] for g in gorder]
                ends_g = [ends[g] for g in gorder]
                pfx = self.ks.dispatch
                # partitioned: the ".<p>" suffix scopes the bundle key
                # to this partition (empty at P=1 — byte-identical)
                tail = "/" + ep + self._bundle_sfx
                keys = [pfx + col_node[sc_l[s]] + tail for s in starts_g]
                if samp is not None:
                    # any-member-sampled per coalesced group (reduceat
                    # over the node-sorted verdicts), in gorder order
                    gs = np.add.reduceat(
                        samp[sx].astype(np.int8),
                        np.asarray(starts, np.int64)) > 0
                    tb = self._tb_stamp(plan.epoch_s)
                    ttails = [',{"tb":%.3f}' % tb if gs[g] else ""
                              for g in gorder]
                else:
                    ttails = None
                orders += zip(keys,
                              ("[" + ",".join(bent_l[s:e])
                               + (ttails[i] if ttails else "") + "]"
                               for i, (s, e)
                               in enumerate(zip(starts_g, ends_g))))
                excl_acct += zip(keys,
                                 (col_node[sc_l[s]] for s in starts_g),
                                 (list(rj_l[s:e])
                                  for s, e in zip(starts_g, ends_g)))
                n_bundles = len(gorder)
                n_excl = int(sx.size)
                n_fires += n_excl
        if n_bundles > self.max_second_node_keys:
            self.max_second_node_keys = n_bundles
        if n_excl > self.max_second_excl_fires:
            self.max_second_excl_fires = n_excl
        seconds.append((plan.epoch_s, orders))
        return n_fires

    def _build_plan_orders_ref(self, plan,
                               seconds: List[Tuple[int, list]],
                               excl_acct: List[Tuple[str, str, list]],
                               pending_excl: Optional[Dict[int, int]]
                               = None) -> int:
        """The per-fire Python loop the vectorized build replaced —
        kept as the differential-test REFERENCE (byte-identical output
        is asserted on randomized plans) and as the plain-language spec
        of the build semantics, INCLUDING the tenancy plane's
        max_running clamp: a tenant's placed exclusive fires stop once
        its exec-concurrency headroom (max_running − outstanding −
        this window's prior admissions) is used up — first fires in
        plan order win, exactly _fair_filter's select_fair."""
        mr_caps = None
        if self._tenants:
            for tname, quota in list(self._tenants.items()):
                if not quota.max_running:
                    continue
                tid = self._tenant_ids.get(tname, 0)
                if tid:
                    if mr_caps is None:
                        mr_caps = {}
                    mr_caps[tid] = max(
                        0, quota.max_running
                        - self._tenant_excl.get(tid, 0)
                        - (pending_excl or {}).get(tid, 0))
        mr_taken: Dict[int, int] = {}
        alone_live = self._alone_live
        row_disp = self._row_dispatch
        col_node = self._col_node
        disp_pfx = self.ks.dispatch
        bcast_pfx = self.ks.dispatch_all
        n_cols = len(col_node)
        ep = str(plan.epoch_s)
        orders: List[Tuple[str, str]] = []
        bundles: Dict[str, list] = {}       # node -> [bundle entry json]
        bundle_jobs: Dict[str, list] = {}   # node -> [(group, job_id)]
        bundle_samp: Set[str] = set()       # nodes with a sampled member
        trace_on = self.trace_shift >= 0
        tmask = (1 << self.trace_shift) - 1 if trace_on else 0
        n_fires = 0
        for row, node_col in zip(plan.fired.tolist(),
                                 plan.assigned.tolist()):
            ent = row_disp.get(row)
            if ent is None:
                continue
            exclusive, payload, group, job_id, kind, suffix, bentry = ent
            if kind == KIND_ALONE and job_id in alone_live:
                continue   # previous run still holds the fleet lock
            if exclusive:
                if 0 <= node_col < n_cols:
                    node = col_node[node_col]
                    if node:
                        if mr_caps is not None:
                            tid = int(self._row_tenant[row])
                            cap = mr_caps.get(tid)
                            if cap is not None:
                                if mr_taken.get(tid, 0) >= cap:
                                    continue    # max_running shed
                                mr_taken[tid] = \
                                    mr_taken.get(tid, 0) + 1
                        bundles.setdefault(node, []).append(bentry)
                        bundle_jobs.setdefault(node, []).append(
                            (group, job_id))
                        if trace_on and (
                                self._rd_tflag[row] or
                                (self._trace.fnv_continue(
                                    int(self._rd_tbase[row]), ep)
                                 & tmask) == 0):
                            bundle_samp.add(node)
                        n_fires += 1
            else:
                orders.append((f"{bcast_pfx}{ep}{suffix}", payload))
                n_fires += 1
        n_excl = 0
        for node, entries in bundles.items():
            key = f"{disp_pfx}{node}/{ep}{self._bundle_sfx}"
            ttail = (',{"tb":%.3f}' % self._tb_stamp(plan.epoch_s)
                     if node in bundle_samp else "")
            orders.append((key, "[" + ",".join(entries) + ttail + "]"))
            excl_acct.append((key, node, bundle_jobs[node]))
            n_excl += len(entries)
        if len(bundles) > self.max_second_node_keys:
            self.max_second_node_keys = len(bundles)
        if n_excl > self.max_second_excl_fires:
            self.max_second_excl_fires = n_excl
        if pending_excl is not None:
            for tid, n in mr_taken.items():
                pending_excl[tid] = pending_excl.get(tid, 0) + n
        seconds.append((plan.epoch_s, orders))
        return n_fires

    def _escalation_want(self, total_fired: int) -> int:
        """Escalated bucket size for an over-bucket second, snapped to
        a warmed executable when one covers it — shared by the async,
        the sync (mesh) and the builder-requested replan paths."""
        from ..ops.planner import _next_pow2
        want = min(_next_pow2(max(2048, total_fired)), self.planner.J)
        if hasattr(self.planner, "snap_escalation"):
            want = self.planner.snap_escalation(want)
        return want

    def _drain_replans(self):
        """Gather and publish pending async replans NOW (leadership
        loss, shutdown): their over-bucket tails were already counted
        as late fires — abandoning the handles would turn late into
        LOST."""
        if not self._pending_replans:
            return
        pending, self._pending_replans = self._pending_replans, []
        try:
            lease = self.store.grant(self.dispatch_ttl)
            seconds: List[Tuple[int, list]] = []
            excl_acct: List[Tuple[str, str, list]] = []
            wpend: Dict[int, int] = {}
            n = 0
            gathered = [self.planner.gather_window(
                self._resolve_handle(handle))[0]
                for _ep, handle, _fires in pending]
            if self._smear_ring and gathered:
                self._smear_begin(min(p.epoch_s for p in gathered),
                                  seconds, excl_acct)
            for plan in gathered:
                n += self._build_plan_orders(
                    plan, seconds, excl_acct, pending_excl=wpend)
            self.publisher.submit(seconds, lease, 0)
            for key, node, jobs in excl_acct:
                self._acct_add_order(key, node, jobs)
            log.infof("drained %d pending replan fires on hand-off", n)
        except Exception as e:  # noqa: BLE001 — store down: the fires
            # are genuinely lost; count the FIRES recorded at queue time
            # (a handle count would understate the loss and skew the
            # late-vs-lost accounting the docs quote)
            self.stats["overflow_drops"] += sum(f for _, _, f in pending)
            log.errorf("pending replans LOST on hand-off: %s", e)

    def _queue_replan(self, plan):
        """Dispatch the escalated re-plan of an over-bucket second on
        the device WITHOUT waiting; the next step gathers and publishes
        the full fire set (late by ~one step, never lost)."""
        want = self._escalation_want(plan.total_fired)
        self.stats["overflow_late_fires"] += plan.overflow
        log.warnf("%d fires over the bucket SLA at t=%d; re-planning "
                  "async with bucket %d (late, never lost)",
                  plan.overflow, plan.epoch_s, want)
        self._pending_replans.append(
            (plan.epoch_s,
             self.planner.plan_window_async(plan.epoch_s, 1,
                                            sla_bucket=want),
             plan.overflow))   # fire count, for honest loss accounting
                               # if the handle can't be drained

    def _replan_overflow(self, plan):
        """A second whose fires exceeded the adaptive bucket is
        immediately re-planned with a bucket sized for its TRUE fire
        count, so every fire still dispatches — late by one extra plan
        dispatch (plus a one-off XLA compile for the new bucket size),
        never lost.  The re-plan re-fires the head rows the truncated
        plan also saw; their re-dispatch is deduplicated downstream
        (exclusive: the (job, second) fence; Common: the agents'
        broadcast dedup), and the transient double-counted load /
        capacity reservation self-heals at the next step's
        reconcile_capacity.  Residual drops are only possible if the
        fire count exceeds the job capacity J — structurally impossible
        for real fires."""
        want = self._escalation_want(plan.total_fired)
        self.stats["overflow_late_fires"] += plan.overflow
        log.warnf("%d fires over the bucket SLA at t=%d; re-planning "
                  "with bucket %d (late, never lost)",
                  plan.overflow, plan.epoch_s, want)
        replan = self.planner.plan_window(plan.epoch_s, 1,
                                          sla_bucket=want)[0]
        if replan.overflow:
            self.stats["overflow_drops"] += replan.overflow
            log.errorf("%d fires still over the escalated bucket %d at "
                       "t=%d — dropped", replan.overflow, want,
                       plan.epoch_s)
        return replan

    # ---- operator metrics ------------------------------------------------

    def health(self) -> dict:
        """Readiness facts for the ``--health-port`` endpoint (bin/
        sched): leader lease held, watch streams open, step loop
        alive.  A warm standby reports leader=False — operators decide
        whether a standby counts as 'ready' for their probe; the
        /readyz endpoint fails only on dead watches or a dead loop,
        and names the leader fact in the body either way."""
        watches = [w for w in self._all_watches() if w is not None]
        thread = getattr(self, "_thread", None)
        return {
            "leader": bool(self.is_leader),
            "watches_open": len(watches),
            "loop_alive": bool(thread is not None and thread.is_alive()),
            "partition": self.partition,
            "partitions": self.partitions,
        }

    def metrics_snapshot(self) -> dict:
        # pipeline overlap: the builder-stage work that did NOT re-enter
        # the step as a stall is time the device/store spent overlapped
        # with (or idle beside) the step thread; the ratio is that
        # hidden time over what a fully serial step would have summed
        stall_ms = self._builder.stats["stall_ms_total"]
        hidden_ms = max(0.0, self._pl_offstep_ms - stall_ms)
        denom_ms = self._pl_step_ms + hidden_ms
        # partitioned plane: the partition index rides every sched
        # series as a partition= label on /v1/metrics (a stalled
        # partition must be visible, not averaged away); absent
        # entirely at P=1 so the unpartitioned snapshot is unchanged
        part = ({"partition": self.partition,
                 "partitions": self.partitions,
                 "acct_exchanges_total":
                     self.stats["acct_exchanges_total"],
                 "acct_partitions_seen": len(self._part_foreign)}
                if self.partitions > 1 else {})
        return {
            **part,
            "tick_p50_ms": round(self._tick_ms.percentile(0.50), 3),
            "tick_p99_ms": round(self._tick_ms.percentile(0.99), 3),
            # the FULL cycle (drain+reconcile+flush+plan+build+publish);
            # tick_* above is the device plan call alone (pipelined:
            # the residual device wait the gather stage paid)
            "sched_step_p50_ms": round(self._step_ms.percentile(0.50), 3),
            "sched_step_p99_ms": round(self._step_ms.percentile(0.99), 3),
            **{f"step_span_{k}_ms": round(v, 3)
               for k, v in self._step_spans.items()},
            # per-span latency DISTRIBUTIONS (last-step instantaneous
            # values above; p50/p99 here), including the builder-side
            # gather/build/submit stage spans
            **{f"step_span_{name}_p{p}_ms":
               round(ring.percentile(p / 100), 3)
               for name, ring in sorted(self._span_hist.items())
               for p in (50, 99)},
            # two-stage pipeline health: depth/stall say whether the
            # build+publish stage keeps up with the plan stage; the
            # overlap ratio is the fraction of total step work hidden
            # off the step thread (0 on the serial path)
            "pipelined": 1 if self.pipelined else 0,
            "pipeline_depth": self._builder.depth,
            "pipeline_stalls_total": self._builder.stats["stalls_total"],
            "pipeline_stall_ms_total": round(stall_ms, 3),
            "pipeline_offstep_ms_total": round(self._pl_offstep_ms, 3),
            "pipeline_overlap_ratio":
                round(hidden_ms / denom_ms, 4) if denom_ms else 0.0,
            "publish_inflight": self.publisher.inflight,
            "overflow_drops_total": self.stats["overflow_drops"],
            "overflow_late_fires_total": self.stats["overflow_late_fires"],
            "skipped_seconds_total": self.stats["skipped_seconds"],
            "watch_losses_total": self.stats["watch_losses"],
            "dispatches_total": self.stats["dispatches_total"],
            "steps_total": self.stats["steps_total"],
            # lease watchdog health (per partition when partitioned —
            # the partition= label rides every series above)
            "lease_resigns_total": self.stats["lease_resigns_total"],
            # per-shard publish decoupling: 1 when the publisher runs
            # one shard-routed lane per store shard
            "publish_shard_lanes":
                1 if self.publisher.shard_lanes else 0,
            # outstanding exclusive-slot reservations: slot counts over
            # the ORDERS mirror only (coalesced keys reserve len(jobs)
            # each, so key count would understate it; _excl_cnt would
            # OVERstate it — it also counts running exclusive procs)
            "dispatch_queue_depth": sum(
                int(excl) for _n, _c, excl in self._orders.values()),
            "procs_running": len(self._procs),
            "jobs": len(self.jobs),
            "is_leader": 1 if self.is_leader else 0,
            # plane-side publish health: per-window wire time and the
            # published/dropped totals (the step only shows backpressure)
            "publish_window_ms": round(self.publisher.last_window_ms, 3),
            "published_total": self.publisher.stats["published_total"],
            "publish_failures": self.publisher.stats["publish_failures"],
            "publish_abandoned": self.publisher.stats["publish_abandoned"],
            "published_through": self.publisher.published_through,
            # herd-burst gauges: the largest key count one second ever
            # published (all kinds), and the exclusive slice — node_keys
            # is bounded by active nodes under coalescing where
            # excl_fires used to be its key count
            "publish_max_second_keys": self.publisher.max_second_keys,
            "publish_max_second_node_keys": self.max_second_node_keys,
            "publish_max_second_excl_fires": self.max_second_excl_fires,
            # herd-smearing plane: jobs arming jitter, fires deferred
            # past their matched second / re-emitted at their smeared
            # one, the widest observed delta and the largest arrival
            # burst any single smeared second absorbed (the smeared
            # twins of the herd gauges above), plus spill-ring health
            # (late = overflow-replan spill emitted on legacy keys;
            # drops = ring cap exceeded, LOUD — fires were lost)
            "smear_jobs": self._jitter_jobs,
            "smear_deferred_total": self._smear_stats["deferred_total"],
            "smear_emitted_total": self._smear_stats["emitted_total"],
            "smear_merged_dups_total":
                self._smear_stats["merged_dups_total"],
            "smear_late_emits_total":
                self._smear_stats["late_emits_total"],
            "smear_ring_depth": self._smear_ring_n,
            "smear_ring_drops_total":
                self._smear_stats["ring_drops_total"],
            "smear_max_spread_s": self._smear_stats["max_spread_s"],
            "smear_max_second_arrivals":
                self._smear_stats["max_second_arrivals"],
            # checkpoint plane: save cadence health + whether this
            # instance booted warm (restored=1) and how fast
            "checkpoint_saves_total": self._ckpt_stats["saves_total"],
            "checkpoint_save_errors_total":
                self._ckpt_stats["save_errors_total"],
            "checkpoint_last_save_ms": self._ckpt_stats["last_save_ms"],
            "checkpoint_last_rev": self._ckpt_stats["last_rev"],
            "checkpoint_restored": self._ckpt_stats["restored"],
            "checkpoint_restore_ms": self._ckpt_stats["restore_ms"],
            # delta-chain health: how many saves were small deltas, the
            # live chain length (restore folds the whole chain — the
            # rebase knobs bound it), and the last delta's event count
            "checkpoint_delta_saves_total":
                self._ckpt_stats["delta_saves_total"],
            "checkpoint_last_delta_events":
                self._ckpt_stats["last_delta_events"],
            "checkpoint_chain_len": (self._ckpt_chain or {}).get("seq", 0),
            # double-buffered full saves: how many serialized off the
            # step thread, and what the last pickle actually cost there
            "checkpoint_bg_writes_total":
                self._ckpt_stats["bg_writes_total"],
            "checkpoint_last_serialize_ms":
                self._ckpt_stats["last_serialize_ms"],
            # workflow DAG plane health
            "dep_jobs": len(self._dep_jobs),
            "dep_blocked_jobs": len(self._dep_blocked),
            "dep_events_mirrored": len(self._dep_latest),
            # multi-tenant admission health (per-tenant breakdown rides
            # the "tenant" component snapshot -> cronsun_tenant_*)
            "tenants": len(self._tenants),
            "excl_slots_available": (
                -1 if self._agg_excl_avail == float("inf")
                else int(min(self._agg_excl_avail, 1 << 60))),
            "tenant_throttled_fires_total": sum(
                c["throttled_fires"]
                for c in self._tenant_counters.values()),
            "tenant_shed_fires_total": sum(
                c["shed_fires"] for c in self._tenant_counters.values()),
        }

    def smear_snapshot(self) -> dict:
        """Per-second smear spread: how many deferred fires currently
        wait in the spill ring for each upcoming target second (plus
        the cumulative counters metrics_snapshot flattens).  Operator
        surface for 'is the herd actually spreading': a healthy smeared
        herd shows ~herd/(jitter+1) arrivals per second across the
        jitter width instead of one spike."""
        with self._smear_lock:
            return {
                "ring_depth": self._smear_ring_n,
                "ring_seconds": len(self._smear_ring),
                "per_second": {
                    int(t): sum(int(g[0].size) for g in b.values())
                    for t, b in sorted(self._smear_ring.items())},
                **self._smear_stats,
            }

    def _advance_hwm(self, value: int):
        for _ in range(8):
            kv = self.store.get(self._hwm_key)
            if kv is not None:
                try:
                    if int(kv.value) >= value:
                        return
                except ValueError:
                    pass
            if self.store.put_if_mod_rev(self._hwm_key, str(value),
                                         kv.mod_rev if kv else 0):
                return

    def _row_cmd(self, row: int) -> Optional[Tuple[str, str, str]]:
        return self.rows.by_row.get(row)

    # ---- background loop -------------------------------------------------

    def start(self):
        if self._thread:
            return
        def run():
            last_tb = 0.0
            while not self._stop.is_set():
                try:
                    self.step()
                except Exception as e:  # noqa: BLE001 — keep the loop alive
                    # rate-limited: a store outage fails EVERY retry; a
                    # full traceback each 0.2 s floods the log transport
                    # (an undrained pipe then blocks this very loop —
                    # the scheduler must stay schedulable even when its
                    # log consumer isn't keeping up)
                    now = time.monotonic()
                    if now - last_tb > 30.0:
                        last_tb = now
                        import traceback
                        traceback.print_exc()
                    else:
                        log.errorf("scheduler step failed: %s", e)
                # plan ahead: sleep until the window is nearly consumed
                nxt = (self._next_epoch or 0) - 1.5
                delay = max(0.2, min(self.window_s, nxt - self.clock()))
                if self._stop.wait(delay):
                    return
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="scheduler-loop")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        # abdicate FIRST (a successor can take over while our in-flight
        # windows drain), THEN drain: seconds the successor re-plans
        # because our HWM advance raced it produce duplicate orders,
        # which the (job, second) fences / broadcast dedup absorb — the
        # same late-never-lost tradeoff as the crash path, minus the
        # lease-TTL wait
        if self._leader_lease is not None:
            self.store.revoke(self._leader_lease)
            self._leader_lease = None
        # run the pipeline dry before the replan drain: in-flight
        # windows publish, their accounting lands, and any replan
        # REQUESTS they raised become handles _drain_replans can gather
        self._builder.flush()
        self._drain_build_acct()
        self._drain_replan_reqs()
        self._drain_replans()
        self._builder.stop()
        self.publisher.stop()
        self._drain_build_acct()
        self._ckpt_join()   # an in-flight base write finishes its rename
        self._dispatch_pool.shutdown(wait=False)
        if self._ae_store is not None and self._ae_store is not self.store:
            try:
                self._ae_store.close()
            except Exception:  # noqa: BLE001 — already dead
                pass
        for lane in self._owned_lanes:
            try:
                lane.close()
            except Exception:  # noqa: BLE001 — already dead
                pass
        if self._acct_lease is not None:
            try:
                self.store.revoke(self._acct_lease)
            except Exception:  # noqa: BLE001 — TTL is the backstop
                pass
            self._acct_lease = None
        self.metrics.revoke()
        self._tenant_metrics.revoke()
        if self._mesh_metrics is not None:
            self._mesh_metrics.revoke()
