"""Partitioned scheduler plane: job-space routing + the partition-map pin.

The job space splits into P partitions by the SAME 64-bit FNV routing
token the sharded store already routes a job's key family by
(``store/sharded.py shard_token``: ``cmd``/``lock``/``proc``/``phase``
keys all hash ``"j:" + job_id``), so a job's fences, orders, procs and
alone-locks co-locate with its owning partition by construction.  Each
partition runs as an independent ``SchedulerService`` — its own leader
lease (``lock/sched/p<i>``), its own watch slice (job-keyed streams
filtered to owned tokens; node/group/tenant/ckpt streams shared), its
own high-water mark and checkpoint chain — so P leaders tick
concurrently against the store with no cross-partition coordination on
the fire path.  The only shared state is per-node load/remaining
capacity, reconciled through the leased ``sched/acct/p<i>`` demand
summaries (O(nodes) each, folded into every partition's capacity view).

The topology is pinned under ``sched/partmap`` exactly like the store's
shardmap (PR 6): the first partition leader publishes ``{"p": P,
"hash": SCHEME}``, every later scheduler verifies it, and a scheduler
configured with a different partition count refuses to start instead of
silently double-scheduling the job space under two topologies.  P=1 is
pure passthrough: no partmap write, no key changes, byte-identical
wire output (pinned by differential test) — but a P=1 scheduler DOES
refuse to start against a fleet whose partmap pins P>1.
"""

from __future__ import annotations

import json
from typing import Optional

from ..core import Keyspace
from ..store.sharded import fnv1a

# versioned with the store's token scheme on purpose: partition routing
# IS the store's job-token routing taken mod P
PART_SCHEME = "fnv1a-jobtoken-v1"


class PartitionMapMismatch(RuntimeError):
    """The fleet's pinned partition topology contradicts this
    scheduler's configuration — refusing beats double-scheduling."""


def job_token(job_id: str) -> int:
    """The job's 64-bit routing token — identical to the sharded
    store's token for the job's ``cmd``/``lock``/``proc``/``phase``
    keys (``fnv1a("j:" + job_id)``)."""
    return fnv1a("j:" + job_id)


def job_partition(job_id: str, partitions: int) -> int:
    """Owning partition of a job: its routing token mod P."""
    return job_token(job_id) % partitions if partitions > 1 else 0


def pin_partition_map(store, ks: Keyspace, partitions: int) -> None:
    """Publish-or-verify the ``sched/partmap`` pin.

    P>1: publish ``{"p": P, "hash": PART_SCHEME}`` create-if-absent,
    then read back and verify — the first leader pins, every later
    scheduler (leader or standby, any partition) must agree.  P=1:
    verify-only — no write (the passthrough contract), but a pinned
    P>1 map refuses the unpartitioned scheduler loudly: its single
    leader would re-dispatch every partition's jobs under a second
    topology.  Raises :class:`PartitionMapMismatch` on any conflict."""
    want = {"p": int(partitions), "hash": PART_SCHEME}
    if partitions > 1:
        kv = store.get(ks.partmap)
        if kv is None:
            store.put_if_absent(
                ks.partmap, json.dumps(want, separators=(",", ":")))
            kv = store.get(ks.partmap)
        pinned = _parse(kv.value if kv is not None else None)
        if pinned != want:
            raise PartitionMapMismatch(
                f"partition map pinned at {ks.partmap} is {pinned}, "
                f"this scheduler is configured for {want} — resize "
                f"requires draining the fleet and clearing the pin "
                f"(see OPERATIONS.md)")
        return
    kv = store.get(ks.partmap)
    if kv is None:
        return
    pinned = _parse(kv.value)
    if pinned is not None and pinned.get("p", 1) != 1:
        raise PartitionMapMismatch(
            f"fleet partition map pins p={pinned.get('p')} "
            f"({ks.partmap}) but this scheduler runs UNPARTITIONED — "
            f"it would re-dispatch every partition's jobs; launch with "
            f"--partitions {pinned.get('p')} --partition <i> instead")


def _parse(value: Optional[str]) -> Optional[dict]:
    if value is None:
        return None
    try:
        doc = json.loads(value)
        if not isinstance(doc, dict):
            return None
        return {"p": int(doc.get("p", 0)), "hash": doc.get("hash", "")}
    except (json.JSONDecodeError, TypeError, ValueError):
        # a hand-edited/corrupted pin must surface as the LOUD
        # mismatch refusal (parsed None != want), never a raw
        # TypeError crashing startup
        return None


def encode_demand(excl: dict, load: dict) -> str:
    """One partition's per-node demand summary wire format:
    ``{node: [excl_slots, load]}`` over nodes with NONZERO demand only
    (demand-sparse: an idle fleet's summary is ``{}``)."""
    out = {}
    for n, e in excl.items():
        if e:
            out[n] = [int(e), 0.0]
    for n, l in load.items():
        if l:
            ent = out.get(n)
            if ent is None:
                out[n] = [0, round(float(l), 3)]
            else:
                ent[1] = round(float(l), 3)
    return json.dumps(out, separators=(",", ":"))


def decode_demand(value: str) -> Optional[dict]:
    """Parse a demand summary into ``{node: (excl, load)}``; None on a
    malformed value (dropped loudly by the caller, never a crash on a
    foreign partition's write)."""
    try:
        doc = json.loads(value)
    except (json.JSONDecodeError, TypeError):
        return None
    if not isinstance(doc, dict):
        return None
    out = {}
    for n, ent in doc.items():
        try:
            out[str(n)] = (int(ent[0]), float(ent[1]))
        except (TypeError, ValueError, IndexError):
            return None
    return out
