"""Cold-tier segment files for the tiered result store.

The tiered layout inside one logd shard splits execution history by a
single **prefix watermark** (``cold_boundary``): every record id at or
below it lives in an immutable, compacted per-day segment file on disk
(the COLD tier); every id above it is HOT — SQLite rows for the Python
backend, the in-memory deque for the native one.  The watermark only
advances, and it advances only past records whose UTC day has aged out
of the hot window, so the hot tier always holds a contiguous id suffix
(the invariant ``get_log``'s index jump and cursor mode's O(new) scan
rely on) and a day's records move cold exactly once per age-out pass.

Segment format — shared byte-for-byte with ``native/logd.cc`` so either
backend (and the reshard tool) can read the other's segments:

    ["d", day, count, min_id, max_id]          # header, first line
    ["L", id, job_id, job_group, name, node,   # one line per record,
          user, command, output, success,      # id ASCENDING — the
          begin_ts, end_ts]                    # native WAL's L body

One file per UTC day, ``<day>.seg`` inside ``<db>.segs/``.  A day's
segment is REWRITTEN (union by id, temp + rename + fdatasync) whenever
an age-out pass moves more of that day cold — late records whose
begin_ts falls in an already-aged day ride a later pass, and a crash
between segment write and hot-trim replays idempotently: the redo
unions the same records and produces the same bytes, then trims.
Readers never see a torn file (rename is atomic) and never double-count
(a segment row is consulted only for ids <= the durably-recorded
watermark; rows above it are still authoritatively hot).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

from .joblog import LogRecord

SEG_SUFFIX = ".seg"
IDX_SUFFIX = ".idx"
# one sparse-index mark every this many records: a seek lands within
# IDX_STRIDE parsed lines of the target id instead of the whole file
IDX_STRIDE = 64


def day_of(ts: float) -> str:
    """UTC day string of a begin_ts — the tier (and stat) day key."""
    return time.strftime("%Y-%m-%d", time.gmtime(ts))


def day_start(day: str) -> float:
    """Epoch seconds of ``day`` 00:00 UTC."""
    import calendar
    return float(calendar.timegm(time.strptime(day, "%Y-%m-%d")))


def hot_cutoff_ts(now: float, hot_days: int) -> float:
    """Start of the hot window: records with begin_ts below this are
    eligible to age cold.  ``hot_days`` counts whole UTC days including
    today — hot_days=1 keeps only today hot."""
    today = day_start(day_of(now))
    return today - 86400.0 * (max(1, hot_days) - 1)


def seg_dir(db_path: str) -> Optional[str]:
    """Segment directory for a sink's backing file, or None when the
    sink has no durable path (``:memory:``) — no file, no cold tier."""
    if not db_path or db_path == ":memory:":
        return None
    return db_path + ".segs"


def seg_path(dirp: str, day: str) -> str:
    return os.path.join(dirp, day + SEG_SUFFIX)


def idx_path(path: str) -> str:
    """``<day>.idx`` sidecar next to a ``<day>.seg``."""
    return path[:-len(SEG_SUFFIX)] + IDX_SUFFIX


def _rec_line(r: LogRecord) -> str:
    return json.dumps(
        ["L", r.id, r.job_id, r.job_group, r.name, r.node, r.user,
         r.command, r.output, bool(r.success), r.begin_ts, r.end_ts],
        separators=(",", ":"), ensure_ascii=False)


def read_segment(path: str) -> List[LogRecord]:
    """Records of one segment, id ASCENDING.  A torn/garbage file reads
    as empty — segments are only consulted below the durable watermark,
    and the age-out redo rewrites any file that predates a crash."""
    out: List[LogRecord] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            header = f.readline()
            h = json.loads(header)
            if not (isinstance(h, list) and h and h[0] == "d"):
                return []
            for line in f:
                v = json.loads(line)
                if not (isinstance(v, list) and len(v) >= 12
                        and v[0] == "L"):
                    return []
                out.append(LogRecord(
                    id=int(v[1]), job_id=v[2], job_group=v[3], name=v[4],
                    node=v[5], user=v[6], command=v[7], output=v[8],
                    success=bool(v[9]), begin_ts=float(v[10]),
                    end_ts=float(v[11])))
    except (OSError, ValueError):
        return []
    out.sort(key=lambda r: r.id)
    return out


def _read_index(path: str, seg_header: list) -> Optional[List[Tuple[int,
                                                                    int]]]:
    """Sparse (id, offset) marks for ``path``'s segment, or None when
    the ``.idx`` sidecar is missing or STALE — its mirrored header must
    equal the segment's (day, count, min, max), which any crash
    ordering between the two renames fails, so a stale index can only
    cost a full scan, never a wrong seek."""
    try:
        with open(idx_path(path), "r", encoding="utf-8") as f:
            h = json.loads(f.readline())
            if not (isinstance(h, list) and len(h) >= 5 and h[0] == "i"
                    and list(h[1:5]) == list(seg_header[1:5])):
                return None
            marks = []
            for line in f:
                v = json.loads(line)
                if not (isinstance(v, list) and len(v) >= 3
                        and v[0] == "e"):
                    return None
                marks.append((int(v[1]), int(v[2])))
            return marks
    except (OSError, ValueError):
        return None


def read_segment_range(path: str, lo: Optional[int] = None,
                       hi: Optional[int] = None) -> List[LogRecord]:
    """Records of one segment with ``lo <= id <= hi``, id ASCENDING —
    the memory-mapped ranged read.  With a fresh ``.idx`` sidecar the
    scan SEEKS to within IDX_STRIDE lines of ``lo`` and stops at the
    first id past ``hi`` (ids are ascending on disk), so a single-id
    lookup or a watermark/floor-bounded cold scan parses O(stride +
    matches) lines instead of the whole day.  Missing/stale sidecars
    fall back to scanning from the top; torn or garbage files read as
    empty, exactly like ``read_segment``."""
    import bisect
    import mmap
    if lo is None and hi is None:
        return read_segment(path)
    out: List[LogRecord] = []
    try:
        with open(path, "rb") as fh:
            try:
                mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
            except (OSError, ValueError):      # empty file can't map
                return []
            try:
                end = mm.find(b"\n")
                if end < 0:
                    return []
                h = json.loads(mm[:end])
                if not (isinstance(h, list) and len(h) >= 5
                        and h[0] == "d"):
                    return []
                if lo is not None and int(h[4]) < lo:
                    return []
                if hi is not None and int(h[3]) > hi:
                    return []
                pos = end + 1
                if lo is not None:
                    marks = _read_index(path, h)
                    if marks:
                        i = bisect.bisect_right(
                            [m[0] for m in marks], lo) - 1
                        if i >= 0:
                            pos = marks[i][1]
                size = mm.size()
                while pos < size:
                    nl = mm.find(b"\n", pos)
                    if nl < 0:
                        nl = size
                    line = mm[pos:nl]
                    pos = nl + 1
                    if not line:
                        continue
                    v = json.loads(line)
                    if not (isinstance(v, list) and len(v) >= 12
                            and v[0] == "L"):
                        return []
                    rid = int(v[1])
                    if hi is not None and rid > hi:
                        break                  # ids ascend on disk
                    if lo is not None and rid < lo:
                        continue
                    out.append(LogRecord(
                        id=rid, job_id=v[2], job_group=v[3], name=v[4],
                        node=v[5], user=v[6], command=v[7], output=v[8],
                        success=bool(v[9]), begin_ts=float(v[10]),
                        end_ts=float(v[11])))
            finally:
                mm.close()
    except (OSError, ValueError):
        return []
    return out


def write_segment(dirp: str, day: str, recs: Iterable[LogRecord]) -> dict:
    """Write (or extend) ``day``'s segment with ``recs``, UNIONED by id
    with whatever the existing file holds — idempotent, so the crash
    redo and a late-record pass both converge on the same bytes.
    Atomic: temp + fdatasync + rename.  Returns the index entry
    {day, path, min, max, count}."""
    os.makedirs(dirp, exist_ok=True)
    path = seg_path(dirp, day)
    by_id: Dict[int, LogRecord] = {r.id: r for r in read_segment(path)}
    for r in recs:
        by_id[r.id] = r
    rows = [by_id[i] for i in sorted(by_id)]
    tmp = path + ".tmp"
    header = json.dumps(
        ["d", day, len(rows), rows[0].id if rows else 0,
         rows[-1].id if rows else 0],
        separators=(",", ":")) + "\n"
    marks: List[Tuple[int, int]] = []    # (id, byte offset) every stride
    with open(tmp, "wb") as f:
        f.write(header.encode("utf-8"))
        off = len(header.encode("utf-8"))
        for i, r in enumerate(rows):
            line = (_rec_line(r) + "\n").encode("utf-8")
            if i % IDX_STRIDE == 0:
                marks.append((r.id, off))
            f.write(line)
            off += len(line)
        f.flush()
        os.fdatasync(f.fileno())
    os.replace(tmp, path)
    # sparse-index sidecar: (id, offset) marks every IDX_STRIDE records
    # so ranged reads SEEK instead of parsing the whole day.  Its header
    # mirrors the segment's — a reader uses the index only when the two
    # match, so any crash ordering between the renames (fresh seg +
    # stale idx, or idx written but seg redo pending) degrades to the
    # full-scan path, never to wrong offsets.  Advisory data: a failed
    # sidecar write must not fail the durable segment write.
    try:
        itmp = idx_path(path) + ".tmp"
        with open(itmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(
                ["i", day, len(rows), rows[0].id if rows else 0,
                 rows[-1].id if rows else 0],
                separators=(",", ":")) + "\n")
            for rid, o in marks:
                f.write(json.dumps(["e", rid, o],
                                   separators=(",", ":")) + "\n")
            f.flush()
            os.fdatasync(f.fileno())
        os.replace(itmp, idx_path(path))
    except OSError:
        pass
    # fsync the DIRECTORY: the rename is only a directory-entry update,
    # and the caller durably advances the cold watermark right after —
    # a power loss could otherwise persist a watermark pointing at a
    # segment whose directory entry never hit disk (rows already
    # deleted, day unrecoverable).  Process crashes can't hit this
    # (renames survive them); power loss can.
    dfd = os.open(dirp, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    return {"day": day, "path": path,
            "min": rows[0].id if rows else 0,
            "max": rows[-1].id if rows else 0, "count": len(rows)}


def scan_segments(dirp: Optional[str]) -> List[dict]:
    """Index every segment under ``dirp`` (day ASC): [{day, path, min,
    max, count}].  Leftover ``.tmp`` files from a crashed write are
    removed — the atomic rename never published them."""
    if not dirp or not os.path.isdir(dirp):
        return []
    out = []
    for name in sorted(os.listdir(dirp)):
        path = os.path.join(dirp, name)
        if name.endswith(".tmp"):
            try:
                os.remove(path)
            except OSError:
                pass
            continue
        if not name.endswith(SEG_SUFFIX):
            continue
        day = name[:-len(SEG_SUFFIX)]
        try:
            with open(path, "r", encoding="utf-8") as f:
                h = json.loads(f.readline())
            if not (isinstance(h, list) and len(h) >= 5 and h[0] == "d"):
                continue
            out.append({"day": day, "path": path, "min": int(h[3]),
                        "max": int(h[4]), "count": int(h[2])})
        except (OSError, ValueError):
            continue
    return out


def segment_overlaps(seg: dict, begin: Optional[float],
                     end: Optional[float]) -> bool:
    """Day-level pruning: can any record in ``seg`` match a
    [begin, end) begin_ts filter?  Every record in a day's segment has
    begin_ts inside that UTC day."""
    d0 = day_start(seg["day"])
    d1 = d0 + 86400.0
    if begin is not None and d1 <= begin:
        return False
    if end is not None and d0 >= end:
        return False
    return True


def cold_query(segments: List[dict], boundary: int, match,
               begin: Optional[float] = None,
               end: Optional[float] = None,
               min_id: int = 0,
               keep: Optional[int] = None,
               hist_order: bool = False
               ) -> Tuple[List[LogRecord], int, int]:
    """Scan the cold tier: records with ``min_id < id <= boundary``
    passing ``match`` (None = everything) from every segment the
    [begin, end) filter can touch.  Returns (rows, exact match count,
    segments read).  ``boundary`` caps reads at the durable watermark
    so a segment written just before a crash (rows still hot) is never
    double-counted; ``min_id`` is the retention floor.

    ``keep`` bounds the rows RETAINED (never the count): only the
    best ``keep`` under the caller's merge order survive — id ASC
    (cursor) or (begin_ts DESC, id ASC) with ``hist_order`` (history)
    — so a 90-day cold tier never materializes millions of records to
    serve page 1.  Segments walk in merge order (newest day first for
    history) and, once ``keep`` rows are held that every record of a
    later segment must sort after, an UNFILTERED fully-visible
    segment contributes its header count without being parsed at all
    — the common unfiltered history poll reads one or two segment
    files, not the whole tier."""
    out: List[LogRecord] = []
    total = 0
    touched = 0
    if hist_order:
        sort_key = lambda r: (-r.begin_ts, r.id)      # noqa: E731
        segs = sorted(segments, key=lambda s: s["day"], reverse=True)
    else:
        sort_key = lambda r: r.id                     # noqa: E731
        segs = sorted(segments, key=lambda s: s["min"])
    full = keep is not None and len(out) >= keep      # keep == 0
    for seg in segs:
        if seg["min"] > boundary or seg["max"] <= min_id:
            continue
        if not segment_overlaps(seg, begin, end):
            continue
        # header-count fast path: the segment is wholly visible (no
        # row filtered by match/time/floor/watermark) and none of its
        # rows can displace the kept set — count without parsing
        whole = (match is None and min_id < seg["min"]
                 and seg["max"] <= boundary
                 and (begin is None or begin <= day_start(seg["day"]))
                 and (end is None
                      or end >= day_start(seg["day"]) + 86400.0))
        if whole and full:
            if hist_order:
                # out is sorted, worst kept is out[-1]; every record
                # in this OLDER day begins before out[-1]
                if out[-1].begin_ts >= day_start(seg["day"]) + 86400.0:
                    total += seg["count"]
                    continue
            else:
                if seg["min"] > out[-1].id:
                    total += seg["count"]
                    continue
        touched += 1
        # ranged read: the retention floor and the durable watermark
        # become the seek bounds — a cursor poll deep into the tier
        # seeks past everything already served instead of re-parsing it
        for r in read_segment_range(seg["path"], lo=min_id + 1,
                                    hi=boundary):
            if match is not None and not match(r):
                continue
            total += 1
            out.append(r)
        if keep is not None and len(out) > keep:
            out.sort(key=sort_key)
            del out[keep:]
            full = True
        elif keep is not None:
            out.sort(key=sort_key)
            full = len(out) >= keep
    out.sort(key=sort_key)
    return out, total, touched
