"""Result store: execution logs, latest-status, counters, accounts.

The reference keeps these in MongoDB (job_log, job_latest_log, stat, node,
account collections — db/mgo.go, job_log.go).  This rebuild uses SQLite
(stdlib, zero-dependency, single file) with the same logical schema and the
same write pattern per execution: insert log + upsert latest + bump overall
and per-day counters (job_log.go:84-133).
"""

from .joblog import JobLogStore, LogRecord  # noqa: F401
from .serve import LogSinkError, LogSinkServer, RemoteJobLogStore  # noqa: F401
