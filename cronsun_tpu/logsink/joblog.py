"""SQLite-backed execution log + stats + account storage, TIERED.

Mirrors the reference's Mongo collections and their access patterns:

- ``job_log``      — one row per execution (job_log.go:19-31)
- ``job_latest_log`` — latest row per (job, node) (job_log.go:12-16, upsert
  at job_log.go:103-117)
- ``stat``         — overall + per-day success/fail counters
  (job_log.go:118-132)
- ``node``         — liveness mirror for the UI (node.go:129-142)
- ``account``      — web users (account.go:67-105)

Thread-safe (single connection + lock; WAL mode).

Tiering (default ON; ``CRONSUN_TIERING=off`` or ``tiering=False`` is
the rollback switch and preserves the untiered behavior exactly):

- **hot tier** — in-memory mirrors behind their OWN lock (``_hot_mu``):
  the latest-per-(job, node) map, the per-day stat counters, and the
  most recent records (a contiguous id suffix, bounded by
  ``hot_max_records``), rebuilt from the DB on boot.  They answer the
  dashboard shapes — ``query_logs(latest=True)``, cursor-mode follow
  polls, ``stat_overall``/``stat_day``/``stat_days``, ``get_log`` of a
  recent id, ``revision`` and ``tail_snapshot`` — without touching
  SQL, so a poll never queues behind the write path's bulk commit.
  Results are byte-identical to the SQL path (same filters, same
  documented tie orders), pinned by a randomized differential test.
- **cold tier** — when ``hot_days`` > 0 and the store is file-backed,
  :meth:`age_out` moves records whose UTC day fell out of the hot
  window into immutable per-day segment files (``<db>.segs/<day>.seg``,
  format shared with native/logd.cc — see logsink/tiering.py) behind a
  prefix watermark (``cold_boundary``): segments are written + fsynced
  FIRST, then one SQL transaction deletes the rows and advances the
  watermark, so a crash between the two replays idempotently (the redo
  unions the same rows into the same bytes).  History/cursor queries
  that reach below the watermark merge cold + hot with the documented
  tie order; cold segments stay readable even with tiering off, so the
  rollback switch never hides data.

Per-op attribution: any read that runs SQL records op ``query_sql``;
hot-served shapes record ``q_latest_hot`` / ``q_cursor_hot`` /
``q_stat_hot`` / ``q_get_hot``; cold merges count ``q_history_cold`` /
``q_cursor_cold`` / ``q_get_cold`` — the bench's hot-hit ratio and the
CI "zero SQL on the hot shapes" smoke read these.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sqlite3
import string
import threading
import time
from collections import deque
from typing import List, Optional, Tuple

_SCHEMA = """
CREATE TABLE IF NOT EXISTS job_log (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  job_id TEXT NOT NULL, job_group TEXT NOT NULL, name TEXT NOT NULL,
  node TEXT NOT NULL, job_user TEXT DEFAULT '', command TEXT DEFAULT '',
  output TEXT DEFAULT '', success INTEGER NOT NULL,
  begin_ts REAL NOT NULL, end_ts REAL NOT NULL);
CREATE INDEX IF NOT EXISTS il_job ON job_log(job_id, begin_ts DESC);
CREATE INDEX IF NOT EXISTS il_node ON job_log(node, begin_ts DESC);
CREATE INDEX IF NOT EXISTS il_begin ON job_log(begin_ts DESC);

CREATE TABLE IF NOT EXISTS job_latest_log (
  job_id TEXT NOT NULL, node TEXT NOT NULL,
  job_group TEXT NOT NULL, name TEXT NOT NULL,
  job_user TEXT DEFAULT '', command TEXT DEFAULT '', output TEXT DEFAULT '',
  success INTEGER NOT NULL, begin_ts REAL NOT NULL, end_ts REAL NOT NULL,
  PRIMARY KEY (job_id, node));

CREATE TABLE IF NOT EXISTS stat (
  day TEXT PRIMARY KEY,           -- '' = overall
  total INTEGER NOT NULL DEFAULT 0,
  successed INTEGER NOT NULL DEFAULT 0,
  failed INTEGER NOT NULL DEFAULT 0);

CREATE TABLE IF NOT EXISTS node (
  id TEXT PRIMARY KEY, doc TEXT NOT NULL, alived INTEGER NOT NULL DEFAULT 0);

CREATE TABLE IF NOT EXISTS account (
  email TEXT PRIMARY KEY, doc TEXT NOT NULL);

CREATE TABLE IF NOT EXISTS meta (
  k TEXT PRIMARY KEY, v TEXT NOT NULL);
"""

# SQLite's default LIKE is case-insensitive for ASCII ONLY; the hot
# path must match it (and native/logd.cc's contains_nocase) exactly
_ASCII_LOWER = str.maketrans(string.ascii_uppercase, string.ascii_lowercase)


@dataclasses.dataclass
class LogRecord:
    job_id: str
    job_group: str
    name: str
    node: str
    user: str
    command: str
    output: str
    success: bool
    begin_ts: float
    end_ts: float
    id: Optional[int] = None

    @property
    def seconds(self) -> float:
        return max(0.0, self.end_ts - self.begin_ts)


_UNSET = object()


class SubscriptionLost(Exception):
    """The subscriber fell behind its bounded buffer (or the stream
    died): pending events were dropped.  The consumer re-lists (cursor
    query from its last delivered id) and re-subscribes — the store
    watch plane's ``WatchLost`` contract, result-plane edition."""


def sub_event(r: LogRecord) -> tuple:
    """The change-stream summary of one record: the 8 fields a
    dashboard row needs, WITHOUT user/command/output (a stream carrying
    every job's stdout would make one chatty job the fan-out's
    bandwidth ceiling; the detail endpoint serves bodies by id).  Wire
    form is the same fields as a JSON list, both backends byte-alike:
    ``[id, job_id, job_group, name, node, success, begin_ts,
    end_ts]``."""
    return (r.id, r.job_id, r.job_group, r.name, r.node, r.success,
            r.begin_ts, r.end_ts)


class LogSubscription:
    """A bounded, lossy, per-subscriber event buffer (the store's
    watcher shape: ``on_ready`` callback for pump loops, blocking
    ``get`` for thread-per-subscription consumers).  Writers push
    summaries; overflow drops EVERYTHING pending and latches ``lost``
    — a slow consumer costs itself a re-list, never the writer a
    stall."""

    def __init__(self, store, cap: int = 4096):
        self._store = store
        self._cap = max(1, int(cap))
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._buf: deque = deque()
        self.lost = False
        self.closed = False
        # set by subscribe(): the revision the stream starts after, and
        # whether the requested resume gap was NOT replayable (the
        # consumer re-lists once; the stream itself is live from rev)
        self.rev = 0
        self.gap = False
        self.on_ready = None       # pump nudge: called outside _mu

    def _push(self, evs) -> None:
        """Writer side — events for this subscriber (already
        filtered/ordered).  Never blocks."""
        if not evs:
            return
        with self._cv:
            if self.lost or self.closed:
                return
            if len(self._buf) + len(evs) > self._cap:
                self._buf.clear()
                self.lost = True
            else:
                self._buf.extend(evs)
            self._cv.notify_all()
            ready = self.on_ready
        if ready is not None:
            ready(self)

    def drain(self) -> list:
        """All pending events, non-blocking.  Raises
        :class:`SubscriptionLost` once the buffer overflowed (after
        which the subscription is dead)."""
        with self._cv:
            if self.lost:
                raise SubscriptionLost("log subscription overflowed")
            out = list(self._buf)
            self._buf.clear()
        return out

    def get(self, timeout: Optional[float] = None) -> list:
        """Pending events, blocking up to ``timeout`` for the first one
        (empty list on timeout).  Raises :class:`SubscriptionLost` when
        the buffer overflowed or the stream closed under the consumer."""
        with self._cv:
            if not self._buf and not self.lost and not self.closed:
                self._cv.wait(timeout)
            if self.lost:
                raise SubscriptionLost("log subscription overflowed")
            if self.closed and not self._buf:
                raise SubscriptionLost("log subscription closed")
            out = list(self._buf)
            self._buf.clear()
        return out

    def close(self):
        store, self._store = self._store, None
        if store is not None:
            store.unsubscribe(self)
        with self._cv:
            self.closed = True
            self._cv.notify_all()


def copy_rec(r: LogRecord, id=_UNSET) -> LogRecord:
    """Positional-field copy — ~6x faster than dataclasses.replace
    (which routes through __init__ via a keyword dict); the hot read
    paths copy every returned row, so this is per-poll cost."""
    return LogRecord(r.job_id, r.job_group, r.name, r.node, r.user,
                     r.command, r.output, r.success, r.begin_ts,
                     r.end_ts, r.id if id is _UNSET else id)


def tiering_default() -> bool:
    """The rollback switch: ``CRONSUN_TIERING=off`` disables the hot
    mirrors (and day-based aging) everywhere — today's scan-per-poll
    behavior, exactly."""
    return os.environ.get("CRONSUN_TIERING", "").lower() not in (
        "off", "0", "false")


class JobLogStore:
    """``retain`` > 0 bounds execution-history rows (oldest evicted on
    insert), mirroring the native logd's --retain: the stats counters
    and the latest-status table — which summarize all history — are
    never evicted, so dashboards stay exact while disk stays bounded.
    The reference keeps Mongo job_log forever (no TTL index anywhere in
    /root/reference/db or job_log.go) — unbounded (0) matches that, the
    cap is the operational improvement.

    ``hot_days`` > 0 (file-backed stores only) turns on cold aging:
    days out of the hot window move to immutable segment files (see
    module docstring).  ``hot_max_records`` bounds the in-memory record
    mirror; reads below it fall back to SQL, correctness unchanged."""

    def __init__(self, path: str = ":memory:", retain: int = 0,
                 tiering: Optional[bool] = None, hot_days: int = 0,
                 hot_max_records: int = 200_000):
        self._lock = threading.RLock()
        self._retain = max(0, int(retain))
        self._path = path
        self._tier = tiering_default() if tiering is None else bool(tiering)
        self._hot_days = max(0, int(hot_days))
        self._hot_max = max(1, int(hot_max_records))
        # per-op timing (memstore.op_stats parity): lets a bench — and
        # /v1/metrics — attribute the result plane's ceiling to a named
        # op (bulk create vs query) instead of "the sink"
        from ..metrics import OpStats
        self._ops = OpStats()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.row_factory = sqlite3.Row
        # hot-tier state: its OWN lock, so dashboard reads never queue
        # behind the SQL lock a bulk flush is committing under.  Writers
        # mutate the mirrors INSIDE self._lock (ordering) but only hold
        # _hot_mu for the in-memory update.
        self._hot_mu = threading.Lock()
        self._h_latest: dict = {}          # (job_id, node) -> LogRecord
        self._h_latest_sorted = None       # memo: pinned-order view, or
        #   None after any latest change — a dashboard polling between
        #   write batches reuses the sort instead of re-keying 512 rows
        self._h_stats: dict = {}           # day ('' = overall) -> [t, s, f]
        self._h_recs: deque = deque()      # contiguous-id record suffix
        self._h_rev = 0                    # max id ever assigned
        self._cold_boundary = 0            # ids <= this live in segments
        self._segments: list = []          # tiering.scan_segments index
        self._age_mu = threading.Lock()    # one age-out pass at a time
        # change-stream plane: live subscriptions (registered and fed
        # under self._lock, so a subscriber snapshot-then-register can
        # never miss a record between its revision and its first event)
        self._subs: dict = {}
        # trace plane: bounded span ring + per-day spill beside the
        # tiered store's segment directory (file-backed sinks only)
        from .traces import TraceStore
        self.traces = TraceStore(
            spill_dir=None if path == ":memory:" else path + ".traces")
        with self._lock:
            if path != ":memory:":
                self._db.execute("PRAGMA journal_mode=WAL")
                # WAL + NORMAL: no fsync per commit (the WAL is synced at
                # checkpoint); a power loss can drop the last moments of
                # execution history but cannot corrupt the DB — the right
                # trade for a result log whose writers retry anyway, and
                # ~10-20x the sustained create_job_log rate (the fsync was
                # the dispatch plane's bottleneck, not the store)
                self._db.execute("PRAGMA synchronous=NORMAL")
                self._db.execute("PRAGMA busy_timeout=5000")
            self._db.executescript(_SCHEMA)
            self._db.commit()
            self._boot_tiers()

    def _boot_tiers(self):
        """Rebuild the hot mirrors from the DB and index the cold
        segments — called under self._lock at boot.  The segment index
        and watermark load regardless of the tiering switch (a rollback
        must not hide already-aged data); the mirrors only when on."""
        from . import tiering as tg
        r = self._db.execute(
            "SELECT v FROM meta WHERE k='cold_boundary'").fetchone()
        self._cold_boundary = int(r["v"]) if r else 0
        self._segments = tg.scan_segments(tg.seg_dir(self._path))
        self._h_rev = self._sql_revision()
        if not self._tier:
            return
        for row in self._db.execute("SELECT * FROM stat"):
            self._h_stats[row["day"]] = [row["total"], row["successed"],
                                         row["failed"]]
        for row in self._db.execute("SELECT * FROM job_latest_log"):
            rec = self._row_to_rec(row, True)
            self._h_latest[(rec.job_id, rec.node)] = rec
        rows = self._db.execute(
            "SELECT * FROM job_log ORDER BY id DESC LIMIT ?",
            (self._hot_max,)).fetchall()
        for row in reversed(rows):
            self._h_recs.append(self._row_to_rec(row, False))

    def close(self):
        self.traces.close()
        with self._lock:
            for s in list(self._subs.values()):
                with s._cv:
                    s.closed = True
                    s._cv.notify_all()
            self._subs.clear()
            self._db.close()

    # ---- trace plane (fire-lifecycle spans) ------------------------------

    def trace_ingest(self, spans: list) -> int:
        t0 = time.perf_counter_ns()
        n = self.traces.ingest(spans)
        self._op_record("trace_ingest", t0)
        return n

    def trace_get(self, job_id: str, epoch_s: int) -> list:
        """Raw span dicts of one (job, second) trace — the web tier
        assembles the waterfall (trace.assemble)."""
        t0 = time.perf_counter_ns()
        out = self.traces.get(job_id, int(epoch_s))
        self._op_record("trace_get", t0)
        return out

    def trace_top(self, n: int = 256) -> list:
        return self.traces.top(int(n))

    def trace_stats(self) -> dict:
        """Cumulative per-stage histogram counters (fixed fleet-wide
        buckets — addable across shards and replicas)."""
        return self.traces.stats()

    # ---- op timing (delegates to the shared metrics.OpStats) -------------

    def _op_record(self, op: str, t0_ns: int):
        self._ops.record(op, t0_ns)

    def op_count(self, op: str, n: int = 1):
        """Count-only stat (no timing): per-record tallies under the
        bulk op — log_records / create_job_logs gives the observed
        batch size."""
        self._ops.count(op, n)

    def op_stats(self) -> dict:
        """Per-op timing snapshot: {op: {count, total_ms, max_ms}}."""
        return self._ops.snapshot()

    # ---- writes (the 4-write pattern of CreateJobLog) --------------------

    def create_job_log(self, rec: LogRecord, idem: str = ""):
        # ``idem`` is accepted for surface parity with the networked
        # sink (the agents' per-record degraded path passes a stable
        # token); in-process writes have no reply to lose, so unused
        del idem
        t0 = time.perf_counter_ns()
        with self._lock:
            day = self._create_locked(rec)
            self._db.commit()
            if self._tier:
                ok = 1 if rec.success else 0
                with self._hot_mu:
                    self._mirror_locked([(rec, ok)],
                                        {day: (1, ok, 1 - ok)}, rec.id)
            if self._subs:
                self._sub_emit([rec])
        self._op_record("create_job_log", t0)

    def _create_locked(self, rec: LogRecord) -> str:
        """The 4-write pattern, no commit — caller owns the transaction.
        Returns the record's day key."""
        day = time.strftime("%Y-%m-%d", time.gmtime(rec.begin_ts))
        ok = 1 if rec.success else 0
        self._insert_log_locked(rec, ok)
        if self._retain:
            # ids stay monotone (only the oldest rows are ever
            # deleted, so max rowid never frees), making the cap a
            # single indexed range delete per insert
            self._db.execute("DELETE FROM job_log WHERE id <= ?",
                             (rec.id - self._retain,))
        self._upsert_latest_locked(rec, ok)
        for d in ("", day):
            self._bump_stat_locked(d, 1, ok, 1 - ok)
        return day

    # the three statements of the 4-write pattern, shared by the single
    # path (one each per record) and the bulk path (insert per record,
    # latest/stat coalesced per batch) so the SQL exists exactly once

    def _insert_log_locked(self, rec: LogRecord, ok: int) -> int:
        cur = self._db.execute(
            "INSERT INTO job_log (job_id, job_group, name, node, "
            "job_user, command, output, success, begin_ts, end_ts) "
            "VALUES (?,?,?,?,?,?,?,?,?,?)",
            (rec.job_id, rec.job_group, rec.name, rec.node, rec.user,
             rec.command, rec.output, ok, rec.begin_ts, rec.end_ts))
        rec.id = cur.lastrowid
        return rec.id

    def _upsert_latest_locked(self, rec: LogRecord, ok: int):
        self._db.execute(
            "INSERT INTO job_latest_log VALUES (?,?,?,?,?,?,?,?,?,?) "
            "ON CONFLICT(job_id, node) DO UPDATE SET "
            "job_group=excluded.job_group, name=excluded.name, "
            "job_user=excluded.job_user, command=excluded.command, "
            "output=excluded.output, success=excluded.success, "
            "begin_ts=excluded.begin_ts, end_ts=excluded.end_ts",
            (rec.job_id, rec.node, rec.job_group, rec.name, rec.user,
             rec.command, rec.output, ok, rec.begin_ts, rec.end_ts))

    def _bump_stat_locked(self, day: str, total: int, ok_n: int,
                          fail_n: int):
        self._db.execute(
            "INSERT INTO stat (day, total, successed, failed) "
            "VALUES (?,?,?,?) ON CONFLICT(day) DO UPDATE SET "
            "total=total+excluded.total, "
            "successed=successed+excluded.successed, "
            "failed=failed+excluded.failed",
            (day, total, ok_n, fail_n))

    def _mirror_locked(self, recs_ok, day_deltas: dict, last_id: int):
        """Apply a committed batch to the hot mirrors — caller holds
        ``_hot_mu``.  ``recs_ok`` is [(rec, ok)] in insert order;
        records are COPIED in (callers — the sharded client, tests —
        mutate rec.id after create; the mirror must keep the raw id)."""
        for rec, ok in recs_ok:
            cp = copy_rec(rec)
            self._h_recs.append(cp)
            # mirror entries are REPLACED, never mutated in place: a
            # reader borrowing the sorted memo outside the lock keeps a
            # consistent snapshot
            self._h_latest[(cp.job_id, cp.node)] = copy_rec(cp, id=None)
        self._h_latest_sorted = None
        for day, (t, s, f) in day_deltas.items():
            for d in ("", day) if day else ("",):
                ent = self._h_stats.setdefault(d, [0, 0, 0])
                ent[0] += t
                ent[1] += s
                ent[2] += f
        self._h_rev = last_id
        floor = last_id - self._retain if self._retain else 0
        while self._h_recs and (self._h_recs[0].id <= floor
                                or len(self._h_recs) > self._hot_max):
            self._h_recs.popleft()

    def create_job_logs(self, recs, idem: str = "",
                        spans: Optional[list] = None) -> list:
        """Bulk insert: the agents' record flushers write whole batches
        in ONE transaction (one fsync).  The per-record side writes
        COALESCE per batch — one stat UPDATE per (day) touched plus one
        for the overall row, one latest-log upsert per (job, node)
        (the last record in batch order wins, exactly the sequential
        outcome), one retention trim — so a 1k-record batch pays ~4
        auxiliary statements, not 4k.  The hot mirrors apply the whole
        batch under ONE ``_hot_mu`` hold, so a concurrent hot read sees
        none or all of it — the same all-or-nothing a reader of the SQL
        transaction sees.  Returns the assigned row ids in order.
        ``idem`` is accepted for surface parity with the networked
        sink; in-process writes have no reply to lose, so it is
        unused.  ``spans`` is the trace plane's piggybacked sidecar —
        ingested into the trace ring/spill before the row writes (its
        merge is LWW-idempotent, so ordering vs the transaction does
        not matter)."""
        del idem
        if spans:
            self.trace_ingest(spans)
        if not recs:
            return []
        t0 = time.perf_counter_ns()
        with self._lock:
            try:
                ids = []
                latest: dict = {}
                days: dict = {}
                mirror = []
                for rec in recs:
                    day = time.strftime("%Y-%m-%d",
                                        time.gmtime(rec.begin_ts))
                    ok = 1 if rec.success else 0
                    ids.append(self._insert_log_locked(rec, ok))
                    mirror.append((rec, ok))
                    latest[(rec.job_id, rec.node)] = (rec, ok)
                    t, s, f = days.get(day, (0, 0, 0))
                    days[day] = (t + 1, s + ok, f + 1 - ok)
                if self._retain:
                    # ids stay monotone (only the oldest rows are ever
                    # deleted), making the cap one indexed range delete
                    # per batch
                    self._db.execute("DELETE FROM job_log WHERE id <= ?",
                                     (ids[-1] - self._retain,))
                for rec, ok in latest.values():
                    self._upsert_latest_locked(rec, ok)
                totals = [sum(v[i] for v in days.values())
                          for i in range(3)]
                for d, (t, s, f) in [("", tuple(totals))] + \
                        sorted(days.items()):
                    self._bump_stat_locked(d, t, s, f)
                self._db.commit()
            except Exception:
                # all-or-nothing: a mid-batch failure (SQLITE_BUSY past
                # the busy timeout, disk full) must not leave the head
                # rows pending in the implicit transaction — the
                # caller's retry re-sends the WHOLE batch, and a later
                # unrelated commit would otherwise flush the stale head
                # alongside it (duplicated rows + double-counted stats)
                self._db.rollback()
                raise
            if self._tier:
                with self._hot_mu:
                    self._mirror_locked(mirror, days, ids[-1])
            if self._subs:
                self._sub_emit([r for r, _ in mirror])
        self._op_record("create_job_logs", t0)
        self.op_count("log_records", len(ids))
        return ids

    # ---- queries (web/job_log.go:18-113) ---------------------------------

    @staticmethod
    def _hot_match(node, job_ids, name_like, begin, end, failed_only):
        """Predicate replicating the SQL WHERE semantics exactly:
        substring name match is ASCII-case-insensitive (SQLite's
        default LIKE; native contains_nocase pins the same).  Returns
        None when there is nothing to filter (every row matches)."""
        if not (node or job_ids or name_like or failed_only) and \
                begin is None and end is None:
            return None
        needle = name_like.translate(_ASCII_LOWER) if name_like else None
        job_set = set(job_ids) if job_ids else None

        def match(r: LogRecord) -> bool:
            if node and r.node != node:
                return False
            if job_set is not None and r.job_id not in job_set:
                return False
            if needle is not None and \
                    needle not in r.name.translate(_ASCII_LOWER):
                return False
            if begin is not None and r.begin_ts < begin:
                return False
            if end is not None and r.begin_ts >= end:
                return False
            if failed_only and r.success:
                return False
            return True
        return match

    def _retain_floor(self, rev: int) -> int:
        """Records with id <= floor are evicted in the untiered store —
        the tiered read path filters cold rows to the same visible set
        so the two layouts answer byte-identically."""
        return rev - self._retain if self._retain else 0

    def query_logs(self, node: Optional[str] = None,
                   job_ids: Optional[List[str]] = None,
                   name_like: Optional[str] = None,
                   begin: Optional[float] = None,
                   end: Optional[float] = None,
                   failed_only: bool = False,
                   latest: bool = False,
                   page: int = 1, page_size: int = 50,
                   after_id: Optional[int] = None
                   ) -> Tuple[List[LogRecord], int]:
        """``after_id`` switches to cursor mode: only rows with
        ``id > after_id``, ordered by id ASCENDING — insertion order, so
        a poller (cronsun-ctl logs --follow) never misses a record that
        was inserted with an old begin_ts (ids are monotone; begin_ts is
        not).  Ignored for the latest view, whose rows have no id.

        Cursor mode returns ``total == -1``: the poller advances its
        cursor from the delivered ids and never reads the total, but
        computing it cost a full filtered COUNT(*) scan PER POLL — the
        one O(history) term left on the follow path.  Both backends
        pin the same -1.

        Tiered serving: the latest view and cursor polls that start at
        or above the hot window come straight from the mirrors (no
        SQL); history — and a cursor resuming below the cold watermark
        — merges SQL rows with the cold segments under the documented
        tie orders, byte-identical to an untiered store fed the same
        stream."""
        # clamp absurd page numbers (empty page, never an overflow —
        # the native backend pins the same bound)
        page = max(1, min(page, 1 << 40))
        page_size = max(1, min(page_size, 500))
        cursor_mode = after_id is not None and not latest
        if cursor_mode:
            after_id = int(after_id)
        match = self._hot_match(node, job_ids, name_like, begin, end,
                                failed_only)
        if self._tier and latest:
            return self._query_latest_hot(match, page, page_size)
        if self._tier and cursor_mode:
            hot = self._query_cursor_hot(match, after_id, page, page_size)
            if hot is not None:
                return hot
        return self._query_sql(node, job_ids, name_like, begin, end,
                               failed_only, latest, page, page_size,
                               after_id, cursor_mode, match)

    def _query_latest_hot(self, match, page, page_size):
        """The dashboard's landing view from the latest mirror: filter
        + the pinned (begin_ts DESC, job_id, node) order + paging, no
        SQL, no SQL lock.  The sort is memoized on the mirror
        generation — polls between write batches (the common dashboard
        cadence) filter a pre-sorted immutable list instead of
        re-keying every row."""
        t0 = time.perf_counter_ns()
        with self._hot_mu:
            lst = self._h_latest_sorted
            if lst is None:
                lst = sorted(self._h_latest.values(),
                             key=lambda r: (-r.begin_ts, r.job_id,
                                            r.node))
                self._h_latest_sorted = lst
        # outside the lock: writers REPLACE the memo (never mutate it
        # or its rows), so this borrowed list is a stable snapshot —
        # and the returned page SHARES its rows (id-less latest rows
        # are never mutated by any caller: the sharded client only
        # re-encodes ids, and there are none), so the common
        # unfiltered dashboard poll is a slice, not 500 copies
        rows = lst if match is None else [r for r in lst if match(r)]
        total = len(rows)
        out = rows[(page - 1) * page_size: page * page_size]
        self._op_record("q_latest_hot", t0)
        return list(out), total

    def _query_cursor_hot(self, match, after_id, page, page_size):
        """Follow-poll fast path: when every id > after_id is inside
        the record mirror, answer from the deque (ids are contiguous —
        the jump is an index, the scan O(new records)).  Returns None
        when the cursor reaches below the mirror (SQL/cold fallback)."""
        t0 = time.perf_counter_ns()
        with self._hot_mu:
            if self._h_recs:
                front = self._h_recs[0].id
                covered = after_id >= front - 1
            else:
                covered = after_id >= self._h_rev
            if not covered:
                return None
            hits = []
            start = max(0, after_id - self._h_recs[0].id + 1) \
                if self._h_recs else 0
            need = page * page_size
            # islice, not positional indexing: deque[i] walks from the
            # nearest end, turning a long scan O(n^2)
            from itertools import islice
            for r in islice(self._h_recs, start, None):
                if match is None or match(r):
                    hits.append(r)
                    if len(hits) >= need:
                        break
            # cursor rows are copied: the sharded client re-encodes
            # their ids in place
            out = [copy_rec(r)
                   for r in hits[(page - 1) * page_size:]]
        self._op_record("q_cursor_hot", t0)
        return out, -1

    def _sql_rows(self, cond: str, args: list, order: str,
                  need: int) -> List[LogRecord]:
        """Up to ``need`` job_log rows under ``cond`` in ``order`` —
        the SQL side of a tier merge."""
        rows = self._db.execute(
            f"SELECT * FROM job_log{cond} ORDER BY {order} LIMIT ?",
            args + [need]).fetchall()
        return [self._row_to_rec(r, False) for r in rows]

    def _query_sql(self, node, job_ids, name_like, begin, end,
                   failed_only, latest, page, page_size, after_id,
                   cursor_mode, match):
        table = "job_latest_log" if latest else "job_log"
        where, args = [], []
        if cursor_mode:
            where.append("id > ?"); args.append(after_id)
        if node:
            where.append("node = ?"); args.append(node)
        if job_ids:
            where.append(f"job_id IN ({','.join('?' * len(job_ids))})")
            args.extend(job_ids)
        if name_like:
            # plain substring semantics: LIKE metacharacters in the
            # needle are escaped so both result-store backends (this
            # SQLite one and the native in-memory one) agree
            esc = (name_like.replace("\\", "\\\\")
                   .replace("%", r"\%").replace("_", r"\_"))
            where.append(r"name LIKE ? ESCAPE '\'")
            args.append(f"%{esc}%")
        if begin is not None:
            where.append("begin_ts >= ?"); args.append(begin)
        if end is not None:
            where.append("begin_ts < ?"); args.append(end)
        if failed_only:
            where.append("success = 0")
        cond = (" WHERE " + " AND ".join(where)) if where else ""
        t0 = time.perf_counter_ns()
        need = page * page_size
        from . import tiering as tg
        with self._lock:
            # cold participation: only history/cursor reads that can
            # reach below the watermark (never the latest view — its
            # rows summarize all history and live hot/in SQL)
            cold_rows: List[LogRecord] = []
            cold_total = 0
            boundary = self._cold_boundary
            if self._segments and not latest and \
                    (not cursor_mode or after_id < boundary):
                rev = self._h_rev if self._tier else self._sql_revision()
                cold_rows, cold_total, touched = tg.cold_query(
                    self._segments, boundary, match, begin, end,
                    min_id=max(self._retain_floor(rev),
                               after_id if cursor_mode else 0),
                    keep=need, hist_order=not cursor_mode)
                if touched:
                    self.op_count("q_cursor_cold" if cursor_mode
                                  else "q_history_cold")
            if cursor_mode:
                total = -1
                if cold_rows:
                    # cold ids all precede SQL ids: concatenation IS
                    # id-ascending order
                    rows = (cold_rows[:need] +
                            self._sql_rows(cond, args, "id ASC", need))
                    rows = rows[(page - 1) * page_size: page * page_size]
                else:
                    rows = [self._row_to_rec(r, False) for r in
                            self._db.execute(
                                f"SELECT * FROM {table}{cond} ORDER BY "
                                "id ASC LIMIT ? OFFSET ?",
                                args + [page_size,
                                        (page - 1) * page_size])]
            else:
                total = self._db.execute(
                    f"SELECT COUNT(*) c FROM {table}{cond}",
                    args).fetchone()["c"] + cold_total
                # tie order pinned explicitly (id ASC within equal
                # begin_ts; the id-less latest view breaks ties by its
                # (job_id, node) primary key) so the native backend —
                # and the sharded client's scatter-gather merge — page
                # identically
                order = "begin_ts DESC" + (", job_id ASC, node ASC"
                                           if latest else ", id ASC")
                if cold_rows:
                    hot = self._sql_rows(cond, args, order, need)
                    cold_rows.sort(key=lambda r: (-r.begin_ts, r.id))
                    merged = sorted(cold_rows[:need] + hot,
                                    key=lambda r: (-r.begin_ts, r.id))
                    rows = merged[(page - 1) * page_size:
                                  page * page_size]
                else:
                    rows = [self._row_to_rec(r, latest) for r in
                            self._db.execute(
                                f"SELECT * FROM {table}{cond} ORDER BY "
                                f"{order} LIMIT ? OFFSET ?",
                                args + [page_size,
                                        (page - 1) * page_size])]
        self._op_record("query_sql", t0)
        return rows, total

    # get_log serves from the mirror only this close to the tail:
    # deque indexing walks from the nearest end, so a mid-mirror id at
    # hot_max_records=200k would cost a ~100k-node walk where the SQL
    # primary-key fetch is an O(log n) B-tree probe — "recent" ids are
    # the hot contract, the rest belong to SQL
    GET_HOT_TAIL = 1024

    def get_log(self, log_id: int) -> Optional[LogRecord]:
        log_id = int(log_id)
        if self._tier:
            t0 = time.perf_counter_ns()
            with self._hot_mu:
                if self._h_recs and \
                        self._h_recs[0].id <= log_id <= self._h_recs[-1].id \
                        and log_id >= self._h_recs[-1].id - self.GET_HOT_TAIL:
                    r = self._h_recs[log_id - self._h_recs[-1].id - 1]
                    self._op_record("q_get_hot", t0)
                    return copy_rec(r)
        with self._lock:
            boundary = self._cold_boundary
            if self._segments and log_id <= boundary:
                rev = self._h_rev if self._tier else self._sql_revision()
                if log_id <= self._retain_floor(rev):
                    return None
                from . import tiering as tg
                for seg in self._segments:
                    if seg["min"] <= log_id <= seg["max"]:
                        # sparse-index seek: parses O(stride) lines of
                        # the day, not the whole segment
                        for r in tg.read_segment_range(
                                seg["path"], lo=log_id, hi=log_id):
                            if r.id == log_id:
                                self.op_count("q_get_cold")
                                return r
                return None
            t0 = time.perf_counter_ns()
            r = self._db.execute("SELECT * FROM job_log WHERE id = ?",
                                 (log_id,)).fetchone()
            self._op_record("query_sql", t0)
        return self._row_to_rec(r, False) if r else None

    @staticmethod
    def _row_to_rec(r, latest: bool) -> LogRecord:
        return LogRecord(
            id=None if latest else r["id"],
            job_id=r["job_id"], job_group=r["job_group"], name=r["name"],
            node=r["node"], user=r["job_user"], command=r["command"],
            output=r["output"], success=bool(r["success"]),
            begin_ts=r["begin_ts"], end_ts=r["end_ts"])

    # ---- change revision + tail snapshot + topology pin ------------------

    def _sql_revision(self) -> int:
        r = self._db.execute(
            "SELECT seq FROM sqlite_sequence WHERE name='job_log'"
        ).fetchone()
        return int(r["seq"]) if r else 0

    def revision(self) -> int:
        """Monotone change token for the read plane: the max record id
        ever assigned (0 when empty).  Every create bumps it; retention
        trims only the oldest rows so it never regresses — the web
        tier's revision-keyed ETag (and a follow poller's tail
        bootstrap) key off this instead of re-running the query.

        Tiered, this reads the mirror — which advances in the same
        critical section that makes the records queryable, so a cursor
        bootstrapped at this revision can never skip a record that was
        visible before it."""
        if self._tier:
            with self._hot_mu:
                return self._h_rev
        with self._lock:
            return self._sql_revision()

    def tail_snapshot(self, limit: int = 0) -> Tuple[int, List[LogRecord]]:
        """Revision AND the last ``limit`` records from ONE snapshot
        (one lock acquisition).  The follow bootstrap needs both
        atomically: reading them in two steps lets a record land in
        between — present in neither the tail page nor the follow
        stream keyed ``id > revision`` — and be skipped forever."""
        limit = max(0, min(int(limit), 500))
        if self._tier:
            from itertools import islice
            with self._hot_mu:
                rev = self._h_rev
                n = len(self._h_recs)
                recs = [copy_rec(r) for r in
                        islice(self._h_recs, max(0, n - limit), None)]
            return rev, recs
        with self._lock:
            rev = self._sql_revision()
            rows = self._db.execute(
                "SELECT * FROM job_log ORDER BY id DESC LIMIT ?",
                (limit,)).fetchall() if limit else []
        return rev, [self._row_to_rec(r, False) for r in reversed(rows)]

    # ---- change stream (the store watch plane, result-plane edition) -----

    def subscribe(self, after_id: int = 0, cap: int = 4096
                  ) -> LogSubscription:
        """Open a live event stream of new-record summaries.

        ``after_id`` <= 0 (or >= revision) starts from NOW; a positive
        cursor replays the gap ``(after_id, revision]`` when the store
        can still prove completeness — from the contiguous hot deque or
        from SQL rows above the retention/cold floor — and otherwise
        sets ``sub.gap`` (the consumer re-lists once; the stream itself
        is live from ``sub.rev`` regardless).  ``cap`` bounds the
        per-subscriber buffer: overflow drops everything pending and
        latches ``lost`` (store watch semantics).

        Registration and the revision snapshot share one ``self._lock``
        hold with the write path's emission, so no record can land
        between the snapshot and the first event."""
        t0 = time.perf_counter_ns()
        after_id = int(after_id)
        with self._lock:
            if self._tier:
                with self._hot_mu:
                    rev = self._h_rev
            else:
                rev = self._sql_revision()
            sub = LogSubscription(self, cap)
            sub.rev = rev
            replay: list = []
            if 0 < after_id < rev:
                served = False
                if self._tier:
                    with self._hot_mu:
                        if self._h_recs and \
                                self._h_recs[0].id <= after_id + 1:
                            # contiguous-id invariant: the deque holds
                            # EVERY id in [head, rev], so covering
                            # after_id+1 proves the replay is complete
                            replay = [sub_event(r) for r in self._h_recs
                                      if r.id > after_id]
                            served = True
                if not served:
                    floor = max(self._retain_floor(rev),
                                self._cold_boundary)
                    if after_id < floor:
                        sub.gap = True
                    else:
                        rows = self._db.execute(
                            "SELECT * FROM job_log WHERE id > ? "
                            "ORDER BY id ASC", (after_id,)).fetchall()
                        replay = [sub_event(self._row_to_rec(r, False))
                                  for r in rows]
            self._subs[id(sub)] = sub
            if replay:
                sub._push(replay)
        self._op_record("subscribe", t0)
        return sub

    def unsubscribe(self, sub: LogSubscription) -> None:
        with self._lock:
            self._subs.pop(id(sub), None)

    def _sub_emit(self, recs) -> None:
        """Fan a committed batch to every live subscription — called
        under ``self._lock`` from both create paths, AFTER the commit
        (an event must never precede the row it announces)."""
        evs = [sub_event(r) for r in recs]
        self.op_count("sub_events", len(evs) * len(self._subs))
        dead = []
        for k, s in self._subs.items():
            s._push(evs)
            if s.lost or s.closed:
                dead.append(k)
        for k in dead:
            self._subs.pop(k, None)

    def logmap(self, n=None, hash=None):
        """The sharded-result-plane topology pin (the store's shardmap,
        result-plane edition): with arguments, publish {n, hash} if no
        pin exists yet and return whatever pin now holds; without
        arguments, a read-only peek (None when unpinned).  Lives on
        shard 0 by fiat so a client can check it knowing only the
        address list; a mismatched client refuses to start instead of
        scattering one job's history under two layouts."""
        with self._lock:
            if n is not None:
                self._db.execute(
                    "INSERT OR IGNORE INTO meta VALUES ('logmap', ?)",
                    (json.dumps({"n": int(n), "hash": hash},
                                sort_keys=True),))
                self._db.commit()
            r = self._db.execute(
                "SELECT v FROM meta WHERE k='logmap'").fetchone()
        return json.loads(r["v"]) if r else None

    # ---- cold aging (the retention sweeper's tier move) ------------------

    AGE_PASS_RECORDS = 50_000

    def age_out(self, now: Optional[float] = None) -> int:
        """Move every record whose UTC day fell out of the hot window
        (``hot_days`` whole days including today) into its day's
        immutable segment file, then trim it from SQL and the mirror.

        Crash-safe by ordering: segments are written + fsynced FIRST
        (union by id — a redo converges on the same bytes), then ONE
        SQL transaction deletes the rows and advances the durable
        ``cold_boundary`` watermark.  A kill -9 anywhere in between
        leaves the rows hot and the watermark behind — reads stay
        exact (cold is only consulted at or below the watermark) and
        the next pass redoes the move idempotently.

        Runs in bounded PASSES of ``AGE_PASS_RECORDS`` each: the first
        enablement on an unbounded store may face millions of rows,
        and one monolithic SELECT would hold the SQL lock (and that
        many LogRecords in memory) for the duration — each pass keeps
        the lock hold and peak memory bounded, and the loop (still one
        pass at a time under ``_age_mu``) continues until the cutoff
        is reached.  Returns the number of records aged."""
        from . import tiering as tg
        dirp = tg.seg_dir(self._path)
        if not self._tier or self._hot_days <= 0 or dirp is None:
            return 0
        t0 = time.perf_counter_ns()
        cutoff = tg.hot_cutoff_ts(now if now is not None else time.time(),
                                  self._hot_days)
        total = 0
        with self._age_mu:
            while True:
                aged = self._age_pass(tg, dirp, cutoff)
                total += aged
                if aged < self.AGE_PASS_RECORDS:
                    break
        self._op_record("age_out", t0)
        if total:
            self.op_count("aged_records", total)
        return total

    def _age_pass(self, tg, dirp: str, cutoff: float) -> int:
        """One bounded age pass — caller holds ``_age_mu``."""
        with self._lock:
            m = self._db.execute(
                "SELECT MIN(id) m FROM job_log WHERE begin_ts >= ?",
                (cutoff,)).fetchone()["m"]
            if m is not None:
                nb = m - 1
            else:
                mx = self._db.execute(
                    "SELECT MAX(id) m FROM job_log").fetchone()["m"]
                nb = mx or 0
            if nb <= self._cold_boundary:
                return 0
            rows = [self._row_to_rec(r, False) for r in
                    self._db.execute(
                        "SELECT * FROM job_log WHERE id <= ? "
                        "ORDER BY id LIMIT ?",
                        (nb, self.AGE_PASS_RECORDS))]
            if not rows:
                # rows below nb already gone (retention evicted them):
                # just advance the durable watermark past the gap
                self._advance_boundary_locked(nb, [])
                return 0
            nb = rows[-1].id      # the pass's own (still-prefix) bound
        # segment writes OUTSIDE the SQL lock: new writes only ever
        # get ids > nb, so the aged set is immutable while we write
        by_day: dict = {}
        for r in rows:
            by_day.setdefault(tg.day_of(r.begin_ts), []).append(r)
        entries = [tg.write_segment(dirp, day, recs)
                   for day, recs in sorted(by_day.items())]
        with self._lock:
            self._db.execute("DELETE FROM job_log WHERE id <= ?", (nb,))
            self._advance_boundary_locked(nb, entries)
        return len(rows)

    def _advance_boundary_locked(self, nb: int, entries: list):
        """Durably advance the cold watermark + apply it to the
        mirrors and segment index — caller holds ``self._lock``."""
        self._db.execute(
            "INSERT INTO meta VALUES ('cold_boundary', ?) "
            "ON CONFLICT(k) DO UPDATE SET v=excluded.v", (str(nb),))
        self._db.commit()
        with self._hot_mu:
            self._cold_boundary = nb
            while self._h_recs and self._h_recs[0].id <= nb:
                self._h_recs.popleft()
            segs = {s["day"]: s for s in self._segments}
            for e in entries:
                segs[e["day"]] = e
            # drop segments wholly below the retention floor — their
            # records are invisible either way; this bounds disk like
            # the untiered delete bounds rows
            floor = self._retain_floor(self._h_rev)
            keep = []
            for s in sorted(segs.values(), key=lambda s: s["day"]):
                if self._retain and s["max"] <= floor:
                    try:
                        os.remove(s["path"])
                    except OSError:
                        pass
                    continue
                keep.append(s)
            self._segments = keep

    def tier_info(self) -> dict:
        """Observability snapshot: watermark, hot sizes, segment
        inventory — OPERATIONS.md's runbook reads this."""
        with self._hot_mu:
            return {
                "tiering": self._tier,
                "hot_days": self._hot_days,
                "cold_boundary": self._cold_boundary,
                "hot_records": len(self._h_recs),
                "revision": self._h_rev if self._tier
                else None,
                "segments": [{k: s[k] for k in
                              ("day", "min", "max", "count")}
                             for s in self._segments],
            }

    # ---- stats -----------------------------------------------------------

    def stat_overall(self) -> dict:
        return self._stat("")

    def stat_day(self, day: str) -> dict:
        return self._stat(day)

    def _stat(self, day: str) -> dict:
        if self._tier:
            t0 = time.perf_counter_ns()
            with self._hot_mu:
                ent = self._h_stats.get(day)
                out = ({"total": ent[0], "successed": ent[1],
                        "failed": ent[2]} if ent else
                       {"total": 0, "successed": 0, "failed": 0})
            self._op_record("q_stat_hot", t0)
            return out
        t0 = time.perf_counter_ns()
        with self._lock:
            r = self._db.execute("SELECT * FROM stat WHERE day = ?",
                                 (day,)).fetchone()
        self._op_record("query_sql", t0)
        if r is None:
            return {"total": 0, "successed": 0, "failed": 0}
        return {"total": r["total"], "successed": r["successed"],
                "failed": r["failed"]}

    def stat_days(self, n_days: int) -> List[dict]:
        n_days = max(0, n_days)
        if self._tier:
            t0 = time.perf_counter_ns()
            with self._hot_mu:
                days = sorted((d for d in self._h_stats if d != ""),
                              reverse=True)[:n_days]
                out = [{"day": d, "total": self._h_stats[d][0],
                        "successed": self._h_stats[d][1],
                        "failed": self._h_stats[d][2]} for d in days]
            self._op_record("q_stat_hot", t0)
            return out
        t0 = time.perf_counter_ns()
        with self._lock:
            rows = self._db.execute(
                "SELECT * FROM stat WHERE day != '' ORDER BY day DESC "
                "LIMIT ?", (n_days,)).fetchall()
        self._op_record("query_sql", t0)
        return [{"day": r["day"], "total": r["total"],
                 "successed": r["successed"], "failed": r["failed"]}
                for r in rows]

    # ---- node mirror -----------------------------------------------------

    def upsert_node(self, node_id: str, doc: str, alived: bool):
        with self._lock:
            self._db.execute(
                "INSERT INTO node VALUES (?,?,?) ON CONFLICT(id) DO UPDATE "
                "SET doc=excluded.doc, alived=excluded.alived",
                (node_id, doc, 1 if alived else 0))
            self._db.commit()

    def set_node_alived(self, node_id: str, alived: bool):
        with self._lock:
            self._db.execute("UPDATE node SET alived=? WHERE id=?",
                             (1 if alived else 0, node_id))
            self._db.commit()

    def get_nodes(self) -> List[dict]:
        with self._lock:
            rows = self._db.execute("SELECT * FROM node ORDER BY id").fetchall()
        out = []
        for r in rows:
            d = json.loads(r["doc"])
            d["alived"] = bool(r["alived"])
            out.append(d)
        return out

    def get_node(self, node_id: str) -> Optional[dict]:
        with self._lock:
            r = self._db.execute("SELECT * FROM node WHERE id=?",
                                 (node_id,)).fetchone()
        if r is None:
            return None
        d = json.loads(r["doc"])
        d["alived"] = bool(r["alived"])
        return d

    # ---- accounts --------------------------------------------------------

    def upsert_account(self, email: str, doc: str):
        with self._lock:
            self._db.execute(
                "INSERT INTO account VALUES (?,?) ON CONFLICT(email) DO "
                "UPDATE SET doc=excluded.doc", (email, doc))
            self._db.commit()

    def get_account(self, email: str) -> Optional[str]:
        with self._lock:
            r = self._db.execute("SELECT doc FROM account WHERE email=?",
                                 (email,)).fetchone()
        return r["doc"] if r else None

    def list_accounts(self) -> List[str]:
        with self._lock:
            rows = self._db.execute(
                "SELECT doc FROM account ORDER BY email").fetchall()
        return [r["doc"] for r in rows]

    def delete_account(self, email: str) -> bool:
        with self._lock:
            cur = self._db.execute("DELETE FROM account WHERE email=?",
                                   (email,))
            self._db.commit()
            return cur.rowcount > 0
