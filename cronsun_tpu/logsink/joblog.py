"""SQLite-backed execution log + stats + account storage.

Mirrors the reference's Mongo collections and their access patterns:

- ``job_log``      — one row per execution (job_log.go:19-31)
- ``job_latest_log`` — latest row per (job, node) (job_log.go:12-16, upsert
  at job_log.go:103-117)
- ``stat``         — overall + per-day success/fail counters
  (job_log.go:118-132)
- ``node``         — liveness mirror for the UI (node.go:129-142)
- ``account``      — web users (account.go:67-105)

Thread-safe (single connection + lock; WAL mode).
"""

from __future__ import annotations

import dataclasses
import json
import sqlite3
import threading
import time
from typing import List, Optional, Tuple

_SCHEMA = """
CREATE TABLE IF NOT EXISTS job_log (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  job_id TEXT NOT NULL, job_group TEXT NOT NULL, name TEXT NOT NULL,
  node TEXT NOT NULL, job_user TEXT DEFAULT '', command TEXT DEFAULT '',
  output TEXT DEFAULT '', success INTEGER NOT NULL,
  begin_ts REAL NOT NULL, end_ts REAL NOT NULL);
CREATE INDEX IF NOT EXISTS il_job ON job_log(job_id, begin_ts DESC);
CREATE INDEX IF NOT EXISTS il_node ON job_log(node, begin_ts DESC);
CREATE INDEX IF NOT EXISTS il_begin ON job_log(begin_ts DESC);

CREATE TABLE IF NOT EXISTS job_latest_log (
  job_id TEXT NOT NULL, node TEXT NOT NULL,
  job_group TEXT NOT NULL, name TEXT NOT NULL,
  job_user TEXT DEFAULT '', command TEXT DEFAULT '', output TEXT DEFAULT '',
  success INTEGER NOT NULL, begin_ts REAL NOT NULL, end_ts REAL NOT NULL,
  PRIMARY KEY (job_id, node));

CREATE TABLE IF NOT EXISTS stat (
  day TEXT PRIMARY KEY,           -- '' = overall
  total INTEGER NOT NULL DEFAULT 0,
  successed INTEGER NOT NULL DEFAULT 0,
  failed INTEGER NOT NULL DEFAULT 0);

CREATE TABLE IF NOT EXISTS node (
  id TEXT PRIMARY KEY, doc TEXT NOT NULL, alived INTEGER NOT NULL DEFAULT 0);

CREATE TABLE IF NOT EXISTS account (
  email TEXT PRIMARY KEY, doc TEXT NOT NULL);

CREATE TABLE IF NOT EXISTS meta (
  k TEXT PRIMARY KEY, v TEXT NOT NULL);
"""


@dataclasses.dataclass
class LogRecord:
    job_id: str
    job_group: str
    name: str
    node: str
    user: str
    command: str
    output: str
    success: bool
    begin_ts: float
    end_ts: float
    id: Optional[int] = None

    @property
    def seconds(self) -> float:
        return max(0.0, self.end_ts - self.begin_ts)


class JobLogStore:
    """``retain`` > 0 bounds execution-history rows (oldest evicted on
    insert), mirroring the native logd's --retain: the stats counters
    and the latest-status table — which summarize all history — are
    never evicted, so dashboards stay exact while disk stays bounded.
    The reference keeps Mongo job_log forever (no TTL index anywhere in
    /root/reference/db or job_log.go) — unbounded (0) matches that, the
    cap is the operational improvement."""

    def __init__(self, path: str = ":memory:", retain: int = 0):
        self._lock = threading.RLock()
        self._retain = max(0, int(retain))
        # per-op timing (memstore.op_stats parity): lets a bench — and
        # /v1/metrics — attribute the result plane's ceiling to a named
        # op (bulk create vs query) instead of "the sink"
        from ..metrics import OpStats
        self._ops = OpStats()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.row_factory = sqlite3.Row
        with self._lock:
            if path != ":memory:":
                self._db.execute("PRAGMA journal_mode=WAL")
                # WAL + NORMAL: no fsync per commit (the WAL is synced at
                # checkpoint); a power loss can drop the last moments of
                # execution history but cannot corrupt the DB — the right
                # trade for a result log whose writers retry anyway, and
                # ~10-20x the sustained create_job_log rate (the fsync was
                # the dispatch plane's bottleneck, not the store)
                self._db.execute("PRAGMA synchronous=NORMAL")
                self._db.execute("PRAGMA busy_timeout=5000")
            self._db.executescript(_SCHEMA)
            self._db.commit()

    def close(self):
        with self._lock:
            self._db.close()

    # ---- op timing (delegates to the shared metrics.OpStats) -------------

    def _op_record(self, op: str, t0_ns: int):
        self._ops.record(op, t0_ns)

    def op_count(self, op: str, n: int = 1):
        """Count-only stat (no timing): per-record tallies under the
        bulk op — log_records / create_job_logs gives the observed
        batch size."""
        self._ops.count(op, n)

    def op_stats(self) -> dict:
        """Per-op timing snapshot: {op: {count, total_ms, max_ms}}."""
        return self._ops.snapshot()

    # ---- writes (the 4-write pattern of CreateJobLog) --------------------

    def create_job_log(self, rec: LogRecord, idem: str = ""):
        # ``idem`` is accepted for surface parity with the networked
        # sink (the agents' per-record degraded path passes a stable
        # token); in-process writes have no reply to lose, so unused
        del idem
        t0 = time.perf_counter_ns()
        with self._lock:
            self._create_locked(rec)
            self._db.commit()
        self._op_record("create_job_log", t0)

    def _create_locked(self, rec: LogRecord) -> int:
        """The 4-write pattern, no commit — caller owns the transaction."""
        day = time.strftime("%Y-%m-%d", time.gmtime(rec.begin_ts))
        ok = 1 if rec.success else 0
        self._insert_log_locked(rec, ok)
        if self._retain:
            # ids stay monotone (only the oldest rows are ever
            # deleted, so max rowid never frees), making the cap a
            # single indexed range delete per insert
            self._db.execute("DELETE FROM job_log WHERE id <= ?",
                             (rec.id - self._retain,))
        self._upsert_latest_locked(rec, ok)
        for d in ("", day):
            self._bump_stat_locked(d, 1, ok, 1 - ok)
        return rec.id

    # the three statements of the 4-write pattern, shared by the single
    # path (one each per record) and the bulk path (insert per record,
    # latest/stat coalesced per batch) so the SQL exists exactly once

    def _insert_log_locked(self, rec: LogRecord, ok: int) -> int:
        cur = self._db.execute(
            "INSERT INTO job_log (job_id, job_group, name, node, "
            "job_user, command, output, success, begin_ts, end_ts) "
            "VALUES (?,?,?,?,?,?,?,?,?,?)",
            (rec.job_id, rec.job_group, rec.name, rec.node, rec.user,
             rec.command, rec.output, ok, rec.begin_ts, rec.end_ts))
        rec.id = cur.lastrowid
        return rec.id

    def _upsert_latest_locked(self, rec: LogRecord, ok: int):
        self._db.execute(
            "INSERT INTO job_latest_log VALUES (?,?,?,?,?,?,?,?,?,?) "
            "ON CONFLICT(job_id, node) DO UPDATE SET "
            "job_group=excluded.job_group, name=excluded.name, "
            "job_user=excluded.job_user, command=excluded.command, "
            "output=excluded.output, success=excluded.success, "
            "begin_ts=excluded.begin_ts, end_ts=excluded.end_ts",
            (rec.job_id, rec.node, rec.job_group, rec.name, rec.user,
             rec.command, rec.output, ok, rec.begin_ts, rec.end_ts))

    def _bump_stat_locked(self, day: str, total: int, ok_n: int,
                          fail_n: int):
        self._db.execute(
            "INSERT INTO stat (day, total, successed, failed) "
            "VALUES (?,?,?,?) ON CONFLICT(day) DO UPDATE SET "
            "total=total+excluded.total, "
            "successed=successed+excluded.successed, "
            "failed=failed+excluded.failed",
            (day, total, ok_n, fail_n))

    def create_job_logs(self, recs, idem: str = "") -> list:
        """Bulk insert: the agents' record flushers write whole batches
        in ONE transaction (one fsync).  The per-record side writes
        COALESCE per batch — one stat UPDATE per (day) touched plus one
        for the overall row, one latest-log upsert per (job, node)
        (the last record in batch order wins, exactly the sequential
        outcome), one retention trim — so a 1k-record batch pays ~4
        auxiliary statements, not 4k.  Returns the assigned row ids in
        order.  ``idem`` is accepted for surface parity with the
        networked sink; in-process writes have no reply to lose, so it
        is unused."""
        del idem
        if not recs:
            return []
        t0 = time.perf_counter_ns()
        with self._lock:
            try:
                ids = []
                latest: dict = {}
                days: dict = {}
                for rec in recs:
                    day = time.strftime("%Y-%m-%d",
                                        time.gmtime(rec.begin_ts))
                    ok = 1 if rec.success else 0
                    ids.append(self._insert_log_locked(rec, ok))
                    latest[(rec.job_id, rec.node)] = (rec, ok)
                    t, s, f = days.get(day, (0, 0, 0))
                    days[day] = (t + 1, s + ok, f + 1 - ok)
                if self._retain:
                    # ids stay monotone (only the oldest rows are ever
                    # deleted), making the cap one indexed range delete
                    # per batch
                    self._db.execute("DELETE FROM job_log WHERE id <= ?",
                                     (ids[-1] - self._retain,))
                for rec, ok in latest.values():
                    self._upsert_latest_locked(rec, ok)
                totals = [sum(v[i] for v in days.values())
                          for i in range(3)]
                for d, (t, s, f) in [("", tuple(totals))] + \
                        sorted(days.items()):
                    self._bump_stat_locked(d, t, s, f)
                self._db.commit()
            except Exception:
                # all-or-nothing: a mid-batch failure (SQLITE_BUSY past
                # the busy timeout, disk full) must not leave the head
                # rows pending in the implicit transaction — the
                # caller's retry re-sends the WHOLE batch, and a later
                # unrelated commit would otherwise flush the stale head
                # alongside it (duplicated rows + double-counted stats)
                self._db.rollback()
                raise
        self._op_record("create_job_logs", t0)
        self.op_count("log_records", len(ids))
        return ids

    # ---- queries (web/job_log.go:18-113) ---------------------------------

    def query_logs(self, node: Optional[str] = None,
                   job_ids: Optional[List[str]] = None,
                   name_like: Optional[str] = None,
                   begin: Optional[float] = None,
                   end: Optional[float] = None,
                   failed_only: bool = False,
                   latest: bool = False,
                   page: int = 1, page_size: int = 50,
                   after_id: Optional[int] = None
                   ) -> Tuple[List[LogRecord], int]:
        """``after_id`` switches to cursor mode: only rows with
        ``id > after_id``, ordered by id ASCENDING — insertion order, so
        a poller (cronsun-ctl logs --follow) never misses a record that
        was inserted with an old begin_ts (ids are monotone; begin_ts is
        not).  Ignored for the latest view, whose rows have no id.

        Cursor mode returns ``total == -1``: the poller advances its
        cursor from the delivered ids and never reads the total, but
        computing it cost a full filtered COUNT(*) scan PER POLL — the
        one O(history) term left on the follow path.  Both backends
        pin the same -1."""
        table = "job_latest_log" if latest else "job_log"
        where, args = [], []
        if after_id is not None and not latest:
            where.append("id > ?"); args.append(int(after_id))
        if node:
            where.append("node = ?"); args.append(node)
        if job_ids:
            where.append(f"job_id IN ({','.join('?' * len(job_ids))})")
            args.extend(job_ids)
        if name_like:
            # plain substring semantics: LIKE metacharacters in the
            # needle are escaped so both result-store backends (this
            # SQLite one and the native in-memory one) agree
            esc = (name_like.replace("\\", "\\\\")
                   .replace("%", r"\%").replace("_", r"\_"))
            where.append(r"name LIKE ? ESCAPE '\'")
            args.append(f"%{esc}%")
        if begin is not None:
            where.append("begin_ts >= ?"); args.append(begin)
        if end is not None:
            where.append("begin_ts < ?"); args.append(end)
        if failed_only:
            where.append("success = 0")
        cond = (" WHERE " + " AND ".join(where)) if where else ""
        # clamp absurd page numbers (empty page, never an overflow —
        # the native backend pins the same bound)
        page = max(1, min(page, 1 << 40))
        page_size = max(1, min(page_size, 500))
        cursor_mode = after_id is not None and not latest
        with self._lock:
            total = -1 if cursor_mode else self._db.execute(
                f"SELECT COUNT(*) c FROM {table}{cond}", args).fetchone()["c"]
            # tie order pinned explicitly (id ASC within equal begin_ts;
            # the id-less latest view breaks ties by its (job_id, node)
            # primary key) so the native backend — and the sharded
            # client's scatter-gather merge — page identically
            order = "id ASC" if cursor_mode else \
                "begin_ts DESC" + (", job_id ASC, node ASC" if latest
                                   else ", id ASC")
            rows = self._db.execute(
                f"SELECT * FROM {table}{cond} ORDER BY {order} "
                "LIMIT ? OFFSET ?",
                args + [page_size, (page - 1) * page_size]).fetchall()
        return [self._row_to_rec(r, latest) for r in rows], total

    def get_log(self, log_id: int) -> Optional[LogRecord]:
        with self._lock:
            r = self._db.execute("SELECT * FROM job_log WHERE id = ?",
                                 (log_id,)).fetchone()
        return self._row_to_rec(r, False) if r else None

    @staticmethod
    def _row_to_rec(r, latest: bool) -> LogRecord:
        return LogRecord(
            id=None if latest else r["id"],
            job_id=r["job_id"], job_group=r["job_group"], name=r["name"],
            node=r["node"], user=r["job_user"], command=r["command"],
            output=r["output"], success=bool(r["success"]),
            begin_ts=r["begin_ts"], end_ts=r["end_ts"])

    # ---- change revision + topology pin ----------------------------------

    def revision(self) -> int:
        """Monotone change token for the read plane: the max record id
        ever assigned (0 when empty).  Every create bumps it; retention
        trims only the oldest rows so it never regresses — the web
        tier's revision-keyed ETag (and a follow poller's tail
        bootstrap) key off this instead of re-running the query."""
        with self._lock:
            r = self._db.execute(
                "SELECT seq FROM sqlite_sequence WHERE name='job_log'"
            ).fetchone()
        return int(r["seq"]) if r else 0

    def logmap(self, n=None, hash=None):
        """The sharded-result-plane topology pin (the store's shardmap,
        result-plane edition): with arguments, publish {n, hash} if no
        pin exists yet and return whatever pin now holds; without
        arguments, a read-only peek (None when unpinned).  Lives on
        shard 0 by fiat so a client can check it knowing only the
        address list; a mismatched client refuses to start instead of
        scattering one job's history under two layouts."""
        with self._lock:
            if n is not None:
                self._db.execute(
                    "INSERT OR IGNORE INTO meta VALUES ('logmap', ?)",
                    (json.dumps({"n": int(n), "hash": hash},
                                sort_keys=True),))
                self._db.commit()
            r = self._db.execute(
                "SELECT v FROM meta WHERE k='logmap'").fetchone()
        return json.loads(r["v"]) if r else None

    # ---- stats -----------------------------------------------------------

    def stat_overall(self) -> dict:
        return self._stat("")

    def stat_day(self, day: str) -> dict:
        return self._stat(day)

    def _stat(self, day: str) -> dict:
        with self._lock:
            r = self._db.execute("SELECT * FROM stat WHERE day = ?",
                                 (day,)).fetchone()
        if r is None:
            return {"total": 0, "successed": 0, "failed": 0}
        return {"total": r["total"], "successed": r["successed"],
                "failed": r["failed"]}

    def stat_days(self, n_days: int) -> List[dict]:
        with self._lock:
            rows = self._db.execute(
                "SELECT * FROM stat WHERE day != '' ORDER BY day DESC "
                "LIMIT ?", (max(0, n_days),)).fetchall()
        return [{"day": r["day"], "total": r["total"],
                 "successed": r["successed"], "failed": r["failed"]}
                for r in rows]

    # ---- node mirror -----------------------------------------------------

    def upsert_node(self, node_id: str, doc: str, alived: bool):
        with self._lock:
            self._db.execute(
                "INSERT INTO node VALUES (?,?,?) ON CONFLICT(id) DO UPDATE "
                "SET doc=excluded.doc, alived=excluded.alived",
                (node_id, doc, 1 if alived else 0))
            self._db.commit()

    def set_node_alived(self, node_id: str, alived: bool):
        with self._lock:
            self._db.execute("UPDATE node SET alived=? WHERE id=?",
                             (1 if alived else 0, node_id))
            self._db.commit()

    def get_nodes(self) -> List[dict]:
        with self._lock:
            rows = self._db.execute("SELECT * FROM node ORDER BY id").fetchall()
        out = []
        for r in rows:
            d = json.loads(r["doc"])
            d["alived"] = bool(r["alived"])
            out.append(d)
        return out

    def get_node(self, node_id: str) -> Optional[dict]:
        with self._lock:
            r = self._db.execute("SELECT * FROM node WHERE id=?",
                                 (node_id,)).fetchone()
        if r is None:
            return None
        d = json.loads(r["doc"])
        d["alived"] = bool(r["alived"])
        return d

    # ---- accounts --------------------------------------------------------

    def upsert_account(self, email: str, doc: str):
        with self._lock:
            self._db.execute(
                "INSERT INTO account VALUES (?,?) ON CONFLICT(email) DO "
                "UPDATE SET doc=excluded.doc", (email, doc))
            self._db.commit()

    def get_account(self, email: str) -> Optional[str]:
        with self._lock:
            r = self._db.execute("SELECT doc FROM account WHERE email=?",
                                 (email,)).fetchone()
        return r["doc"] if r else None

    def list_accounts(self) -> List[str]:
        with self._lock:
            rows = self._db.execute(
                "SELECT doc FROM account ORDER BY email").fetchall()
        return [r["doc"] for r in rows]

    def delete_account(self, email: str) -> bool:
        with self._lock:
            cur = self._db.execute("DELETE FROM account WHERE email=?",
                                   (email,))
            self._db.commit()
            return cur.rowcount > 0
