"""Trace store: the result plane's span sink (fire-lifecycle tracing).

Spans arrive piggybacked on the agents' record flushes
(``create_job_logs(..., spans=[...])`` — zero extra RPCs) and land in

- a bounded in-memory RING keyed by trace id (newest evicts oldest;
  the operator surface ``/v1/trace/...`` and ``cronsun-ctl trace``
  read it), merged last-write-wins per (trace, node) so a retried
  batch re-merges identical values instead of duplicating; and
- an append-only per-day SPILL file beside the tiered store's segment
  directory (``<db>.traces/<day>.jsonl``, one JSON line per span
  batch entry) for traces that have aged out of the ring — the same
  day-file layout the cold tier uses, readable offline.

Ingest also folds every span's stage durations into fixed-bucket
per-stage histograms (trace.BUCKETS_MS — identical fleet-wide, so the
counters aggregate across logd shards and replicas), served as the
``trace_stats`` op and rendered by the web tier as
``cronsun_trace_stage_ms_{bucket,sum,count}{stage=...}``.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from .. import log, trace as _trace


class TraceStore:
    """Bounded trace ring + per-day spill + per-stage histograms.
    ``spill_dir`` None (in-memory sinks) keeps the ring only."""

    def __init__(self, cap: int = 4096, spill_dir: Optional[str] = None):
        self.cap = cap
        self.spill_dir = spill_dir
        self._mu = threading.Lock()
        # tid -> {"job", "grp", "sec", "spans": {node: span dict}}
        self._ring: "OrderedDict[str, dict]" = OrderedDict()
        self._stage_hist: Dict[str, list] = {
            s: [0] * (len(_trace.BUCKETS_MS) + 1) for s in _trace.STAGES}
        self._stage_sum: Dict[str, float] = {s: 0.0 for s in _trace.STAGES}
        self._stage_cnt: Dict[str, int] = {s: 0 for s in _trace.STAGES}
        self._spans_total = 0
        self._spill_day = None
        self._spill_f = None

    # ---- ingest ----------------------------------------------------------

    def ingest(self, spans: List[dict]) -> int:
        """Merge a span batch; returns the number accepted.  Malformed
        entries are skipped (the record path must never fail on a bad
        span sidecar)."""
        n = 0
        spill: List[str] = []
        with self._mu:
            for sp in spans:
                if not isinstance(sp, dict):
                    continue
                tid = sp.get("tid")
                job = sp.get("job")
                sec = sp.get("sec")
                ts = sp.get("ts")
                if not (isinstance(tid, str) and isinstance(job, str)
                        and isinstance(sec, int)
                        and isinstance(ts, dict)):
                    continue
                ent = self._ring.get(tid)
                if ent is None:
                    ent = {"tid": tid, "job": job,
                           "grp": sp.get("grp", ""), "sec": sec,
                           "spans": {}}
                    self._ring[tid] = ent
                    if len(self._ring) > self.cap:
                        self._ring.popitem(last=False)
                else:
                    self._ring.move_to_end(tid)
                node = sp.get("node", "")
                prev = ent["spans"].get(node)
                if prev is not None:
                    # LWW merge per (trace, node): a batch retry
                    # re-sends identical stamps; a later flush stamp
                    # (re-stamped per attempt) overwrites
                    prev["ts"].update(ts)
                    prev["ok"] = bool(sp.get("ok", prev.get("ok", True)))
                else:
                    ent["spans"][node] = {
                        "node": node, "ok": bool(sp.get("ok", True)),
                        "grp": sp.get("grp", ""), "ten": sp.get("ten"),
                        "ts": dict(ts)}
                for stage, ms in _trace.stage_durations(sec, ts).items():
                    bi = bisect.bisect_left(_trace.BUCKETS_MS, ms)
                    self._stage_hist[stage][bi] += 1
                    self._stage_sum[stage] += ms
                    self._stage_cnt[stage] += 1
                self._spans_total += 1
                n += 1
                if self.spill_dir is not None:
                    spill.append((int(sec),
                                  json.dumps(sp, separators=(",", ":"))))
            if spill:
                self._spill_locked(spill)
        return n

    def _spill_locked(self, entries: List[tuple]):
        """Append each span to the day file of ITS OWN scheduled
        second — get() opens exactly one day file, so a span filed
        under a neighboring day (a record flush straddling midnight)
        would be unrecoverable once the ring evicts it.  Batches are
        near-real-time, so one open file handles the overwhelmingly
        common case and the day rolls over at most once per batch.
        Best-effort: a disk error logs once and disables spill."""
        try:
            for sec, line in entries:
                day = time.strftime("%Y-%m-%d", time.gmtime(sec))
                if self._spill_day != day or self._spill_f is None:
                    if self._spill_f is not None:
                        self._spill_f.close()
                    os.makedirs(self.spill_dir, exist_ok=True)
                    self._spill_f = open(
                        os.path.join(self.spill_dir, f"{day}.jsonl"),
                        "a")
                    self._spill_day = day
                self._spill_f.write(line + "\n")
            self._spill_f.flush()
        except OSError as e:
            log.warnf("trace spill disabled: %s", e)
            self.spill_dir = None
            self._spill_f = None

    # ---- reads -----------------------------------------------------------

    def get(self, job_id: str, epoch_s: int) -> List[dict]:
        """Raw span dicts of one trace (one per executing node), ring
        first, then the scheduled day's spill file."""
        tid = str(_trace.trace_id(job_id, int(epoch_s)))
        with self._mu:
            ent = self._ring.get(tid)
            if ent is not None:
                return [dict(s, tid=ent["tid"], job=ent["job"],
                             sec=ent["sec"], ts=dict(s["ts"]))
                        for s in ent["spans"].values()]
        if self.spill_dir is None:
            return []
        day = time.strftime("%Y-%m-%d", time.gmtime(int(epoch_s)))
        path = os.path.join(self.spill_dir, f"{day}.jsonl")
        out: Dict[str, dict] = {}
        try:
            with open(path) as f:
                for ln in f:
                    try:
                        sp = json.loads(ln)
                    except json.JSONDecodeError:
                        continue
                    if sp.get("tid") != tid:
                        continue
                    node = sp.get("node", "")
                    prev = out.get(node)
                    if prev is not None:
                        prev["ts"].update(sp.get("ts") or {})
                    else:
                        out[node] = sp
        except OSError:
            return []
        return list(out.values())

    def top(self, n: int = 256) -> List[dict]:
        """Most-recent ring traces summarized (tid, job, sec, per-node
        stage durations, total) — the web sorts by total or any stage;
        the backend stays dumb so py and native agree by construction."""
        with self._mu:
            ents = list(self._ring.values())[-max(1, n):]
        out = []
        for ent in ents:
            nodes = []
            for s in ent["spans"].values():
                nodes.append({
                    "node": s["node"], "ok": s.get("ok", True),
                    "stages": _trace.stage_durations(ent["sec"], s["ts"]),
                    "total_ms": _trace.span_total_ms(ent["sec"], s["ts"]),
                })
            if not nodes:
                continue
            out.append({"tid": ent["tid"], "job": ent["job"],
                        "grp": ent.get("grp", ""), "sec": ent["sec"],
                        "total_ms": max(x["total_ms"] for x in nodes),
                        "nodes": nodes})
        return out

    def stats(self) -> dict:
        """Cumulative per-stage histogram counters (the trace_stats
        wire op): {stage: {buckets, sum, count}} + spans_total."""
        with self._mu:
            return {
                "spans_total": self._spans_total,
                "stages": {
                    s: {"buckets": list(self._stage_hist[s]),
                        "sum": round(self._stage_sum[s], 3),
                        "count": self._stage_cnt[s]}
                    for s in _trace.STAGES if self._stage_cnt[s]}}

    def close(self):
        with self._mu:
            if self._spill_f is not None:
                try:
                    self._spill_f.close()
                except OSError:
                    pass
                self._spill_f = None
