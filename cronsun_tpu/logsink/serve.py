"""Networked result store: JobLogStore served over TCP.

The reference's execution logs, latest-log, stats, node-liveness mirror
and accounts live in MongoDB — a networked multi-host store every node
writes and the web server reads (/root/reference/db/mgo.go:24-49,
job_log.go:84-133).  The rebuild's equivalent: :class:`LogSinkServer`
exposes a JobLogStore (SQLite, WAL) over the same line-JSON transport
the coordination store uses, and :class:`RemoteJobLogStore` is a client
with the identical Python surface — agent, web server and noticer run
unchanged against either, and processes on different machines share one
result store the way the reference's share one Mongo.

Wire protocol (one JSON object per line, UTF-8):

    client -> server   {"i": <id>, "o": <op>, "a": [args...]}
    server -> client   {"i": <id>, "r": <result>}        (ok)
                       {"i": <id>, "e": <msg>}           (error)

LogRecord wire form: plain dict of its dataclass fields.

Authentication: when the server is started with a ``token``, the first
request on every connection must be ``{"i":0,"o":"auth","a":[token]}``;
anything else (or a wrong token) closes the connection.  The reference
carries Mongo credentials through config the same way
(/root/reference/db/mgo.go:33-36).
"""

from __future__ import annotations

import json
import queue
import socket
import socketserver
import threading
import uuid
from collections import deque
from typing import List, Optional, Tuple

from .. import log
from ..chaos.hooks import hooks as _chaos
from ..store.wire import LineJsonHandler
from .joblog import JobLogStore, LogRecord, SubscriptionLost

# ops dispatched 1:1 onto the JobLogStore surface (auth + create_job_log
# + query_logs + tail_snapshot get special marshalling)
_PLAIN_OPS = ("get_log", "stat_overall", "stat_day", "stat_days",
              "upsert_node", "set_node_alived", "get_nodes", "get_node",
              "upsert_account", "get_account", "list_accounts",
              "delete_account", "op_stats", "revision", "logmap",
              "age_out", "tier_info",
              "trace_get", "trace_top", "trace_stats")


def _rec_wire(rec: Optional[LogRecord]):
    # dict(__dict__), not dataclasses.asdict: asdict routes through the
    # recursive deep-copy machinery (~10x slower) and a latest-view
    # reply marshals 500+ records per dashboard poll; field order (and
    # so the wire bytes) is identical — __dict__ fills in declaration
    # order
    return None if rec is None else dict(rec.__dict__)


def _rec_unwire(w) -> Optional[LogRecord]:
    # positional construction — a latest reply carries 500+ records and
    # LogRecord(**w) pays the keyword-matching path per row
    return None if w is None else LogRecord(
        w["job_id"], w["job_group"], w["name"], w["node"], w["user"],
        w["command"], w["output"], w["success"], w["begin_ts"],
        w["end_ts"], w["id"])


class _Conn(LineJsonHandler):
    # Wire form of the change stream (see JobLogStore.subscribe): the
    # ``subscribe`` op acks {"rev": R, "lost": gap?} on the request id,
    # then the server pushes frames on the SAME connection —
    #   {"s": <rid>, "evs": [[id, job_id, job_group, name, node,
    #                         success, begin_ts, end_ts], ...]}
    # in id order, and {"s": <rid>, "lost": true} once the bounded
    # buffer overflowed (after which the subscription is dead and the
    # consumer re-lists + re-subscribes).  Both backends pin the same
    # frames byte-for-byte-compatibly.

    def setup(self):
        super().setup()
        # per-connection change-stream state: subscriptions opened on
        # this connection and the pump thread that writes their frames
        # (lazy — request/response-only connections never pay a thread)
        self._subs: dict = {}
        self._sub_ready: "queue.Queue" = queue.Queue()
        self._pump: Optional[threading.Thread] = None

    def finish(self):
        for sub in list(self._subs.values()):
            sub.close()
        self._subs.clear()
        if self._pump is not None:
            self._sub_ready.put(None)
        super().finish()

    def _subscribe(self, sink, rid, after_id, cap):
        sub = sink.subscribe(after_id=after_id, cap=cap)
        # ack FIRST, then arm the pump: events landing in between just
        # buffer in the subscription, and the nudge below flushes them —
        # so the client always reads the ack before any frame
        self._send({"i": rid, "r": {"rev": sub.rev,
                                    "lost": bool(sub.gap)}})
        sid = int(rid)
        self._subs[sid] = sub
        if self._pump is None:
            self._pump = threading.Thread(target=self._sub_pump,
                                          daemon=True,
                                          name="logsink-sub-pump")
            self._pump.start()
        sub.on_ready = lambda _s, q=self._sub_ready, i=sid: q.put(i)
        self._sub_ready.put(sid)

    def _sub_pump(self):
        while self.alive:
            sid = self._sub_ready.get()
            if sid is None:
                return
            sub = self._subs.get(sid)
            if sub is None:
                continue
            try:
                evs = sub.drain()
            except SubscriptionLost:
                self._send_raw('{"s":%d,"lost":true}\n' % sid)
                self._subs.pop(sid, None)
                sub.close()
                continue
            for i in range(0, len(evs), 2048):
                self._send_raw(json.dumps(
                    {"s": sid, "evs": evs[i:i + 2048]},
                    separators=(",", ":")) + "\n")

    def _send_raw(self, line: str):
        data = line.encode()
        with self.wlock:
            try:
                self.request.sendall(data)
            except OSError:
                self.alive = False

    def _latest_reply_cached(self, sink, rid, kw) -> bool:
        """Serialized-reply memo for the latest view, keyed on the
        sink's revision (the web cache's idea one level down): a
        dashboard fleet polling between write batches reuses the
        MARSHALLED bytes — no row copies, no dict building, no
        json.dumps of 500 records per poll.  Sound for the same reason
        the web cache is: the revision is read BEFORE computing, so a
        write racing the compute bumps it and the entry can never
        satisfy a later poll.  Only engaged on tiered sinks (revision
        there is a mirror read, not a SQL query).  Returns True when
        it handled the request."""
        if not kw.get("latest") or not getattr(sink, "_tier", False):
            return False
        try:
            rev = sink.revision()
        except Exception:  # noqa: BLE001 — fall back to the plain path
            return False
        key = json.dumps(kw, sort_keys=True)
        cache = self.server.reply_cache           # type: ignore[attr-defined]
        lock = self.server.reply_lock             # type: ignore[attr-defined]
        with lock:
            ent = cache.get(key)
            payload = ent[1] if ent and ent[0] == rev else None
        if payload is not None:
            sink.op_count("q_latest_memo")   # served from marshalled bytes
        else:
            recs, total = sink.query_logs(**kw)
            payload = json.dumps(
                {"total": total, "list": [_rec_wire(r) for r in recs]},
                separators=(",", ":"))
            with lock:
                cache[key] = (rev, payload)
                while len(cache) > 64:
                    cache.pop(next(iter(cache)))
        self._send_raw('{"i":%d,"r":%s}\n' % (rid, payload))
        return True

    def dispatch(self, rid, op, args):
        sink: JobLogStore = self.server.sink      # type: ignore[attr-defined]
        try:
            if op == "create_job_log":
                self._send({"i": rid,
                            "r": self._create(sink, args[0],
                                              args[1] if len(args) > 1
                                              else None)})
            elif op == "create_job_logs":
                self._send({"i": rid,
                            "r": self._create_bulk(
                                sink, args[0],
                                args[1] if len(args) > 1 else None,
                                args[2] if len(args) > 2 else None)})
            elif op == "query_logs":
                if not self._latest_reply_cached(sink, rid, args[0]):
                    recs, total = sink.query_logs(**args[0])
                    self._send({"i": rid, "r": {
                        "total": total,
                        "list": [_rec_wire(r) for r in recs]}})
            elif op == "tail_snapshot":
                rev, recs = sink.tail_snapshot(args[0] if args else 0)
                self._send({"i": rid, "r": {
                    "revision": rev,
                    "list": [_rec_wire(r) for r in recs]}})
            elif op == "subscribe":
                self._subscribe(sink, rid,
                                int(args[0]) if args else 0,
                                int(args[1]) if len(args) > 1 else 4096)
            elif op == "unsubscribe":
                sub = self._subs.pop(int(args[0]), None)
                if sub is not None:
                    sub.close()
                self._send({"i": rid, "r": sub is not None})
            elif op in _PLAIN_OPS:
                r = getattr(sink, op)(*args)
                if op == "get_log":
                    r = _rec_wire(r)
                self._send({"i": rid, "r": r})
            else:
                self._send({"i": rid, "e": f"unknown op {op!r}"})
        except Exception as e:  # noqa: BLE001 — report, keep serving
            self._send({"i": rid, "e": f"{type(e).__name__}: {e}"})

    def _idempotent(self, idem, thunk):
        """Run ``thunk()`` at most once per idempotency token.  The token
        is RESERVED before the write — a concurrent retry of the same
        token latches onto the original attempt instead of racing it —
        and replays return the original result.  A failed attempt
        withdraws its reservation so a later retry can re-race; a waiter
        that times out (pathologically slow owner) re-races too.  Shared
        by the single and bulk create paths so the reservation state
        machine exists exactly once."""
        if not idem:
            return thunk()
        seen = self.server.idem                   # type: ignore[attr-defined]
        lock = self.server.idem_lock              # type: ignore[attr-defined]
        with lock:
            ent = seen.get(idem)
            if ent is None:
                ent = {"done": threading.Event(), "id": None}
                seen[idem] = ent
                # bounded LRU: evict oldest COMPLETED entries
                if len(seen) > 8192:
                    for k in list(seen):
                        if len(seen) <= 8192:
                            break
                        if k != idem and seen[k]["done"].is_set():
                            seen.pop(k)
                owner = True
            else:
                owner = False
        if not owner:
            ent["done"].wait(timeout=30)
            if ent["id"] is not None:
                return ent["id"]
            with lock:
                if seen.get(idem) is ent:
                    seen.pop(idem)
            return self._idempotent(idem, thunk)
        try:
            result = thunk()
        except Exception:
            with lock:
                seen.pop(idem, None)
            ent["done"].set()
            raise
        ent["id"] = result
        ent["done"].set()
        return result

    def _create_bulk(self, sink: JobLogStore, wires, idem, spans=None):
        """Bulk insert (agent record flushers): one idempotency token
        covers the whole batch — a retried batch whose first attempt
        committed replays the original ids, never double-inserts.  The
        trace-span sidecar rides INSIDE the idempotent thunk, so a
        replayed batch does not double-count the stage histograms."""
        recs = [_rec_unwire(w) for w in wires]      # parse before reserving
        if spans:
            return self._idempotent(
                idem, lambda: sink.create_job_logs(recs, spans=spans))
        return self._idempotent(idem, lambda: sink.create_job_logs(recs))

    def _create(self, sink: JobLogStore, wire, idem):
        """Idempotent insert: the client's transparent reconnect+retry
        must not double-insert a record whose first attempt committed (or
        is still committing) when the reply was lost."""
        # parse BEFORE reserving: a bad wire dict must raise without
        # leaking a never-completed reservation
        rec = _rec_unwire(wire)

        def write():
            sink.create_job_log(rec)
            return rec.id
        return self._idempotent(idem, write)


class LogSinkServer:
    """Serve a JobLogStore over TCP; port 0 picks a free port."""

    def __init__(self, sink: Optional[JobLogStore] = None,
                 db_path: str = ":memory:", host: str = "127.0.0.1",
                 port: int = 0, token: str = "", sslctx=None,
                 retain: int = 0, hot_days: int = 0,
                 age_interval: float = 30.0):
        self.sink = sink or JobLogStore(db_path, retain=retain,
                                        hot_days=hot_days)
        # the retention sweeper's tier move: day aging rides a
        # background beat (cheap when nothing aged — the boundary scan
        # is one indexed MIN(id)); native logd runs the same loop on
        # its sweep thread
        self._age_stop = threading.Event()
        self._age_thread: Optional[threading.Thread] = None
        self._age_interval = age_interval
        if hot_days > 0 and sink is None and db_path != ":memory:":
            self._age_thread = threading.Thread(
                target=self._age_loop, daemon=True, name="logsink-ager")

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
        self._srv = _Server((host, port), _Conn)
        self._srv.sink = self.sink                # type: ignore[attr-defined]
        self._srv.token = token                   # type: ignore[attr-defined]
        self._srv.sslctx = sslctx                 # type: ignore[attr-defined]
        self._srv.idem = {}                       # type: ignore[attr-defined]
        self._srv.idem_lock = threading.Lock()    # type: ignore[attr-defined]
        self._srv.reply_cache = {}                # type: ignore[attr-defined]
        self._srv.reply_lock = threading.Lock()   # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def _age_loop(self):
        while not self._age_stop.wait(self._age_interval):
            try:
                self.sink.age_out()
            except Exception as e:  # noqa: BLE001 — keep sweeping
                log.warnf("logsink age_out failed: %s", e)

    def start(self) -> "LogSinkServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True, name="logsink-server")
        self._thread.start()
        if self._age_thread:
            self._age_thread.start()
        return self

    def stop(self):
        self._age_stop.set()
        if self._thread:
            self._srv.shutdown()
            self._srv.server_close()
            self._thread.join(timeout=3)
        else:
            # never start()ed (error-path cleanup): shutdown() would
            # block forever on the serve_forever event that never fires
            self._srv.server_close()
        if self._age_thread and self._age_thread.ident is not None:
            # only a STARTED thread can be joined (stop() on a
            # constructed-but-never-started server must not raise);
            # passes are bounded (AGE_PASS_RECORDS) so the in-flight
            # one finishes inside the timeout — and the age loop
            # catches-and-warns if the close below still races it
            self._age_thread.join(timeout=10)
        self.sink.close()


class LogSinkError(RuntimeError):
    pass


class RemoteLogSubscription:
    """Client side of the ``subscribe`` wire op, on a DEDICATED
    connection (the shared request/response connection is strictly
    synchronous — one streaming op is not worth teaching every caller
    a demux).  A reader thread feeds a local bounded buffer with the
    same ``get``/``drain``/``lost``/``on_ready`` surface as the
    in-process :class:`~.joblog.LogSubscription`, and ANY transport
    failure latches ``lost`` (never silent staleness): the consumer
    re-lists from its cursor and re-subscribes, exactly as after an
    overflow."""

    def __init__(self, host: str, port: int, timeout: float,
                 token: str, sslctx, tls_hostname: str,
                 after_id: int, cap: int):
        sock = socket.create_connection((host, port), timeout=timeout)
        if sslctx is not None:
            from ..tlsutil import wrap_client
            sock = wrap_client(sock, sslctx, tls_hostname)
        sock.settimeout(timeout)
        self._sock = sock
        self._rfile = sock.makefile("rb")
        self._cap = max(1, int(cap))
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._buf: deque = deque()
        self.lost = False
        self.closed = False
        self.on_ready = None
        try:
            if token:
                self._handshake("auth", token)
            r = self._handshake("subscribe", int(after_id), int(cap))
            self.rev = int(r.get("rev", 0))
            self.gap = bool(r.get("lost"))
        except Exception:
            try:
                sock.close()
            except OSError:
                pass
            raise
        # subscribed: frames arrive whenever the server has events, so
        # reads must be allowed to block indefinitely
        sock.settimeout(None)
        self._thread = threading.Thread(target=self._read_loop,
                                        daemon=True,
                                        name="logsink-sub-reader")
        self._thread.start()

    def _handshake(self, op: str, *args):
        data = (json.dumps({"i": 1, "o": op, "a": list(args)},
                           separators=(",", ":")) + "\n").encode()
        self._sock.sendall(data)
        line = self._rfile.readline()
        if not line:
            raise LogSinkError(f"{op}: connection closed")
        msg = json.loads(line)
        if "e" in msg:
            raise LogSinkError(msg["e"])
        return msg.get("r")

    def _read_loop(self):
        while True:
            try:
                line = self._rfile.readline()
            except (OSError, ValueError):
                line = b""
            if not line:
                self._mark_lost()
                return
            try:
                msg = json.loads(line)
            except ValueError:
                self._mark_lost()
                return
            if msg.get("lost"):
                self._mark_lost()
                return
            evs = msg.get("evs") or []
            ready = None
            with self._cv:
                if self.closed:
                    return
                if len(self._buf) + len(evs) > self._cap:
                    # local overflow mirrors the server-side contract
                    self._buf.clear()
                    self.lost = True
                else:
                    self._buf.extend(tuple(e) for e in evs)
                self._cv.notify_all()
                ready = self.on_ready
            if ready is not None:
                ready(self)
            if self.lost:
                return

    def _mark_lost(self):
        ready = None
        with self._cv:
            if not self.closed:
                self._buf.clear()
                self.lost = True
                ready = self.on_ready
            self._cv.notify_all()
        if ready is not None:
            ready(self)

    def drain(self) -> list:
        with self._cv:
            if self.lost:
                raise SubscriptionLost("log subscription lost")
            out = list(self._buf)
            self._buf.clear()
        return out

    def get(self, timeout: Optional[float] = None) -> list:
        with self._cv:
            if not self._buf and not self.lost and not self.closed:
                self._cv.wait(timeout)
            if self.lost:
                raise SubscriptionLost("log subscription lost")
            if self.closed and not self._buf:
                raise SubscriptionLost("log subscription closed")
            out = list(self._buf)
            self._buf.clear()
        return out

    def close(self):
        with self._cv:
            self.closed = True
            self._cv.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass


class RemoteJobLogStore:
    """TCP client with JobLogStore's exact surface.

    Calls are synchronous request/response under one lock (the result
    path has no server pushes to demux).  A dropped connection is healed
    by one transparent reconnect+retry per call; if that also fails the
    caller sees :class:`LogSinkError` and retries at its own cadence —
    the agent's log writes tolerate this the way the reference tolerates
    a Mongo hiccup (job_log.go:84 logs and moves on)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 token: str = "", sslctx=None, tls_hostname: str = ""):
        self.host, self.port = host, port
        self._timeout = timeout
        self._token = token
        self._sslctx = sslctx
        self._tls_hostname = tls_hostname
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._next_id = 1
        self._closed = False
        with self._lock:
            self._connect()

    # -- plumbing ----------------------------------------------------------

    def _connect(self):
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self._timeout)
        if self._sslctx is not None:
            from ..tlsutil import wrap_client
            sock = wrap_client(sock, self._sslctx, self._tls_hostname)
        self._sock = sock
        self._sock.settimeout(self._timeout)
        self._rfile = self._sock.makefile("rb")
        if self._token:
            self._exchange("auth", self._token)

    def _exchange(self, op: str, *args):
        rid = self._next_id
        self._next_id += 1
        data = (json.dumps({"i": rid, "o": op, "a": list(args)},
                           separators=(",", ":")) + "\n").encode()
        self._sock.sendall(data)
        line = self._rfile.readline()
        if not line:
            raise OSError("connection closed")
        msg = json.loads(line)
        if "e" in msg:
            raise LogSinkError(msg["e"])
        return msg.get("r")

    def _call(self, op: str, *args):
        if self._closed:
            raise LogSinkError("logsink connection closed")
        # chaos-plane fault point (env-gated off in production): see
        # store/remote.py — 'timeout' fails before the wire,
        # 'reply_lost' lets the op apply and fails the reply path (the
        # indeterminate shape the record flusher's pinned idempotency
        # tokens exist for), 'delay' stalls the caller
        act = _chaos.intercept("logsink.rpc", op) if _chaos.armed else None
        if act is not None:
            act.pre(LogSinkError, op)
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._sock is None:
                        self._connect()
                    r = self._exchange(op, *args)
                    if act is not None:
                        # LogSinkError, not OSError: the reply is
                        # "lost" WITHOUT burning the reconnect retry
                        # (the op applied; the caller's idem ladder
                        # owns the re-send)
                        act.post(LogSinkError, op)
                    return r
                except (OSError, ValueError) as e:
                    # ValueError covers JSONDecodeError and the
                    # UnicodeDecodeError binary garbage raises
                    self._drop()
                    if attempt:
                        raise LogSinkError(f"{op}: {e}") from e
                    log.warnf("logsink call %s failed (%s); reconnecting",
                              op, e)

    def _drop(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._rfile = None

    def close(self):
        with self._lock:
            self._closed = True
            self._drop()

    # -- surface (mirrors JobLogStore) -------------------------------------

    def create_job_log(self, rec: LogRecord, idem: str = ""):
        # one token per logical record, stable across the reconnect
        # retry; callers that re-send a record after an INDETERMINATE
        # reply (the agent's record flusher) pass their own stable
        # ``idem`` so an applied-but-reply-lost write dedups
        # server-side instead of double-inserting (the token contract
        # of _Conn._idempotent above)
        rec.id = self._call("create_job_log", _rec_wire(rec),
                            idem or uuid.uuid4().hex)

    def create_job_logs(self, recs: List[LogRecord], idem: str = "",
                        spans: Optional[list] = None):
        """Bulk insert in one round trip (one idempotency token per
        batch) — the agents' record flushers use this so a 10k-order
        burst is tens of calls, not 10k.  Callers that re-flush a
        failed batch pass a stable ``idem`` so an applied-but-reply-
        lost write dedups server-side instead of double-inserting.
        ``spans`` is the trace plane's piggybacked sidecar: shipped as
        a third wire argument (older servers ignore it)."""
        if not recs and not spans:
            return
        if spans:
            ids = self._call("create_job_logs",
                             [_rec_wire(r) for r in recs],
                             idem or uuid.uuid4().hex, spans)
        else:
            ids = self._call("create_job_logs",
                             [_rec_wire(r) for r in recs],
                             idem or uuid.uuid4().hex)
        for r, i in zip(recs, ids or []):
            r.id = i

    def query_logs(self, **kw) -> Tuple[List[LogRecord], int]:
        r = self._call("query_logs", kw)
        return [_rec_unwire(w) for w in r["list"]], r["total"]

    def get_log(self, log_id: int) -> Optional[LogRecord]:
        return _rec_unwire(self._call("get_log", log_id))

    def stat_overall(self) -> dict:
        return self._call("stat_overall")

    def stat_day(self, day: str) -> dict:
        return self._call("stat_day", day)

    def stat_days(self, n_days: int) -> List[dict]:
        return self._call("stat_days", n_days)

    def op_stats(self) -> dict:
        """Server-side per-op timing snapshot (JobLogStore.op_stats —
        bulk create vs query attribution for the result plane)."""
        return self._call("op_stats")

    def revision(self) -> int:
        """Monotone change token (max record id ever assigned) — the
        web tier's ETag key and the follow poller's tail bootstrap."""
        return self._call("revision")

    def tail_snapshot(self, limit: int = 0) -> Tuple[int, List[LogRecord]]:
        """Revision AND the last ``limit`` records from ONE server-side
        snapshot — the follow bootstrap's atomic read (see
        JobLogStore.tail_snapshot for why two reads can skip)."""
        r = self._call("tail_snapshot", limit)
        return r["revision"], [_rec_unwire(w) for w in r["list"]]

    def subscribe(self, after_id: int = 0,
                  cap: int = 4096) -> RemoteLogSubscription:
        """Open a live change stream (see JobLogStore.subscribe) on a
        dedicated connection.  Raises LogSinkError when the server is
        unreachable or predates the ``subscribe`` op."""
        if self._closed:
            raise LogSinkError("logsink connection closed")
        try:
            return RemoteLogSubscription(
                self.host, self.port, self._timeout, self._token,
                self._sslctx, self._tls_hostname, after_id, cap)
        except (OSError, ValueError) as e:
            raise LogSinkError(f"subscribe: {e}") from e

    def age_out(self, now: Optional[float] = None) -> int:
        """Force a cold-aging pass (the sweeper runs it periodically);
        ``now`` overrides the clock for deterministic tests."""
        return self._call("age_out") if now is None \
            else self._call("age_out", now)

    def tier_info(self) -> dict:
        """Tiering observability: watermark, hot sizes, segments."""
        return self._call("tier_info")

    def logmap(self, n=None, hash=None):
        """Topology pin (see JobLogStore.logmap): publish-if-absent with
        arguments, read-only peek without."""
        if n is None:
            return self._call("logmap")
        return self._call("logmap", n, hash)

    # -- trace plane -------------------------------------------------------

    def trace_get(self, job_id: str, epoch_s: int) -> list:
        return self._call("trace_get", job_id, int(epoch_s))

    def trace_top(self, n: int = 256) -> list:
        return self._call("trace_top", int(n))

    def trace_stats(self) -> dict:
        return self._call("trace_stats")

    def upsert_node(self, node_id: str, doc: str, alived: bool):
        self._call("upsert_node", node_id, doc, alived)

    def set_node_alived(self, node_id: str, alived: bool):
        self._call("set_node_alived", node_id, alived)

    def get_nodes(self) -> List[dict]:
        return self._call("get_nodes")

    def get_node(self, node_id: str) -> Optional[dict]:
        return self._call("get_node", node_id)

    def upsert_account(self, email: str, doc: str):
        self._call("upsert_account", email, doc)

    def get_account(self, email: str) -> Optional[str]:
        return self._call("get_account", email)

    def list_accounts(self) -> List[str]:
        return self._call("list_accounts")

    def delete_account(self, email: str) -> bool:
        return self._call("delete_account", email)
