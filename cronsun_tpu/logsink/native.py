"""Launcher for the native (C++) result store server.

``native/logd.cc`` implements the same wire protocol as
:class:`~cronsun_tpu.logsink.serve.LogSinkServer` — in-memory tables
with a WAL instead of SQLite, no GIL, bounded retention.
``tests/test_logsink_remote.py`` runs the same conformance suite against
both backends, exactly the StoreServer/stored.cc pairing on the
coordination side.
"""

from __future__ import annotations

from typing import List, Optional

from ..native_launcher import NativeProcess, find_binary as _find


def find_binary(build: bool = True) -> Optional[str]:
    return _find("cronsun-logd", "CRONSUN_LOGD", build)


class NativeLogSinkServer(NativeProcess):
    """Run cronsun-logd as a child process; same lifecycle surface as
    the Python LogSinkServer (host/port/stop/monitor)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 binary: Optional[str] = None, db: Optional[str] = None,
                 retain: Optional[int] = None, token: str = "",
                 hot_days: Optional[int] = None,
                 extra_args: Optional[List[str]] = None,
                 ready_timeout: float = 10.0):
        binary = binary or find_binary()
        if binary is None:
            raise FileNotFoundError(
                "cronsun-logd not found (set $CRONSUN_LOGD or build "
                "native/)")
        self.binary = binary
        argv = ["--host", host, "--port", str(port)] + (extra_args or [])
        if db:
            argv += ["--db", db]
        if retain is not None:
            argv += ["--retain", str(retain)]
        if hot_days is not None:
            argv += ["--hot-days", str(hot_days)]
        super().__init__(binary, argv, token=token,
                         ready_timeout=ready_timeout)
