"""Horizontal result-plane sharding: a routing client over N ``logd``
shards.

The shard ladder proved the dispatch store scales past one process, and
measured the UNSHARDED logd sink as the new wall (~33k records/s on the
bench host, logd op_stats showing 60 s of busy time in a 13 s run).
This module partitions the RESULT keyspace across N independent logd
processes — each a perfectly ordinary ``cronsun-logd`` (same wire
protocol, same WAL/SQLite sidecar, just a smaller record space) — and
gives every component a drop-in client with the exact JobLogStore
surface, mirroring ``store/sharded.py`` end to end.

Routing — deterministic, shared with ``native/agentd.cc`` bit-for-bit:

- the token is the record's ``job_id``, hashed with the same 64-bit
  FNV-1a the store shards use (:func:`~cronsun_tpu.store.sharded.fnv1a`
  — Python's salted builtin hash can't agree across processes).  A
  job's ``job_log`` rows, its ``job_latest_log`` entries, and its
  retention trim therefore all live on ONE shard: the hot write path
  (an agent's bulk flush) splits per shard and fans out concurrently,
  and the common dashboard filter ("this job's history") is a
  single-shard read.
- ``node`` and ``account`` tables pin to SHARD 0 — tiny, single-writer,
  not worth scattering.

Record ids are encoded ``raw * N + shard`` so they stay globally unique
and decodable: ``get_log`` routes by ``id % N``, and a follow poller
can recover each record's shard from the id alone.

Writes: :meth:`ShardedJobLogStore.create_job_logs` splits the batch by
job token, derives ONE pinned idempotency token per sub-batch from the
caller's batch token (``idem + ".s<shard>"`` — deterministic, so a
whole-batch retry re-derives the same per-shard tokens), and fans the
sub-batches out concurrently.  A retry after a partial failure re-sends
every sub-batch; shards that already applied dedup server-side — the
PR 4 whole-batch retry contract, unchanged PER SHARD.

Reads scatter-gather:

- ``query_logs`` fetches up to ``page * page_size`` candidates per
  shard (paging the shard at a fixed stride) and merge-sorts with a
  DOCUMENTED stable tie order so paging is deterministic:
  ``(begin_ts DESC, shard ASC, id ASC)`` for history rows, and
  ``(begin_ts DESC, job_id ASC, node ASC)`` for the id-less latest
  view — the latter is exactly the order both backends pin, so the
  merged latest view is byte-identical to an unsharded sink's.
- cursor mode (``after_id``) becomes a PER-SHARD CURSOR VECTOR (the
  sharded store's revision-vector pattern): each shard keeps its own
  monotone id space, so one scalar cannot resume N independent
  streams without missing a slow shard's records.  Results merge by
  ``(raw id ASC, shard ASC)`` and carry encoded ids; the consumer
  advances its vector per delivered record (:func:`advance_cursor`).
- ``stat_overall`` / ``stat_day`` / ``stat_days`` sum per-shard
  counters — exact, because every record lands on exactly one shard
  (and a day in the global top-n is by date order within every
  shard's top-n where present).

The shard topology is pinned by a ``logmap`` record on shard 0: the
first client publishes ``{"n": N, "hash": HASH}``, every later client
verifies it, and a client configured with a different shard count
refuses to start instead of scattering one job's history under two
layouts.  With ONE shard every operation passes through verbatim — no
split, no id encoding, no pin write (:func:`connect_sharded_sink`
returns the plain client after a read-only pin check).
"""

from __future__ import annotations

import json
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.breaker import BreakerBank, ShardDegradedError  # noqa: F401
# (ShardDegradedError re-exported: the error create_job_logs raises
# fail-fast into the agents' retry ladders when a shard's breaker is
# open)
from ..store.sharded import breaker_env_deadline, fnv1a
from .joblog import LogRecord, SubscriptionLost

LOG_HASH_SCHEME = "fnv1a-job-v1"


def log_shard_index(job_id: str, nshards: int) -> int:
    """The routing hash: 64-bit FNV-1a of the raw ``job_id`` mod N —
    deterministic across processes and languages (native/agentd.cc
    carries the same constants)."""
    if nshards <= 1:
        return 0
    return fnv1a(job_id) % nshards


def encode_log_id(raw: int, shard: int, nshards: int) -> int:
    """Globally-unique record id: ``raw * N + shard``.  Monotone per
    shard, decodable without a lookup."""
    return raw * nshards + shard


def decode_log_id(gid: int, nshards: int) -> Tuple[int, int]:
    """-> (raw per-shard id, shard index)."""
    return gid // nshards, gid % nshards


def advance_cursor(vec: Sequence[int], recs, nshards: int) -> List[int]:
    """Next per-shard cursor vector after consuming ``recs`` (records
    with ENCODED ids, as returned by a sharded cursor query): each
    delivered record advances its own shard's entry; shards that
    delivered nothing keep theirs."""
    out = list(vec)
    for r in recs:
        if r.id is None:
            continue
        raw, si = decode_log_id(r.id, nshards)
        if raw > out[si]:
            out[si] = raw
    return out


def fetch_top(client, kw: dict, need: int):
    """Top ``need`` rows from one sink client under ``kw``'s filters
    (the client's own documented order), paging at a fixed stride so
    backend OFFSET math stays consistent.  -> (rows, client total).
    Module-level so the web tier's response cache can compute one
    shard's partial with exactly the scatter-gather's fetch."""
    ps = max(1, min(500, need))
    out: List[LogRecord] = []
    total = 0
    page = 1
    while len(out) < need:
        rows, total = client.query_logs(**kw, page=page, page_size=ps)
        out.extend(rows)
        if len(rows) < ps:
            break
        page += 1
    return out[:need], total


def merge_latest_parts(parts, page: int, page_size: int):
    """Merge per-shard latest-view partials [(rows, total), ...] into
    the one global page: both backends pin (begin_ts DESC, job_id,
    node) and the (job, node) space partitions by shard, so this sort
    IS the global order — byte-identical to an unsharded sink.  Shared
    by the sharded read path and the web response cache (which reuses
    unchanged shards' cached partials before this merge)."""
    rows = [r for part, _t in parts for r in part]
    rows.sort(key=lambda r: (-r.begin_ts, r.job_id, r.node))
    total = sum(t for _p, t in parts)
    return rows[(page - 1) * page_size: page * page_size], total


def merge_stat_days(parts: List[List[dict]], n_days: int) -> List[dict]:
    """Sum per-shard stat_days partials per day, newest first.  Exact:
    each shard's top-n days contain every one of its days that falls
    in the GLOBAL top-n (day order is global).  Shared by the sharded
    read path and the web response cache."""
    days: Dict[str, List[int]] = {}
    for part in parts:
        for d in part:
            ent = days.setdefault(d["day"], [0, 0, 0])
            ent[0] += d["total"]
            ent[1] += d["successed"]
            ent[2] += d["failed"]
    return [{"day": day, "total": t, "successed": s, "failed": f}
            for day, (t, s, f) in
            sorted(days.items(), reverse=True)[:max(0, n_days)]]


class ShardedLogSubscription:
    """Merged change stream over one subscription PER SHARD — the
    cursor-vector machinery, live.  Each shard's drainer re-encodes its
    raw ids (``raw * N + shard``) and appends into one bounded merged
    buffer; per-shard order is preserved (cross-shard interleave is
    arbitrary, exactly like concurrent writes).  ``vector`` is the
    per-shard resume cursor advanced per DELIVERED event — hand it to
    ``query_logs(after_id=vector)`` to re-list after a ``lost``, or to
    ``subscribe`` to resume.  Any shard's loss (overflow, transport)
    latches the merged stream ``lost``: one vector describes one
    consistent resume point, so a half-lost stream is not a thing."""

    def __init__(self, sharded: "ShardedJobLogStore", vec: List[int],
                 cap: int):
        self._n = sharded.nshards
        self._cap = max(1, int(cap))
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._buf: deque = deque()
        self.lost = False
        self.closed = False
        self.on_ready = None
        self._subs: list = []
        try:
            # raw clients, not breaker guards: a stream is long-lived —
            # failure latches ``lost`` and the consumer re-subscribes
            # at its own cadence, which IS the breaker story here
            for si in range(self._n):
                self._subs.append(
                    sharded._raw[si].subscribe(after_id=vec[si],
                                               cap=self._cap))
        except BaseException:
            for s in self._subs:
                s.close()
            raise
        self.rev = [s.rev for s in self._subs]
        self.gap = any(s.gap for s in self._subs)
        # resume vector: a gap (or from-now) shard starts at its stream
        # revision — the caller re-lists the gap once, signalled by
        # ``gap`` — a replayed shard at the requested cursor
        self._vec = [self._subs[si].rev
                     if vec[si] <= 0 or self._subs[si].gap else vec[si]
                     for si in range(self._n)]
        self._threads = [
            threading.Thread(target=self._drain_loop, args=(si,),
                             daemon=True, name=f"logsub-merge-{si}")
            for si in range(self._n)]
        for t in self._threads:
            t.start()

    def _drain_loop(self, si: int):
        sub = self._subs[si]
        while True:
            try:
                evs = sub.get(timeout=0.5)
            except SubscriptionLost:
                self._mark_lost()
                return
            with self._cv:
                if self.closed or self.lost:
                    return
            if not evs:
                continue
            enc = [(encode_log_id(e[0], si, self._n),) + tuple(e[1:])
                   for e in evs]
            ready = None
            with self._cv:
                if self.closed or self.lost:
                    return
                if len(self._buf) + len(enc) > self._cap:
                    self._buf.clear()
                    self.lost = True
                else:
                    self._buf.extend(enc)
                self._cv.notify_all()
                ready = self.on_ready
            if ready is not None:
                ready(self)
            if self.lost:
                return

    def _mark_lost(self):
        ready = None
        with self._cv:
            if not self.closed:
                self._buf.clear()
                self.lost = True
                ready = self.on_ready
            self._cv.notify_all()
        if ready is not None:
            ready(self)

    @property
    def vector(self) -> List[int]:
        """Per-shard resume cursor of everything DELIVERED so far."""
        with self._mu:
            return list(self._vec)

    def _take_locked(self) -> list:
        out = list(self._buf)
        self._buf.clear()
        for e in out:
            raw, si = decode_log_id(e[0], self._n)
            if raw > self._vec[si]:
                self._vec[si] = raw
        return out

    def drain(self) -> list:
        with self._cv:
            if self.lost:
                raise SubscriptionLost("sharded log subscription lost")
            return self._take_locked()

    def get(self, timeout: Optional[float] = None) -> list:
        """Pending events (encoded ids), blocking up to ``timeout``."""
        with self._cv:
            if not self._buf and not self.lost and not self.closed:
                self._cv.wait(timeout)
            if self.lost:
                raise SubscriptionLost("sharded log subscription lost")
            if self.closed and not self._buf:
                raise SubscriptionLost("sharded log subscription closed")
            return self._take_locked()

    def close(self):
        with self._cv:
            self.closed = True
            self._cv.notify_all()
        for s in self._subs:
            s.close()


class ShardedJobLogStore:
    """Routing client over N result-store shards with the full
    JobLogStore surface — agents, web, noticer and ctl run unchanged
    against it.

    ``shards`` is a list of sink clients (RemoteJobLogStore per shard
    in production; in-process JobLogStore works too, which is what the
    differential tests use)."""

    def __init__(self, shards: Sequence, verify_map: bool = True,
                 shard_deadline: Optional[float] = None,
                 breaker_fails: int = 3, breaker_cooldown: float = 1.0):
        if not shards:
            raise ValueError("ShardedJobLogStore needs at least one shard")
        self._raw = list(shards)
        self.nshards = len(self._raw)
        # per-shard brownout handling (the store client's contract,
        # store/sharded.py): with a deadline configured (param or
        # CRONSUN_SHARD_DEADLINE_S) each shard is breaker-guarded —
        # writes against an OPEN shard fail fast into the agents'
        # record-flush retry ladder (idem tokens pinned, so nothing
        # duplicates on the re-send), dashboard reads skip it with a
        # loud shard_degraded count.  deadline <= 0 (default) disables:
        # self.shards IS the raw list, behavior byte-identical.
        if shard_deadline is None:
            shard_deadline = breaker_env_deadline()
        self.shard_deadline = shard_deadline
        self._bank = BreakerBank(self.nshards, shard_deadline,
                                 fail_threshold=breaker_fails,
                                 cooldown=breaker_cooldown,
                                 label="logsink shard")
        self._breakers = self._bank.breakers
        self.shards = self._bank.guards(self._raw,
                                        healthy_errors=(KeyError,))
        self._pool = (ThreadPoolExecutor(
            max_workers=max(2, 2 * self.nshards) +
            (2 * self.nshards if shard_deadline > 0 else 0),
            thread_name_prefix="logshard-fan") if self.nshards > 1 else None)
        self._lock = threading.Lock()
        if self.nshards > 1 and verify_map:
            self._pin_log_map()

    def arm_breaker_notices(self, store, prefix: str = "/cronsun",
                            source: str = ""):
        """Route breaker OPEN transitions into the noticer plane.  The
        logsink client cannot write notices itself (they live in the
        COORDINATION store) — the process that owns both (the web
        server hosts the noticer in the reference) passes its store
        here.  No-op when the breaker bank is disabled."""
        self._bank.arm_notices(store, prefix, source=source)

    # ---- routing ---------------------------------------------------------

    def _idx(self, job_id: str) -> int:
        return log_shard_index(job_id, self.nshards)

    def _fan(self, fns):
        """Run thunks concurrently (one per shard touched); re-raises
        the first failure after all complete."""
        fns = list(fns)
        if len(fns) == 1 or self._pool is None:
            return [fn() for fn in fns]
        futs = [self._pool.submit(fn) for fn in fns]
        out, first_err = [], None
        for f in futs:
            try:
                out.append(f.result())
            except BaseException as e:  # noqa: BLE001 — collected below
                out.append(None)
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return out

    def _tolerant(self, i: int, fn, default=None):
        """A dashboard read that can TOLERATE a missing shard
        (core.breaker.BreakerBank): an open breaker yields ``default``
        (counted loudly) instead of failing — or stalling — the whole
        scatter-gather."""
        return self._bank.tolerant(i, fn, default=default)

    def breaker_snapshot(self) -> List[dict]:
        """Per-shard breaker state + degraded-read counts (rendered at
        /v1/metrics beside the store's).  Empty when disabled."""
        return self._bank.snapshot()

    def _pin_log_map(self):
        got = self.shards[0].logmap(self.nshards, LOG_HASH_SCHEME)
        if not isinstance(got, dict) or got.get("n") != self.nshards \
                or got.get("hash") != LOG_HASH_SCHEME:
            raise RuntimeError(
                f"logmap mismatch: result-store set was laid out as "
                f"{got!r}, this client is configured for "
                f"{{'n': {self.nshards}, 'hash': {LOG_HASH_SCHEME!r}}} — "
                "refusing to scatter one job's history under two "
                "topologies")

    # ---- writes ----------------------------------------------------------

    def create_job_log(self, rec: LogRecord, idem: str = ""):
        # idem passes through untouched (the wire client mints its own
        # per-call token when empty, exactly the unsharded behavior)
        si = self._idx(rec.job_id)
        self.shards[si].create_job_log(rec, idem=idem)
        if rec.id is not None:
            rec.id = encode_log_id(rec.id, si, self.nshards)
        return rec.id

    def create_job_logs(self, recs, idem: str = "",
                        spans: Optional[list] = None) -> list:
        """Split the batch by job token, fan the sub-batches out
        concurrently — one bulk RPC per shard touched, each riding a
        per-shard idempotency token DERIVED from the batch token
        (``idem + ".s<shard>"``).  A caller retrying the whole logical
        batch (the agents' record flushers, token pinned) re-derives
        the same per-shard tokens, so shards that applied the first
        attempt dedup server-side while the failed shard gets its
        records — whole-batch retry, per shard.  Raises on ANY shard
        failing (after every sub-batch settles), matching the
        unsharded client's all-or-retry contract."""
        recs = list(recs)
        # trace spans route by the SAME job token as their records, so
        # a trace's spans co-locate with its job's history
        span_groups: Dict[int, list] = {}
        for sp in spans or []:
            jid = sp.get("job") if isinstance(sp, dict) else None
            if isinstance(jid, str):
                span_groups.setdefault(self._idx(jid), []).append(sp)
        if not recs and not span_groups:
            return []
        groups: Dict[int, list] = {}
        for pos, r in enumerate(recs):
            groups.setdefault(self._idx(r.job_id), []).append((pos, r))
        for si in span_groups:
            groups.setdefault(si, [])

        def send(si, group):
            sub = [r for _p, r in group]
            # no caller token -> each shard's wire client mints its own
            # per-call token (a bare ".s<i>" suffix would be one shared
            # token for EVERY token-less batch — a dedup collision)
            sp = span_groups.get(si)
            if sp:
                self.shards[si].create_job_logs(
                    sub, idem=f"{idem}.s{si}" if idem else "", spans=sp)
            else:
                self.shards[si].create_job_logs(
                    sub, idem=f"{idem}.s{si}" if idem else "")
        self._fan([lambda si=si, g=g: send(si, g)
                   for si, g in groups.items()])
        for si, group in groups.items():
            for _pos, r in group:
                if r.id is not None:
                    r.id = encode_log_id(r.id, si, self.nshards)
        return [r.id for r in recs]

    # ---- queries ---------------------------------------------------------

    def _fetch_top(self, si: int, kw: dict, need: int):
        return fetch_top(self.shards[si], kw, need)

    def query_logs(self, node: Optional[str] = None,
                   job_ids: Optional[List[str]] = None,
                   name_like: Optional[str] = None,
                   begin: Optional[float] = None,
                   end: Optional[float] = None,
                   failed_only: bool = False,
                   latest: bool = False,
                   page: int = 1, page_size: int = 50,
                   after_id=None) -> Tuple[List[LogRecord], int]:
        """Scatter-gather read.  ``after_id`` in SHARDED cursor mode is
        a per-shard raw-id VECTOR (list/tuple, one entry per shard;
        scalar 0 means "from the beginning everywhere") — one scalar
        cannot resume N independent id spaces without skipping a slow
        shard's records.  Cursor results merge by (raw id ASC, shard
        ASC) with total pinned to -1; the consumer advances its vector
        from the delivered encoded ids (:func:`advance_cursor`)."""
        kw = dict(node=node, job_ids=job_ids, name_like=name_like,
                  begin=begin, end=end, failed_only=failed_only,
                  latest=latest)
        page = max(1, min(page, 1 << 40))
        page_size = max(1, min(page_size, 500))
        # a job-filtered read touches only the filter's shards — the
        # dashboard's "this job's history" is a single-shard read
        sids = sorted({self._idx(j) for j in job_ids}) if job_ids \
            else list(range(self.nshards))

        if after_id is not None and not latest:
            if isinstance(after_id, (list, tuple)):
                if len(after_id) != self.nshards:
                    raise ValueError(
                        f"cursor vector has {len(after_id)} entries for "
                        f"{self.nshards} shards")
                vec = [int(v) for v in after_id]
            elif int(after_id) == 0:
                vec = [0] * self.nshards
            else:
                raise ValueError(
                    "a sharded sink resumes from a per-shard cursor "
                    "vector (advance_cursor()), not a scalar id")
            parts = self._fan([
                self._tolerant(si, lambda si=si: (
                    si, self.shards[si].query_logs(
                        **kw, after_id=vec[si], page=1,
                        page_size=page_size)[0]))
                for si in sids])
            parts = [p for p in parts if p is not None]
            merged = [(r.id, si, r) for si, rows in parts for r in rows]
            merged.sort(key=lambda t: (t[0], t[1]))
            out = []
            for raw, si, r in merged[:page_size]:
                r.id = encode_log_id(raw, si, self.nshards)
                out.append(r)
            return out, -1

        need = page * page_size
        parts = self._fan([
            self._tolerant(si, lambda si=si: (
                si, *self._fetch_top(si, kw, need)))
            for si in sids])
        parts = [p for p in parts if p is not None]
        total = sum(t for _si, _rows, t in parts)
        if latest:
            return merge_latest_parts(
                [(part, t) for _si, part, t in parts], page, page_size)
        else:
            # documented cross-shard tie order: (begin_ts DESC, shard
            # ASC, id ASC) — per-shard order is preserved, ties across
            # shards break deterministically so page N+1 never
            # re-serves or skips a row page N touched
            keyed = [(-r.begin_ts, si, r.id, r)
                     for si, part, _t in parts for r in part]
            keyed.sort(key=lambda t: t[:3])
            rows = []
            for _b, si, raw, r in keyed:
                r.id = encode_log_id(raw, si, self.nshards)
                rows.append(r)
        return rows[(page - 1) * page_size: page * page_size], total

    def get_log(self, log_id: int) -> Optional[LogRecord]:
        raw, si = decode_log_id(int(log_id), self.nshards)
        rec = self.shards[si].get_log(raw)
        if rec is not None and rec.id is not None:
            rec.id = encode_log_id(rec.id, si, self.nshards)
        return rec

    # ---- stats (exact per-shard summation) -------------------------------

    @staticmethod
    def _sum_stats(parts: List[dict]) -> dict:
        return {k: sum(p[k] for p in parts)
                for k in ("total", "successed", "failed")}

    def stat_overall(self) -> dict:
        parts = self._fan([
            self._tolerant(i, lambda s=s: s.stat_overall())
            for i, s in enumerate(self.shards)])
        return self._sum_stats([p for p in parts if p is not None])

    def stat_day(self, day: str) -> dict:
        parts = self._fan([
            self._tolerant(i, lambda s=s: s.stat_day(day))
            for i, s in enumerate(self.shards)])
        return self._sum_stats([p for p in parts if p is not None])

    def stat_days(self, n_days: int) -> List[dict]:
        parts = self._fan([
            self._tolerant(i, lambda s=s: s.stat_days(n_days))
            for i, s in enumerate(self.shards)])
        return merge_stat_days([p for p in parts if p is not None],
                               n_days)

    # ---- change revision / ops -------------------------------------------

    def revision(self) -> List[int]:
        """Per-shard revision VECTOR (each entry that shard's max
        record id) — the web tier's ETag key and a follow poller's
        tail-cursor bootstrap in one read."""
        return self._fan([lambda s=s: s.revision() for s in self.shards])

    def tail_snapshot(self, limit: int = 0):
        """Per-shard atomic (revision, tail) snapshots, merged: the
        vector is each shard's snapshot revision, the tail is the last
        ``limit`` records under the cursor merge order (raw id, shard)
        with ENCODED ids.  Each shard's pair is atomic, so a cursor
        bootstrapped at this vector never skips a record that was
        visible in (or before) the returned tail."""
        parts = self._fan([lambda si=si: self.shards[si].tail_snapshot(limit)
                           for si in range(self.nshards)])
        vec = [rev for rev, _recs in parts]
        merged = [(r.id, si, r) for si, (_rev, recs) in enumerate(parts)
                  for r in recs]
        merged.sort(key=lambda t: (t[0], t[1]))
        out = []
        for raw, si, r in merged[-limit:] if limit else []:
            r.id = encode_log_id(raw, si, self.nshards)
            out.append(r)
        return vec, out

    def subscribe(self, after_id=0, cap: int = 8192
                  ) -> ShardedLogSubscription:
        """Merged live change stream across every shard.  ``after_id``
        is a per-shard cursor VECTOR (scalar <= 0 means from-now on
        every shard) — the same shape ``query_logs`` cursor mode takes
        and ``tail_snapshot`` returns.  Delivered events carry ENCODED
        ids; resume from ``sub.vector``."""
        if isinstance(after_id, (list, tuple)):
            if len(after_id) != self.nshards:
                raise ValueError(
                    f"cursor vector has {len(after_id)} entries for "
                    f"{self.nshards} shards")
            vec = [int(v) for v in after_id]
        elif int(after_id) <= 0:
            vec = [0] * self.nshards
        else:
            raise ValueError(
                "a sharded sink subscribes from a per-shard cursor "
                "vector (sub.vector), not a scalar id")
        return ShardedLogSubscription(self, vec, cap)

    def age_out(self, now=None) -> int:
        """Run a cold-aging pass on every shard; returns total aged."""
        return sum(self._fan([lambda s=s: s.age_out(now)
                              for s in self.shards]))

    def tier_info(self) -> List[dict]:
        """Per-shard tiering snapshots, shard order."""
        return self._fan([lambda s=s: s.tier_info() for s in self.shards])

    def op_stats(self) -> dict:
        """Per-op stats MERGED across shards (counts/total summed,
        max_ms maxed) — same shape as a single sink's."""
        parts = self.op_stats_shards()
        if len(parts) == 1:
            return parts[0]
        merged: Dict[str, dict] = {}
        for part in parts:
            for op, ent in part.items():
                m = merged.setdefault(op, {"count": 0, "total_ms": 0.0,
                                           "max_ms": 0.0})
                m["count"] += ent.get("count", 0)
                m["total_ms"] = round(
                    m["total_ms"] + ent.get("total_ms", 0.0), 3)
                m["max_ms"] = max(m["max_ms"], ent.get("max_ms", 0.0))
        return merged

    def op_stats_shards(self) -> List[dict]:
        """Per-SHARD op stats, shard order — /v1/metrics renders these
        with a ``shard`` label when more than one is present.  A
        degraded shard reports ``{}`` (metrics scraping must not stall
        behind a browned-out shard)."""
        return self._fan([
            self._tolerant(i, lambda s=s: s.op_stats(), default={})
            for i, s in enumerate(self.shards)])

    def logmap(self, n=None, hash=None):
        return self.shards[0].logmap(n, hash)

    # ---- trace plane -----------------------------------------------------

    def trace_get(self, job_id: str, epoch_s: int) -> list:
        """One trace lives on ONE shard (spans route by job token with
        their records) — a direct read, no scatter."""
        return self.shards[self._idx(job_id)].trace_get(job_id,
                                                        int(epoch_s))

    def trace_top(self, n: int = 256) -> list:
        """Recent-trace summaries from every shard, concatenated (the
        web tier sorts); a degraded shard contributes nothing."""
        parts = self._fan([
            self._tolerant(i, lambda s=s, m=n: s.trace_top(m),
                           default=[])
            for i, s in enumerate(self.shards)])
        return [t for part in parts for t in (part or [])]

    def trace_stats(self) -> dict:
        """Per-stage histogram counters SUMMED across shards — sound
        because the bucket bounds are fixed fleet-wide."""
        parts = self._fan([
            self._tolerant(i, lambda s=s: s.trace_stats(), default={})
            for i, s in enumerate(self.shards)])
        merged: dict = {"spans_total": 0, "stages": {}}
        for part in parts:
            if not part:
                continue
            merged["spans_total"] += part.get("spans_total", 0)
            for stage, ent in (part.get("stages") or {}).items():
                m = merged["stages"].setdefault(
                    stage, {"buckets": [0] * len(ent.get("buckets", [])),
                            "sum": 0.0, "count": 0})
                b = m["buckets"]
                for i, v in enumerate(ent.get("buckets", [])):
                    if i >= len(b):
                        b.extend([0] * (i + 1 - len(b)))
                    b[i] += int(v)
                m["sum"] = round(m["sum"] + ent.get("sum", 0.0), 3)
                m["count"] += ent.get("count", 0)
        return merged

    # ---- node mirror + accounts (tiny, single-writer: shard 0) -----------

    def upsert_node(self, node_id: str, doc: str, alived: bool):
        self.shards[0].upsert_node(node_id, doc, alived)

    def set_node_alived(self, node_id: str, alived: bool):
        self.shards[0].set_node_alived(node_id, alived)

    def get_nodes(self) -> List[dict]:
        return self.shards[0].get_nodes()

    def get_node(self, node_id: str) -> Optional[dict]:
        return self.shards[0].get_node(node_id)

    def upsert_account(self, email: str, doc: str):
        self.shards[0].upsert_account(email, doc)

    def get_account(self, email: str) -> Optional[str]:
        return self.shards[0].get_account(email)

    def list_accounts(self) -> List[str]:
        return self.shards[0].list_accounts()

    def delete_account(self, email: str) -> bool:
        return self.shards[0].delete_account(email)

    # ---- lifecycle -------------------------------------------------------

    def close(self):
        for s in self._raw:
            try:
                s.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        if self._pool is not None:
            self._pool.shutdown(wait=False)


def reshard_sinks(src: Sequence, dst: Sequence, batch: int = 500,
                  on_log=None) -> dict:
    """Online-resharding escape hatch: dump every record from the
    ``src`` shard set, rehash by job token under the ``dst`` layout,
    and load — closing the "record ids encode the shard count" trap
    (ids are re-encoded ``raw' * N' + shard'`` as the destination
    assigns them; the destination ``logmap`` is re-pinned to N').

    The dump rides per-shard cursors (``after_id`` from 0 — the tiered
    backends merge their COLD segments below the watermark, so aged
    history migrates too) and merges by (raw id, shard), the sharded
    cursor order; the load preserves that order, so each destination
    shard's per-job id order matches the source's and the rebuilt
    latest/stat tables land identical (stats for records the source
    had already retention-evicted cannot migrate — reported loudly in
    the summary as ``stat_shortfall``).

    ``src``/``dst`` are lists of sink clients (RemoteJobLogStore in
    production; in-process JobLogStore in tests).  Destination shards
    must be EMPTY (revision 0) and unpinned — refusing a half-full
    target beats interleaving two id spaces."""
    log_ = on_log or (lambda *a: None)
    if not src or not dst:
        raise ValueError("reshard needs at least one source and one "
                         "destination shard")
    sgot = src[0].logmap()
    if sgot is not None and sgot.get("n") != len(src):
        raise RuntimeError(
            f"source logmap {sgot!r} does not match the provided "
            f"{len(src)} source addresses — a partial source set would "
            "silently drop the missing shards' history")
    for i, s in enumerate(dst):
        rev = s.revision()
        if rev != 0:
            raise RuntimeError(
                f"destination shard {i} is not empty (revision {rev}) — "
                "reshard loads into a fresh shard set")
    got = dst[0].logmap()
    if got is not None and got.get("n") != len(dst):
        raise RuntimeError(
            f"destination logmap {got!r} does not match the "
            f"{len(dst)}-shard layout")
    out_sink = ShardedJobLogStore(dst) if len(dst) > 1 else dst[0]

    # dump: per-source-shard cursors, merged by (raw id, shard) — the
    # sharded cursor order — loaded in that order per batch
    cursors = [0] * len(src)
    done = [False] * len(src)
    moved = 0
    while not all(done):
        rows_batch = []
        for si, s in enumerate(src):
            if done[si]:
                continue
            rows, _t = s.query_logs(after_id=cursors[si], page=1,
                                    page_size=batch)
            if not rows:
                done[si] = True
                continue
            cursors[si] = rows[-1].id
            rows_batch.extend((r.id, si, r) for r in rows)
        if not rows_batch:
            break
        rows_batch.sort(key=lambda t: (t[0], t[1]))
        recs = []
        for _raw, _si, r in rows_batch:
            r.id = None          # destination assigns its own raw ids
            recs.append(r)
        out_sink.create_job_logs(recs)
        moved += len(recs)
        log_(f"reshard: moved {moved} records")

    # node mirror + accounts pin to shard 0 on both layouts
    nodes = 0
    for d in src[0].get_nodes():
        doc = dict(d)
        alived = bool(doc.pop("alived", False))
        out_sink.upsert_node(doc.get("id", ""), json.dumps(doc), alived)
        nodes += 1
    accounts = 0
    for doc in src[0].list_accounts():
        email = json.loads(doc).get("email", "")
        if email:
            out_sink.upsert_account(email, doc)
            accounts += 1

    def latest_map(sink_or_shards):
        out: Dict[tuple, float] = {}
        clients = sink_or_shards if isinstance(sink_or_shards, list) \
            else [sink_or_shards]
        for cl in clients:
            page = 1
            while True:
                rows, _t = cl.query_logs(latest=True, page=page,
                                         page_size=500)
                out.update(((r.job_id, r.node), r.begin_ts)
                           for r in rows)
                if len(rows) < 500:
                    break
                page += 1
        return out

    src_total = sum(s.stat_overall()["total"] for s in src)
    dst_total = out_sink.stat_overall()["total"]
    # the latest view survives retention (it summarizes ALL history),
    # but the destination rebuilds it purely from migrated records — a
    # (job, node) whose every record was evicted cannot reappear, and
    # one whose NEWEST record was evicted rebuilds from an older run.
    # Both counted and warned, not silently shrunk/regressed.
    src_latest = latest_map(src)
    dst_latest = latest_map(out_sink)
    lost_latest = set(src_latest) - set(dst_latest)
    stale_latest = {p for p, ts in dst_latest.items()
                    if p in src_latest and ts < src_latest[p]}
    summary = {"records": moved, "nodes": nodes, "accounts": accounts,
               "src_stat_total": src_total, "dst_stat_total": dst_total,
               "stat_shortfall": src_total - dst_total,
               "latest_shortfall": len(lost_latest),
               "latest_stale": len(stale_latest)}
    if summary["stat_shortfall"]:
        log_(f"reshard: WARNING — {summary['stat_shortfall']} executions "
             "counted in the source stats have no surviving record "
             "(retention-evicted before the reshard); the destination "
             "counters reflect migrated records only")

    def name_pairs(pairs):
        return (", ".join(f"{j}@{n}" for j, n in sorted(pairs)[:5])
                + ("…" if len(pairs) > 5 else ""))
    if lost_latest:
        log_(f"reshard: WARNING — {len(lost_latest)} (job, node) latest-"
             "status rows had no surviving record to rebuild from "
             "(fully retention-evicted jobs); they are absent from the "
             "destination's latest view: " + name_pairs(lost_latest))
    if stale_latest:
        log_(f"reshard: WARNING — {len(stale_latest)} (job, node) "
             "latest-status rows rebuilt from an OLDER surviving run "
             "(the newest record was retention-evicted): "
             + name_pairs(stale_latest))
    return summary


def verify_single_sink(sink):
    """Topology pin for a SINGLE-address client: a stale one-logd
    config pointed at shard 0 of a multi-shard layout must refuse (it
    would see a fraction of every job's history and write new records
    into the wrong id space), not silently serve.  Read-only — an
    un-sharded deployment never writes the pin, so its behavior is
    unchanged."""
    try:
        got = sink.logmap()
    except Exception:  # noqa: BLE001 — pre-logmap server: nothing to pin
        return
    if got is None:
        return
    if not isinstance(got, dict) or got.get("n") != 1:
        raise RuntimeError(
            f"logmap mismatch: result-store set was laid out as {got!r}, "
            "this client is configured for a single result store — "
            "refusing to scatter one job's history under two topologies")


def connect_sharded_sink(addrs: Sequence[str], timeout: float = 10.0,
                         token: str = "", sslctx=None,
                         tls_hostname: str = ""):
    """Connect a routing client to a logd shard set.  One address
    returns a plain RemoteJobLogStore (byte-identical single-sink
    behavior) after the read-only pin check; several return a
    ShardedJobLogStore that pins/verifies the logmap."""
    from .serve import RemoteJobLogStore
    addrs = [a for a in addrs if a]
    if not addrs:
        raise ValueError("logsink address list has no host:port entries")
    conns = []
    try:
        for addr in addrs:
            host, _, port = addr.rpartition(":")
            conns.append(RemoteJobLogStore(host or "127.0.0.1", int(port),
                                           timeout=timeout, token=token,
                                           sslctx=sslctx,
                                           tls_hostname=tls_hostname))
    except BaseException:
        for c in conns:
            c.close()
        raise
    if len(conns) == 1:
        try:
            verify_single_sink(conns[0])
        except BaseException:
            conns[0].close()
            raise
        return conns[0]
    return ShardedJobLogStore(conns)
