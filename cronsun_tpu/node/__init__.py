"""Node-side runtime: executor, process registry, agent."""

from .executor import ExecResult, Executor  # noqa: F401
