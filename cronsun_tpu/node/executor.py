"""Job execution: fork/exec with setuid, timeout, retry, concurrency gate.

The Python analogue of the reference's execution tail (job.go:404-470 run,
job.go:134-187 retry + Parallels gate):

- commands are tokenized with shell quoting (shlex) — a deliberate
  improvement over the reference's whitespace-only split (job.go:391-393),
  which cannot express arguments containing spaces;
- ``user`` demotes the child via setuid/setgid before exec (reference
  job.go:413-434) — requires running as root, otherwise recorded as failure;
- timeout kills the whole process group (reference uses CommandContext,
  job.go:437-443);
- stdout+stderr are captured combined, truncated at ``max_output`` bytes;
- a per-job concurrency gate mirrors ``Parallels`` (job.go:165-187): when
  the cap is reached the run is *skipped*, not queued;
- retries re-run after ``interval`` seconds, up to ``retry`` times
  (job.go:149-162); a success stops the loop.
"""

from __future__ import annotations

import dataclasses
import os
import pwd
import shlex
import signal
import subprocess
import threading
import time
from typing import Callable, Dict, Optional

DEFAULT_MAX_OUTPUT = 1 << 20  # 1 MiB


@dataclasses.dataclass
class ExecResult:
    success: bool
    output: str
    begin_ts: float
    end_ts: float
    exit_code: int = 0
    error: str = ""
    retries_used: int = 0
    skipped: bool = False        # concurrency gate refused the run

    @property
    def seconds(self) -> float:
        return max(0.0, self.end_ts - self.begin_ts)


class _Gate:
    """Per-job concurrent-execution counter (reference job.go:165-187)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def enter(self, job_id: str, limit: int) -> bool:
        if limit <= 0:
            return True
        with self._lock:
            cur = self._counts.get(job_id, 0)
            if cur >= limit:
                return False
            self._counts[job_id] = cur + 1
            return True

    def leave(self, job_id: str, limit: int):
        if limit <= 0:
            return
        with self._lock:
            cur = self._counts.get(job_id, 0)
            if cur <= 1:
                self._counts.pop(job_id, None)
            else:
                self._counts[job_id] = cur - 1


def _demote(user: str) -> Callable[[], None]:
    info = pwd.getpwnam(user)

    def fn():
        os.setgid(info.pw_gid)
        os.setuid(info.pw_uid)
    return fn


class Executor:
    def __init__(self, max_output: int = DEFAULT_MAX_OUTPUT,
                 clock: Callable[[], float] = time.time):
        self.max_output = max_output
        self.clock = clock
        self._gate = _Gate()

    # -- single run --------------------------------------------------------

    def run_once(self, command: str, user: str = "", timeout: int = 0,
                 env: Optional[dict] = None) -> ExecResult:
        begin = self.clock()
        try:
            argv = shlex.split(command)
        except ValueError as e:
            return ExecResult(False, "", begin, self.clock(),
                              error=f"bad command: {e}")
        if not argv:
            return ExecResult(False, "", begin, self.clock(),
                              error="empty command")
        preexec = None
        if user:
            try:
                demote = _demote(user)
            except KeyError:
                return ExecResult(False, "", begin, self.clock(),
                                  error=f"user {user!r} not found")

            def preexec():  # noqa: F811
                os.setsid()
                demote()
        else:
            preexec = os.setsid

        try:
            proc = subprocess.Popen(
                argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env=env, preexec_fn=preexec, start_new_session=False)
        except (OSError, PermissionError) as e:
            return ExecResult(False, "", begin, self.clock(), error=str(e))

        try:
            out, _ = proc.communicate(timeout=timeout or None)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            out, _ = proc.communicate()
            return ExecResult(
                False, self._trunc(out), begin, self.clock(),
                exit_code=-9, error=f"timeout after {timeout}s")
        end = self.clock()
        return ExecResult(
            success=proc.returncode == 0,
            output=self._trunc(out),
            begin_ts=begin, end_ts=end, exit_code=proc.returncode,
            error="" if proc.returncode == 0
            else f"exit status {proc.returncode}")

    def _trunc(self, out: bytes) -> str:
        if out is None:
            return ""
        if len(out) > self.max_output:
            out = out[:self.max_output] + b"\n...[truncated]"
        return out.decode(errors="replace")

    # -- full job semantics ------------------------------------------------

    def run_job(self, job_id: str, command: str, user: str = "",
                timeout: int = 0, retry: int = 0, interval: int = 0,
                parallels: int = 0, env: Optional[dict] = None,
                sleep: Callable[[float], None] = time.sleep) -> ExecResult:
        """Parallels gate + retry loop around run_once."""
        if not self._gate.enter(job_id, parallels):
            now = self.clock()
            return ExecResult(False, "", now, now, skipped=True,
                              error="parallels limit reached, run skipped")
        try:
            result = self.run_once(command, user, timeout, env)
            attempts = 0
            while not result.success and attempts < retry:
                if interval > 0:
                    sleep(interval)
                attempts += 1
                nxt = self.run_once(command, user, timeout, env)
                nxt.retries_used = attempts
                nxt.begin_ts = result.begin_ts  # whole-run span
                result = nxt
                if result.success:
                    break
            return result
        finally:
            self._gate.leave(job_id, parallels)
