"""Node agent: a thin watch-and-exec shell.

Where the reference's node runs a full cron engine (node/node.go:445-464),
this agent only:

- registers its identity under a lease and keeps it alive
  (node/node.go:64-119 semantics: re-grant + re-put after lapses);
- watches its dispatch prefix for execution orders from the leader
  scheduler and runs them through the Executor;
- watches the once prefix for run-now triggers (value == own id or "" —
  reference node/node.go:423-442; bypasses locks and the parallels gate);
- fences exclusive executions with a create-if-absent (job, second) lock so
  a double-dispatch (leader failover race) still runs exactly once —
  the lease-fenced safety net the central assignment keeps from the
  reference's lock protocol (job.go:243-271);
- maintains the proc registry (leased running-execution keys,
  proc.go:209-256), writes the execution record + stats, and posts failure
  notices for the noticer (job.go:549-579).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import uuid
from typing import Callable, Dict, Optional, Tuple

from .. import log, trace as _trace
from ..core import Group, Job, Keyspace, Node
from ..core.backoff import REC_FLUSH
from ..core.errors import DuplicateNode
from ..core.models import KIND_ALONE
from ..logsink import JobLogStore, LogRecord
from ..store.memstore import DELETE, MemStore, WatchLost
from .executor import ExecResult, Executor

VERSION = "v0.1.0-tpu"


class _ExecTask:
    __slots__ = ("fn", "finished")

    def __init__(self, fn):
        self.fn = fn
        self.finished = threading.Event()

    def done(self) -> bool:
        return self.finished.is_set()

    def run(self):
        try:
            self.fn()
        finally:
            self.finished.set()


class _ExecPool:
    """Bounded pool of DAEMON worker threads.  The reference spawns a
    goroutine per fire (cron.go:237-244); Python needs bounding under
    dispatch bursts, and the workers must be daemons — process exit must
    never block behind a long-running job command (stdlib
    ThreadPoolExecutor joins its non-daemon workers at exit)."""

    def __init__(self, workers: int, prefix: str):
        import queue
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._workers = workers
        for i in range(workers):
            threading.Thread(target=self._worker, daemon=True,
                             name=f"{prefix}-{i}").start()

    def _worker(self):
        while True:
            task = self._q.get()
            if task is None:
                return
            task.run()

    def enqueue(self, task: _ExecTask):
        self._q.put(task)

    def shutdown(self):
        for _ in range(self._workers):
            self._q.put(None)      # idle workers exit; busy ones are daemons


class NodeAgent:
    def __init__(self, store: MemStore, sink: JobLogStore,
                 node_id: Optional[str] = None,
                 ks: Optional[Keyspace] = None,
                 ttl: float = 10.0, proc_ttl: float = 600.0,
                 lock_ttl: float = 300.0, proc_req: float = 0.0,
                 executor: Optional[Executor] = None,
                 clock: Callable[[], float] = time.time,
                 on_fatal: Optional[Callable] = None,
                 dep_events: bool = True,
                 trace_shift: int = _trace.DEFAULT_SHIFT):
        self.store = store
        self.sink = sink
        self.ks = ks or Keyspace()
        self.id = node_id or _local_id()
        self.ttl = ttl
        self.proc_ttl = proc_ttl
        self.lock_ttl = lock_ttl
        self.proc_req = proc_req   # short-run suppression (proc.go:218-236)
        # workflow DAG edge signal: publish one dep/ completion key per
        # finished round (value = the SCHEDULED epoch + outcome, so every
        # node of a Common fan-out writes the same round idempotently)
        self.dep_events = dep_events
        self.executor = executor or Executor()
        self.clock = clock
        self.on_fatal = on_fatal

        self._lease: Optional[int] = None
        self._proc_lease: Optional[int] = None
        self._procs: Dict[str, str] = {}   # live proc keys -> value
        self._procs_mu = threading.Lock()  # guards _procs + _proc_lease
        self._stop = threading.Event()
        self._threads = []
        self._open_watches()
        self.groups: Dict[str, Group] = {}
        self._load_groups()
        self.running: Dict[str, _ExecTask] = {}
        self._bseen: Dict[tuple, float] = {}   # broadcast (job, sec) dedup
        # executions run on a bounded pool: the reference spawns a
        # goroutine per fire (cron.go:237-244) but an unbounded Python
        # thread per order collapses under a dispatch burst — the pool
        # queues instead (orders run late, never dropped, never early)
        self.max_inflight = 64
        self._pool = None
        # staged (not yet due) orders: one monitor thread scans for due
        # work — no per-order timers, and stop() can atomically drop the
        # backlog under the same lock the monitor enqueues under
        self._staged: Dict[str, Tuple[_ExecTask, int]] = {}
        self._stage_mu = threading.Lock()
        self._stage_monitor: Optional[threading.Thread] = None
        self._fence_mu = threading.Lock()
        self._fence_lease_id: Optional[int] = None
        self._fence_rotate_at = 0.0
        # one-RPC claim support (store.claim collapses the fence +
        # proc-registry + order-consume chain); detected once, legacy
        # multi-RPC chain kept as the fallback for older stores
        self._claim_supported = True
        # claim batcher: concurrent due executions queue their claims
        # here and ONE claim_many round trip settles the whole burst
        # (group-commit dynamics: whatever piles up during the in-flight
        # RPC forms the next batch)
        self._claim_pending: list = []
        self._claim_cv = threading.Condition()
        self._claim_thread: Optional[threading.Thread] = None
        import itertools
        self._claim_seq = itertools.count(1)   # per-attempt fence nonces
        # bundle-claim batcher: concurrent due (node, second) bundles —
        # a catch-up drain surfacing a whole backlog at once, the herd
        # case — group-commit into ONE claim_bundle_many round trip; a
        # lone bundle goes through the plain claim_bundle op (equally
        # one RPC, and the degraded-store ladder stays byte-identical)
        self._bundle_pending: list = []
        self._bundle_cv = threading.Condition()
        self._bundle_thread: Optional[threading.Thread] = None
        self._bundle_many_supported = True
        # consumed-order ACKS buffer here and flush in periodic
        # delete_many batches: order deletion is capacity bookkeeping,
        # not correctness (exactly-once rests on the (job, second)
        # fences), so a slow store must never stall an executor thread
        # on a per-fire delete RPC
        self._ack_buf: list = []
        self._ack_mu = threading.Lock()
        # pop+delete ride one flush mutex (the record flusher's pattern):
        # join_running/stop use _flush_acks as a completion barrier, so a
        # batch the background flusher already popped must not still be
        # in flight when a barrier flush returns empty-handed
        self._ack_flush_mu = threading.Lock()
        self._ack_thread: Optional[threading.Thread] = None
        self.ack_flush_interval = 0.05
        # execution records buffer here and flush in batches over the
        # result-store wire (one bulk call per interval, not one round
        # trip per execution — the reference pays 4 Mongo writes per
        # execution, job_log.go:84-133)
        self._rec_buf: list = []
        self._rec_mu = threading.Lock()
        self._rec_flush_mu = threading.Lock()   # pop+write atomicity
        self._rec_flusher: Optional[threading.Thread] = None
        self.rec_flush_interval = 0.05
        # a failed batch parks in the retry slot (idempotency token
        # pinned) and retries with exponential backoff (0.5 s .. 10 s
        # between attempts, NOT every 50 ms flush tick — fast-failing
        # connects would otherwise burn all attempts in ~1 s) for this
        # many attempts before it is declared lost: ~4-5 minutes of
        # sink outage coverage
        self.rec_flush_max_fails = 30
        self._rec_flush_fails = 0
        # (batch, batch idem token, per-record idem tokens, trace spans)
        self._rec_retry: Optional[Tuple[list, str, list, list]] = None
        self._rec_retry_at = 0.0
        # sink-outage backstop: the live buffer stops growing here
        # (oldest dropped, counted) instead of absorbing the outage in
        # unbounded memory
        self.rec_buf_max = 100_000
        self._rec_dropped = 0
        self._rec_drop_log_at = 0.0
        # per-record idempotency on the degraded (no-create_job_logs)
        # path needs the sink to accept an idem kwarg; resolved lazily
        # from the signature (None = not yet probed) — catching
        # TypeError at the call site would misread a TypeError raised
        # INSIDE a conforming sink as "no idem support" and silently
        # disable dedup forever
        self._sink_takes_idem: Optional[bool] = None
        self._sink_spans_ok: Optional[bool] = None
        # record-plane flush telemetry: flush count, records shipped,
        # and the largest batch one flush carried (the coalescing win
        # the bench reads as records-per-flush)
        self._rec_flush_max_batch = 0
        # delayed proc-registry puts (the ProcReq threshold) ride ONE
        # monitor thread instead of a threading.Timer per execution —
        # a timer thread per order was a measured top cost of the
        # dispatch plane at >1k orders/s
        self._pdelay: Dict[int, Tuple[float, Callable]] = {}
        self._pdelay_mu = threading.Lock()
        self._pdelay_thread: Optional[threading.Thread] = None
        self._pdelay_seq = 0
        # snapshot of the process environment taken once: rebuilding the
        # cron-context env from the live os.environ mapping proxy costs
        # ~70 dict-proxy lookups per execution (measured in the dispatch
        # profile); post-start environment changes don't propagate to
        # jobs, which matches the reference (os/exec inherits the env
        # captured at Cmd construction)
        self._base_env = dict(os.environ)
        # watch-invalidated job cache (the reference keeps every job in
        # memory, maintained by watchJobs, node/node.go:121-141,361-391;
        # here bounded and filled on demand so a 1M-job fleet doesn't
        # cost each agent a gigabyte)
        self._job_cache: Dict[tuple, Job] = {}
        self._job_cache_cap = 65536
        # operator metrics (rendered fleet-wide at /v1/metrics); counters
        # are bumped from concurrent pool workers -> lock the increments
        self.stats = {"orders_consumed_total": 0, "execs_total": 0,
                      "execs_failed_total": 0, "watch_losses_total": 0,
                      "ack_flush_total": 0, "ack_flush_orders_total": 0,
                      "rec_flush_total": 0, "rec_flush_records_total": 0,
                      "rec_dropped_total": 0, "dep_events_total": 0,
                      "dep_event_failures_total": 0,
                      "trace_spans_total": 0, "trace_spans_dropped_total": 0}
        # fire-lifecycle tracing: head-sampled (or failed, or per-job
        # trace:true) executions buffer a span here and ride the record
        # flush — zero extra RPCs on the hot path.  The verdict is the
        # same deterministic trace-id hash the scheduler stamps bundles
        # by; CRONSUN_TRACE=off (or trace_shift < 0) disables stamping.
        self.trace_shift = trace_shift if _trace.armed() else -1
        self._span_buf: list = []          # guarded by _rec_mu
        self._span_buf_max = 10_000
        # SLO counters: per-scope execution latency histogram + failure
        # count over EVERY execution (not the sampled subset — burn
        # rates must be unbiased).  Scopes: "" fleet-wide, "t:<tenant>"
        # per tenant, "c:<group>/<job>" per DAG chain member.  The web
        # tier's SLO engine scrapes these from the leased metrics
        # snapshot and sums them across agents (fixed buckets add).
        self._slo: Dict[str, list] = {}    # scope -> [count, fail,
        self._slo_cap = 256                #           sum_ms, buckets]
        self._stats_mu = threading.Lock()
        # scheduled-second -> exec-start lag samples (the end-to-end
        # dispatch SLA), published as p50/p99 in the metrics snapshot
        self._lag_ring: list = []
        from ..metrics import MetricsPublisher
        self.metrics = MetricsPublisher(
            store, self.ks, "node", self.id, self.metrics_snapshot,
            interval_s=10.0, clock=clock)

    def _open_watches(self):
        self._w_dispatch = self.store.watch(
            self.ks.dispatch + self.id + "/")
        self._w_broadcast = self.store.watch(self.ks.dispatch_all)
        self._w_groups = self.store.watch(self.ks.group)
        self._w_once = self.store.watch(self.ks.once)
        self._w_jobs = self.store.watch(self.ks.cmd)

    # ---- registration (node/node.go:64-119) ------------------------------

    def register(self):
        self._probe_duplicate()
        self._lease = self.store.grant(self.ttl + 2)
        self.store.put(self.ks.node_key(self.id),
                       f"{socket.gethostname()}:{os.getpid()}",
                       lease=self._lease)
        self._ensure_proc_lease()
        node = Node(id=self.id, pid=os.getpid(), ip=self.id,
                    hostname=socket.gethostname(), version=VERSION,
                    up_ts=self.clock(), alived=True)
        self.sink.upsert_node(self.id, node.to_json(), alived=True)

    def _probe_duplicate(self):
        """Duplicate-node guard (reference node.go:51-79): if the node key
        is already registered, refuse to start rather than fight over the
        lease.  The registration value is ``hostname:pid``; the signal-0
        probe only applies when the registration came from THIS machine —
        a same-host dead PID (crashed agent) is taken over.  A different
        host's registration is refused outright while its lease lives
        (node death clears it within ttl+2 s); we cannot probe a remote
        PID, and assuming it dead would run two agents under one identity.
        EPERM from the probe means the process exists (owned by another
        user) — that is a live duplicate, not a stale key."""
        kv = self.store.get(self.ks.node_key(self.id))
        if kv is None:
            return
        host, _, pid_s = kv.value.rpartition(":")
        try:
            pid = int(pid_s)
        except ValueError:
            return          # unparseable legacy value: take over
        me = socket.gethostname()
        if host and host != me:
            raise DuplicateNode(
                f"node {self.id!r} already registered on host {host!r} "
                f"(pid {pid}); its lease has not expired")
        if pid == os.getpid():
            return          # keepalive re-register path: our own key
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return          # stale registration from a dead process
        except PermissionError:
            pass            # exists, different user: live duplicate
        raise DuplicateNode(
            f"node {self.id!r} already registered by live pid {pid}")

    def _ensure_proc_lease(self):
        """Keep the shared proc lease alive; on a lapse grant a fresh one
        and re-attach the proc keys of still-running executions (on a lapse
        the keys die with the old lease and the executing list / capacity
        reconciliation would otherwise lose them).  A healthy lease is
        reused — no spurious re-puts."""
        with self._procs_mu:
            if (self._proc_lease is None
                    or not self.store.keepalive(self._proc_lease)):
                self._repair_proc_lease_locked()

    def _repair_proc_lease_locked(self):
        """Grant a fresh proc lease and re-attach live proc keys.  Caller
        must hold ``_procs_mu``."""
        self._proc_lease = self.store.grant(self.proc_ttl)
        for k, v in self._procs.items():
            self.store.put(k, v, lease=self._proc_lease)

    def keepalive_once(self) -> bool:
        ok = self._lease is not None and self.store.keepalive(self._lease)
        if not ok:
            self.register()     # reference re-registers after a lapse
        else:
            self._ensure_proc_lease()
        self.metrics.maybe_publish()
        return ok

    def _bump(self, counter: str, n: int = 1):
        with self._stats_mu:
            self.stats[counter] += n

    def metrics_snapshot(self) -> dict:
        with self._stats_mu:
            snap = dict(self.stats)
            lags = sorted(self._lag_ring)
        if lags:
            q = lambda p: lags[min(len(lags) - 1, int(p * len(lags)))]
            snap["exec_start_lag_p50_s"] = round(q(0.50), 3)
            snap["exec_start_lag_p99_s"] = round(q(0.99), 3)
        snap["running"] = len(self.running)
        snap["procs_registered"] = len(self._procs)
        snap["rec_flush_max_batch"] = self._rec_flush_max_batch
        with self._rec_mu:
            snap["rec_buf"] = len(self._rec_buf)
            snap["trace_span_buf"] = len(self._span_buf)
        # per-scope SLO counters (nested — the generic /v1/metrics
        # numeric-leaf renderer skips it; the web SLO engine and the
        # exec-latency histogram renderer read it explicitly)
        with self._stats_mu:
            if self._slo:
                snap["slo"] = {
                    s: {"count": e[0], "fail": e[1],
                        "sum_ms": round(e[2], 3), "buckets": list(e[3]),
                        "fbuckets": list(e[4])}
                    for s, e in self._slo.items()}
        return snap

    def _record_flushed(self, n: int):
        with self._stats_mu:
            self.stats["rec_flush_total"] += 1
            self.stats["rec_flush_records_total"] += n
        if n > self._rec_flush_max_batch:
            self._rec_flush_max_batch = n

    def unregister(self):
        if self._lease is not None:
            self.store.revoke(self._lease)
            self._lease = None
        if self._proc_lease is not None:
            self.store.revoke(self._proc_lease)
            self._proc_lease = None
        self.metrics.revoke()   # don't render a gone node for the TTL
        self.sink.set_node_alived(self.id, False)

    # ---- local eligibility (reference IsRunOn, job.go:616-630) -----------

    def _load_groups(self):
        for kv in self.store.get_prefix(self.ks.group):
            self._apply_group(kv.value)

    def _apply_group(self, value: str):
        try:
            g = Group.from_json(value)
        except (json.JSONDecodeError, TypeError):
            return
        self.groups[g.id] = g

    def _poll_groups(self):
        for ev in self._w_groups.drain():
            if ev.type == DELETE:
                self.groups.pop(ev.kv.key[len(self.ks.group):], None)
            else:
                self._apply_group(ev.kv.value)

    def is_run_on(self, job: Job) -> bool:
        """Does any rule place this job on this node?  Include nodes ∪
        include groups − exclude nodes, subtractive exclude (the intended
        semantics; the reference's inner-loop continue is a no-op bug —
        SURVEY.md §7)."""
        for rule in job.rules:
            if self.id in rule.exclude_nids:
                continue
            if self.id in rule.nids:
                return True
            if any(self.id in g.node_ids
                   for gid in rule.gids
                   if (g := self.groups.get(gid)) is not None):
                return True
        return False

    # ---- job lookup ------------------------------------------------------

    def _get_job(self, group: str, job_id: str) -> Optional[Job]:
        cached = self._job_cache.get((group, job_id))
        if cached is not None:
            return cached
        kv = self.store.get(self.ks.job_key(group, job_id))
        if kv is None:
            return None
        try:
            job = Job.from_json(kv.value)
        except (json.JSONDecodeError, TypeError):
            return None
        job.group, job.id = group, job_id
        if len(self._job_cache) >= self._job_cache_cap:
            self._job_cache.clear()        # rare full reset beats LRU math
        self._job_cache[(group, job_id)] = job
        return job

    def _poll_jobs(self):
        """Job watch feeds cache invalidation (drained BEFORE the
        dispatch watch, so an order never runs against a staler view of
        its job than the store had when the order arrived)."""
        for ev in self._w_jobs.drain():
            rest = ev.kv.key[len(self.ks.cmd):]
            if "/" not in rest:
                continue
            key = tuple(rest.split("/", 1))
            if ev.type == DELETE:
                self._job_cache.pop(key, None)
            elif key in self._job_cache:
                try:
                    job = Job.from_json(ev.kv.value)
                    job.group, job.id = key
                    self._job_cache[key] = job
                except (json.JSONDecodeError, TypeError):
                    self._job_cache.pop(key, None)

    # ---- execution -------------------------------------------------------

    def _wait_until(self, epoch_s: int) -> bool:
        """Block until ``epoch_s`` arrives.  The scheduler publishes the
        whole planned window [t+1, t+W] ahead of wall-clock; a job must
        never run before its cron instant (the reference only ever fires
        late — cron.go:212-215).  Returns False if the agent is stopping."""
        while True:
            delay = epoch_s - self.clock()
            if delay <= 0:
                return True
            # bounded naps so injected (virtual) clocks still make progress
            if self._stop.wait(min(delay, 0.05)):
                return False

    def _acquire_alone_lock(self, job: Job):
        """Fleet-wide running lock for KindAlone: held under a lease with
        keepalive for the execution's lifetime, released on completion
        (reference job.go:87-123).  A still-running Alone job blocks the
        next fire everywhere.  Returns (lease, stop_event) or None if the
        lock is already live."""
        # TTL is a crash-safety net only (keepalive holds the lock while we
        # live); sized from the cost estimate like the reference's lockTtl
        # (job.go:194-233).
        ttl = max(5.0, min(self.lock_ttl, 2.0 * job.avg_time + 5.0))
        lease = self.store.grant(ttl)
        if not self.store.put_if_absent(
                self.ks.alone_lock_key(job.id), self.id, lease=lease):
            self.store.revoke(lease)
            return None
        stop = threading.Event()

        def ka_loop():
            # transient store errors (RPC timeout, reconnecting TCP) must
            # not kill the keepalive — the lock would expire mid-run and a
            # second Alone execution could overlap
            while not stop.wait(max(0.5, ttl / 3)):
                try:
                    if not self.store.keepalive(lease):
                        return   # lease definitively gone
                except Exception as e:  # noqa: BLE001
                    log.warnf("alone-lock keepalive for %s failed "
                              "(retrying): %s", job.id, e)
        threading.Thread(target=ka_loop, daemon=True,
                         name=f"alone-ka-{job.id}").start()
        return lease, stop

    def _execute(self, job: Job, epoch_s: int, fenced: bool,
                 use_gate: bool = True, order_key: Optional[str] = None,
                 pre: Optional[tuple] = None,
                 tr: Optional[tuple] = None):
        """Run one fire.  ``pre`` = (proc_registered, alone) marks an
        execution whose (job, second) fence — and KindAlone lifetime
        lock — were already settled by a bundle claim (_run_bundle): the
        fence/claim section is skipped, the rest (proc lifecycle,
        executor, record) is identical.  ``tr`` = (tb, recv, claim)
        carries the trace-plane stamps collected upstream (any may be
        None); this path adds its own claim stamp when it settles the
        fence itself."""
        if not self._wait_until(epoch_s):
            return
        # the user-visible SLA: scheduled second -> execution start.
        # Orders arrive AHEAD of time (the planner publishes whole
        # windows) and are held to their instant, so this lag is pure
        # plane latency: late watch delivery, claim round trip, local
        # queueing.  Reference per-fire latency is a goroutine spawn
        # (cron.go:237-244); this is the number that must stay bounded.
        lag = max(0.0, self.clock() - epoch_s)
        with self._stats_mu:
            self._lag_ring.append(lag)
            del self._lag_ring[:-512]
        alone = None
        order_done = [False]

        def consume_order():
            if order_key is not None and not order_done[0]:
                order_done[0] = True
                # buffered ack: a slow store must not stall this
                # executor thread on a per-fire delete RPC
                self._ack(order_key)
                self._bump("orders_consumed_total")

        try:
            proc_key = self.ks.proc_key(self.id, job.group, job.id,
                                        f"{epoch_s}-{os.getpid()}")
            proc_val = json.dumps({"time": self.clock()})
            proc_registered = False
            if pre is not None:
                # bundle claim already won the fence (and holds any
                # Alone lock); adopt its proc/alone state and skip
                # straight to the proc lifecycle + run
                proc_registered, alone = pre
            if pre is None and fenced and job.kind == KIND_ALONE:
                # lifetime lock FIRST: a skip because the previous run is
                # still live must not consume the (job, second) fence
                alone = self._acquire_alone_lock(job)
                if alone is None:
                    return  # previous Alone run still live fleet-wide
            if pre is None and fenced and job.exclusive:
                # one-RPC claim: fence + proc registration + order
                # consume collapse into a single store round trip (the
                # per-execution chain was the dispatch plane's measured
                # bottleneck).  The proc key rides the claim only when
                # the job is EXPECTED to outlive proc_req (cost
                # estimate); a mispredicted long run still registers via
                # the delay timer below, exactly the reference's ProcReq
                # threshold semantics (proc.go:218-236).
                with_proc = self.proc_req <= 0 or \
                    job.avg_time >= self.proc_req
                won = self._claim(job, epoch_s, order_key,
                                  proc_key if with_proc else "", proc_val)
                if order_key is not None:
                    order_done[0] = True    # claim consumed it, win or lose
                    self._bump("orders_consumed_total")
                if not won:
                    return  # another node already ran this (job, second)
                if self.trace_shift >= 0:
                    tr = ((tr[0], tr[1]) if tr else (None, None)) \
                        + (self.clock(),)
                if with_proc:
                    proc_registered = True
                    with self._procs_mu:
                        self._procs[proc_key] = proc_val
            finished = [False]
            pdelay_token = None

            def put_proc():
                """Register the running execution.  With proc_req > 0 this
                runs from a delay timer so sub-threshold jobs never touch
                the store (reference proc.go:218-236); the dispatch order
                key is consumed in the same breath — until then it is the
                scheduler's outstanding-capacity reservation."""
                with self._procs_mu:
                    if finished[0]:
                        return
                    self._procs[proc_key] = proc_val
                    try:
                        self.store.put(proc_key, proc_val,
                                       lease=self._proc_lease or 0)
                    except KeyError:
                        # proc lease expired under us — repair + re-attach
                        self._repair_proc_lease_locked()
                consume_order()

            if proc_registered:
                pass                    # claim already wrote the proc key
            elif self.proc_req > 0:
                pdelay_token = self._schedule_proc_put(put_proc)
            else:
                put_proc()
            try:
                res = self.executor.run_job(
                    job_id=job.id, command=job.command, user=job.user,
                    timeout=job.timeout, retry=job.retry,
                    interval=job.interval,
                    parallels=job.parallels if use_gate else 0,
                    # cron-context environment: jobs learn which second
                    # they were scheduled FOR (begin_ts in the log is
                    # when they actually ran — under load the two can
                    # differ, and scripts that write period-stamped
                    # artifacts need the scheduled one)
                    env={**self._base_env,
                         "CRONSUN_NODE": self.id,
                         "CRONSUN_JOB_ID": job.id,
                         "CRONSUN_JOB_GROUP": job.group,
                         "CRONSUN_JOB_NAME": job.name,
                         "CRONSUN_SCHEDULED_TS": str(epoch_s)})
            finally:
                if pdelay_token is not None:
                    self._cancel_proc_put(pdelay_token)
                with self._procs_mu:
                    finished[0] = True
                    if self._procs.pop(proc_key, None) is not None:
                        try:
                            self.store.delete(proc_key)
                        except Exception as e:  # noqa: BLE001
                            # registry cleanup is bookkeeping — the
                            # leased key ages out; a degraded store
                            # must not destroy a FINISHED execution's
                            # record (and span) below
                            log.warnf("proc delete for %s failed "
                                      "(lease will expire it): %s",
                                      proc_key, e)
        finally:
            if alone is not None:
                lease, stop = alone
                stop.set()
                try:
                    self.store.revoke(lease)  # deletes the alone lock
                except Exception as e:  # noqa: BLE001 — TTL cleans up
                    log.warnf("alone lock revoke failed (lease will "
                              "expire it): %s", e)
            consume_order()                # consume the order regardless
        self._record(job, res, epoch_s, tr=tr)
        self._update_avg_time(job, res)

    _FENCE_GRACE = 60.0

    def _fence_lease(self) -> int:
        """Shared periodically-rotated fence lease (see _fence)."""
        with self._fence_mu:
            now = self.clock()
            if self._fence_lease_id is None or now >= self._fence_rotate_at:
                self._fence_lease_id = self.store.grant(
                    self.lock_ttl + self._FENCE_GRACE)
                self._fence_rotate_at = now + self.lock_ttl / 2
            return self._fence_lease_id

    def _rotate_fence_lease(self) -> int:
        with self._fence_mu:
            self._fence_lease_id = self.store.grant(
                self.lock_ttl + self._FENCE_GRACE)
            self._fence_rotate_at = self.clock() + self.lock_ttl / 2
            return self._fence_lease_id

    def _claim(self, job: Job, epoch_s: int, order_key: Optional[str],
               proc_key: str, proc_val: str) -> bool:
        """Execution claim: (job, second) fence + optional proc
        registration + order-key consume, atomic server-side.  Claims
        from concurrent executions funnel through a batcher so a burst
        of due orders costs ONE claim_many round trip, not one RPC per
        execution.  Falls back to the legacy multi-RPC chain on stores
        that predate the ops."""
        fence_key = self.ks.lock_key(job.id, epoch_s)
        # Fence VALUE is a per-attempt nonce (node id + unique suffix),
        # not the bare node id: after an INDETERMINATE claim (reply lost
        # on reconnect, batcher timeout) the fallback must distinguish
        # "my claim actually applied" (fence holds MY nonce -> won) from
        # "someone else won" and from "a previous attempt of mine on
        # this (job, second) won" — a bare-node-id owner check would
        # misread all three and either skip a won execution fleet-wide
        # or double-run on a re-delivered order.
        nonce = f"{self.id}@{os.getpid()}-{next(self._claim_seq)}"
        if self._claim_supported:
            item = (fence_key, nonce, order_key or "", proc_key,
                    proc_val)
            ev = threading.Event()
            slot = [None]
            with self._claim_cv:
                self._claim_pending.append((item, ev, slot))
                if self._claim_thread is None or \
                        not self._claim_thread.is_alive():
                    self._claim_thread = threading.Thread(
                        target=self._claim_flush_loop, daemon=True,
                        name=f"claims-{self.id}")
                    self._claim_thread.start()
                self._claim_cv.notify()
            ev.wait(timeout=30)
            if slot[0] is not None:
                return slot[0]
            # indeterminate: the RPC may or may not have applied.  Read
            # the fence back before falling to the legacy chain —
            # waiting out the store client's auto-heal (~0.2 s backoff):
            # a bare get here races the reconnect and would misread
            # "asked 50 ms too early" as "fence absent".
            kv = None
            for _ in range(12):
                try:
                    kv = self.store.get(fence_key)
                    break
                except Exception:  # noqa: BLE001 — still healing
                    time.sleep(0.5)
            else:
                return False    # store unreachable: do NOT run unfenced
            if kv is not None:
                if kv.value == nonce:
                    return True        # our claim DID apply (incl. its
                                       # proc put + order consume)
                if order_key is not None:
                    try:               # lost to another attempt: the
                        self.store.delete(order_key)   # claim may not
                    except Exception:  # noqa: BLE001  # have consumed it
                        pass
                return False
            # fence absent: the claim never applied — legacy chain
        won = self._fence(job.id, epoch_s, value=nonce)
        if not won:
            # TOCTOU on the indeterminate path: an in-flight claim_many
            # can apply BETWEEN the fence read-back above (absent) and
            # this put_if_absent (exists) — the existing fence may be
            # OUR OWN nonce (unique per attempt), which is a win, not a
            # loss
            try:
                kv = self.store.get(fence_key)
                won = kv is not None and kv.value == nonce
            except Exception:  # noqa: BLE001 — stay with the loss
                pass
        if order_key is not None:
            self.store.delete(order_key)
        if won and proc_key:
            with self._procs_mu:
                try:
                    self.store.put(proc_key, proc_val,
                                   lease=self._proc_lease or 0)
                except KeyError:
                    self._repair_proc_lease_locked()
                    self.store.put(proc_key, proc_val,
                                   lease=self._proc_lease or 0)
        return won

    def _claim_flush_loop(self):
        """Group-commit loop: settle every pending claim in one
        claim_many RPC; claims arriving during the in-flight RPC form
        the next batch."""
        while True:
            with self._claim_cv:
                while not self._claim_pending:
                    if self._stop.is_set():
                        return
                    self._claim_cv.wait(timeout=0.5)
                batch, self._claim_pending = self._claim_pending, []
            results = None
            try:
                results = self._claim_batch_rpc([b[0] for b in batch])
            except Exception as e:  # noqa: BLE001
                if "unknown op" in str(e):
                    log.warnf("store lacks claim_many; using the legacy "
                              "fence chain")
                    self._claim_supported = False
                else:
                    log.errorf("claim batch of %d failed (callers retry "
                               "via the legacy chain): %s", len(batch), e)
            for i, (_item, ev, slot) in enumerate(batch):
                slot[0] = results[i] if results is not None else None
                ev.set()

    def _claim_batch_rpc(self, items):
        fence_lease = self._fence_lease()
        with self._procs_mu:
            proc_lease = self._proc_lease or 0
        try:
            return self.store.claim_many(items, fence_lease, proc_lease)
        except KeyError:
            # a lease expired under us (suspended VM, clock jump):
            # rotate/repair both, retry once
            fence_lease = self._rotate_fence_lease()
            with self._procs_mu:
                self._repair_proc_lease_locked()
                proc_lease = self._proc_lease or 0
            return self.store.claim_many(items, fence_lease, proc_lease)

    # ---- buffered order acks --------------------------------------------

    def _ack(self, key: str):
        """Queue a consumed order key for the periodic delete_many
        flush.  The order key is the scheduler's outstanding-capacity
        reservation — deleting it is bookkeeping the plane can do
        lazily; a run's exactly-once never depends on it."""
        with self._ack_mu:
            self._ack_buf.append(key)
            if self._ack_thread is None or not self._ack_thread.is_alive():
                self._ack_thread = threading.Thread(
                    target=self._ack_flush_loop, daemon=True,
                    name=f"ackflush-{self.id}")
                self._ack_thread.start()

    def _ack_flush_loop(self):
        while not self._stop.wait(self.ack_flush_interval):
            self._flush_acks()

    def _flush_acks(self):
        with self._ack_flush_mu:
            self._flush_acks_locked()

    def _flush_acks_locked(self):
        with self._ack_mu:
            batch, self._ack_buf = self._ack_buf, []
        if not batch:
            return
        try:
            if hasattr(self.store, "delete_many"):
                self.store.delete_many(batch)
            else:                       # minimal store: per-key deletes,
                for k in batch:         # still off the exec path
                    self.store.delete(k)
        except Exception as e:  # noqa: BLE001
            # order keys are leased: on a store hiccup they age out
            # server-side, so a failed ack batch is dropped, not
            # retried into a backlog that outlives its usefulness
            log.warnf("order-ack flush of %d failed (keys age out): %s",
                      len(batch), e)
            return
        with self._stats_mu:
            self.stats["ack_flush_total"] += 1
            self.stats["ack_flush_orders_total"] += len(batch)

    def _fence(self, job_id: str, epoch_s: int,
               value: Optional[str] = None) -> bool:
        """(job, second) create-if-absent fence.  Fence keys ride a
        SHARED periodically re-granted lease — the reference pools its
        proc keys on one shared lease the same way (proc.go:60-123) —
        instead of one grant+revoke round trip pair per execution.  A
        batch's keys live between lock_ttl/2 + grace and lock_ttl +
        grace, comfortably beyond the scheduler's max re-dispatch
        horizon (max_catchup_s)."""
        lease = self._fence_lease()
        key = self.ks.lock_key(job_id, epoch_s)
        val = value if value is not None else self.id
        try:
            return self.store.put_if_absent(key, val, lease=lease)
        except KeyError:
            # lease expired under us (suspended VM, clock jump): rotate
            lease = self._rotate_fence_lease()
            return self.store.put_if_absent(key, val, lease=lease)

    def _update_avg_time(self, job: Job, res: ExecResult):
        """Close the cost loop: fold the measured runtime into the job's
        EWMA and persist it CAS-style (reference job.go:581-589,
        job_log.go:85-86).  The resulting watch event flows the new cost
        into the planner's waterfill."""
        if res.skipped:
            return
        dur = max(0.0, res.end_ts - res.begin_ts)
        # skip uninformative updates: a runtime within 10% of the current
        # EWMA would move the planner's cost estimate by nothing worth a
        # get+CAS round trip pair per execution.  Applies at avg_time==0
        # too — an instant job (dur < 0.1 s) must NOT pay a CAS per fire
        # forever (each CAS also churns the job watch fleet-wide: every
        # agent invalidates its cache and the scheduler re-applies the
        # job), and the planner floors its cost at 1.0 regardless.
        if abs(dur - job.avg_time) <= 0.1 * max(1.0, job.avg_time):
            return
        key = self.ks.job_key(job.group, job.id)
        for _ in range(3):
            kv = self.store.get(key)
            if kv is None:
                return
            try:
                cur = Job.from_json(kv.value)
            except (json.JSONDecodeError, TypeError):
                return
            cur.group, cur.id = job.group, job.id
            cur.update_avg_time(dur)
            if self.store.put_if_mod_rev(key, cur.to_json(), kv.mod_rev):
                return

    def _record(self, job: Job, res: ExecResult, epoch_s: int = 0,
                tr: Optional[tuple] = None):
        if res.skipped:
            return
        self._bump("execs_total")
        if not res.success:
            self._bump("execs_failed_total")
        self._slo_observe(job, res)
        if self.dep_events and epoch_s:
            # the workflow DAG edge signal: last-write-wins per job, the
            # value carries the SCHEDULED round so N Common nodes
            # completing one round write one idempotent value (the
            # scheduler's fold is a monotone max on it).  Best-effort —
            # a store outage here must not fail the execution path; the
            # round re-announces on the job's next completion.
            try:
                self.store.put(
                    self.ks.dep_key(job.group, job.id),
                    f"{int(epoch_s)}|{'ok' if res.success else 'fail'}")
                self._bump("dep_events_total")
            except Exception as e:  # noqa: BLE001 — degraded, not down
                self._bump("dep_event_failures_total")
                log.warnf("dep completion event for %s/%s failed: %s",
                          job.group, job.id, e)
        rec = LogRecord(
            job_id=job.id, job_group=job.group, name=job.name, node=self.id,
            user=job.user, command=job.command,
            output=res.output if res.success
            else f"{res.output}\n[error] {res.error}".strip(),
            success=res.success, begin_ts=res.begin_ts, end_ts=res.end_ts)
        span = self._trace_span(job, res, epoch_s, tr)
        # batch the result-store write: records buffer here and a
        # flusher writes whole batches per interval (create_job_logs —
        # one round trip and one sink transaction per batch, not per
        # execution)
        with self._rec_mu:
            self._rec_buf.append(rec)
            if span is not None:
                self._span_buf.append(span)
                if len(self._span_buf) > self._span_buf_max:
                    drop = len(self._span_buf) - self._span_buf_max
                    del self._span_buf[:drop]
                    self._bump("trace_spans_dropped_total", drop)
            # trim in 4096-record chunks: a per-append del of the list
            # head is an O(buffer) memmove inside _rec_mu on every
            # record once the cap pins — chunking amortizes it away
            if len(self._rec_buf) > self.rec_buf_max + 4096:
                drop = len(self._rec_buf) - self.rec_buf_max
                del self._rec_buf[:drop]
                # rate-limited: at dispatch-plane rates a per-record
                # error line (~8k/s measured) would make the log pipe
                # the next bottleneck of the outage
                self._rec_dropped += drop
                self._bump("rec_dropped_total", drop)
                now = self.clock()
                if now >= self._rec_drop_log_at:
                    self._rec_drop_log_at = now + 5.0
                    log.errorf("record buffer over %d during sink "
                               "outage; %d dropped so far",
                               self.rec_buf_max, self._rec_dropped)
            if self._rec_flusher is None or not self._rec_flusher.is_alive():
                self._rec_flusher = threading.Thread(
                    target=self._rec_flush_loop, daemon=True,
                    name=f"recflush-{self.id}")
                self._rec_flusher.start()
        if not res.success and job.fail_notify:
            msg = {"subject": f"[cronsun] job [{job.name}] fail",
                   "body": f"job: {job.group}/{job.id}\nnode: {self.id}\n"
                           f"output: {res.output}\nerror: {res.error}",
                   "to": job.to}
            self.store.put(self.ks.noticer_key(self.id),
                           json.dumps(msg, separators=(",", ":")))

    def _trace_span(self, job: Job, res: ExecResult, epoch_s: int,
                    tr: Optional[tuple]) -> Optional[dict]:
        """Build this execution's trace span, or None when the fire is
        not sampled.  Head-sampling re-derives the scheduler's verdict
        from the same deterministic hash; failed executions and
        ``trace: true`` jobs sample regardless (tail capture — their
        scheduler stages may be absent when the head said no)."""
        if self.trace_shift < 0 or not epoch_s:
            return None
        tid = _trace.trace_id(job.id, epoch_s)
        if not (getattr(job, "trace", False)
                or not res.success
                or _trace.head_sampled(tid, self.trace_shift)):
            return None
        ts = {"start": res.begin_ts, "end": res.end_ts}
        if tr is not None:
            for name, v in zip(("b", "recv", "claim"), tr):
                if v is not None:
                    ts[name] = v
        span = {"tid": str(tid), "job": job.id, "grp": job.group,
                "sec": int(epoch_s), "node": self.id,
                "ok": bool(res.success), "ts": ts}
        if job.tenant:
            span["ten"] = job.tenant
        self._bump("trace_spans_total")
        return span

    def _slo_observe(self, job: Job, res: ExecResult):
        """Per-scope SLO counters over EVERY execution: latency
        histogram (fixed fleet-wide buckets) + failure count + failure
        latency histogram, keyed "" / "t:<tenant>" / "c:<group>/<job>"
        (chain scope only for DAG members — bounded cardinality).  The
        failure buckets let the burn-rate engine count slow SUCCESSES
        exactly (bad = failed OR slow; without them a fast failure and
        a slow success are indistinguishable in the joint)."""
        import bisect
        lat_ms = max(0.0, (res.end_ts - res.begin_ts)) * 1e3
        bi = bisect.bisect_left(_trace.BUCKETS_MS, lat_ms)
        scopes = [""]
        if job.tenant:
            scopes.append("t:" + job.tenant)
        if job.deps is not None:
            scopes.append(f"c:{job.group}/{job.id}")
        with self._stats_mu:
            for s in scopes:
                ent = self._slo.get(s)
                if ent is None:
                    if len(self._slo) >= self._slo_cap:
                        continue       # bounded; global "" always fits
                    ent = self._slo[s] = [
                        0, 0, 0.0, [0] * (len(_trace.BUCKETS_MS) + 1),
                        [0] * (len(_trace.BUCKETS_MS) + 1)]
                ent[0] += 1
                if not res.success:
                    ent[1] += 1
                    ent[4][bi] += 1
                ent[2] += lat_ms
                ent[3][bi] += 1

    def _schedule_proc_put(self, fn) -> int:
        """Register a ProcReq-delayed proc put on the shared monitor
        thread; returns a token for :meth:`_cancel_proc_put`.  The fn
        itself is idempotent-safe (it checks the execution's finished
        flag under the procs lock), so the cancel race is harmless."""
        with self._pdelay_mu:
            self._pdelay_seq += 1
            token = self._pdelay_seq
            self._pdelay[token] = (self.clock() + self.proc_req, fn)
            if self._pdelay_thread is None or \
                    not self._pdelay_thread.is_alive():
                self._pdelay_thread = threading.Thread(
                    target=self._pdelay_loop, daemon=True,
                    name=f"procdelay-{self.id}")
                self._pdelay_thread.start()
        return token

    def _cancel_proc_put(self, token: int):
        with self._pdelay_mu:
            self._pdelay.pop(token, None)

    def _pdelay_loop(self):
        while True:
            with self._pdelay_mu:
                if self._stop.is_set() or not self._pdelay:
                    # clear the handle under the lock before exiting so a
                    # concurrent _schedule_proc_put spawns a fresh one
                    self._pdelay_thread = None
                    return
                now = self.clock()
                fns = [self._pdelay.pop(t)[1]
                       for t in [t for t, (ts, _f) in self._pdelay.items()
                                 if ts <= now]]
            for f in fns:
                try:
                    f()
                except Exception as e:  # noqa: BLE001
                    log.warnf("delayed proc put failed: %s", e)
            time.sleep(0.1)

    def _rec_flush_loop(self):
        """Drain the record buffer every ``rec_flush_interval``; exits
        once the agent is stopping and the buffer is empty (stop() does
        a final synchronous flush)."""
        while True:
            if self._stop.wait(self.rec_flush_interval):
                return
            self._flush_records()

    def _sink_takes_spans(self) -> bool:
        """Does the sink's bulk create accept the trace-span sidecar?
        Resolved once from the signature (the _sink_idem_ok contract:
        never from a caught TypeError)."""
        if self._sink_spans_ok is None:
            try:
                import inspect
                fn = getattr(self.sink, "create_job_logs", None)
                if fn is None:
                    self._sink_spans_ok = False
                else:
                    params = inspect.signature(fn).parameters
                    self._sink_spans_ok = "spans" in params or any(
                        p.kind == p.VAR_KEYWORD for p in params.values())
            except (TypeError, ValueError):
                self._sink_spans_ok = False
        return self._sink_spans_ok

    def _send_records(self, batch: list, idem: str,
                      toks: Optional[list] = None,
                      spans: Optional[list] = None) -> bool:
        """One write attempt.  On a mid-batch failure of the per-record
        path the already-written head is removed from ``batch`` (and
        ``toks``) in place, so a caller that re-buffers retries only
        the unwritten tail (re-sending the head would duplicate
        job-log rows).  ``toks`` are the per-record idempotency tokens
        minted when the batch first formed: they stay pinned across
        EVERY retry of the same logical records, so a record whose
        first per-record attempt committed with the reply lost dedups
        server-side on the re-send instead of double-inserting (the
        token contract of logsink/serve.py) — the same guarantee the
        bulk path gets from the batch-level ``idem``."""
        written = 0
        if spans:
            # record-flush stamp: when this attempt ships the batch —
            # re-stamped per retry so the stage measures the time the
            # records actually became visible, outages included
            fts = self.clock()
            for sp in spans:
                sp["ts"]["flush"] = fts
        try:
            if hasattr(self.sink, "create_job_logs"):
                if spans and self._sink_takes_spans():
                    self.sink.create_job_logs(batch, idem=idem,
                                              spans=spans)
                else:
                    self.sink.create_job_logs(batch, idem=idem)
            else:                   # minimal sink: per-record
                use_idem = toks is not None and self._sink_idem_ok()
                for k, r in enumerate(batch):
                    if use_idem:
                        self.sink.create_job_log(r, idem=toks[k])
                    else:
                        self.sink.create_job_log(r)
                    written += 1
            return True
        except Exception as e:  # noqa: BLE001 — sink client already
            del batch[:written]  # retried once; caller decides the rest
            if toks is not None:
                del toks[:written]
            log.warnf("record write failed (%d records unwritten): %s",
                      len(batch), e)
            return False

    def _sink_idem_ok(self) -> bool:
        """Does the sink's per-record create accept an ``idem`` kwarg?
        Resolved once from the signature, never from a caught
        TypeError (which could equally come from inside the sink)."""
        if self._sink_takes_idem is None:
            try:
                import inspect
                params = inspect.signature(
                    self.sink.create_job_log).parameters
                self._sink_takes_idem = "idem" in params or any(
                    p.kind == p.VAR_KEYWORD for p in params.values())
            except (TypeError, ValueError):  # builtins, odd callables
                self._sink_takes_idem = False
        return self._sink_takes_idem

    def _flush_records(self, final: bool = False, force: bool = False):
        # pop AND write under one flush mutex: join_running()/stop() use
        # this as a completion barrier, so a batch the background
        # flusher popped must not still be in flight when a barrier
        # flush returns empty-handed
        with self._rec_flush_mu:
            # Batching widened the blast radius of a sink hiccup from one
            # record to a whole flush interval, so a failed batch parks in
            # a retry slot — SEPARATE from the live buffer, with its
            # idempotency token pinned, so (a) an applied-but-reply-lost
            # bulk write dedups server-side on the retry instead of
            # double-inserting, and (b) records appended since never ride
            # a token the server may already have settled.  Only after
            # ``rec_flush_max_fails`` consecutive failures (or at
            # shutdown, when no retry can happen) is the batch dropped,
            # the way the reference tolerates a Mongo outage
            # (job_log.go:84).
            if self._rec_retry is not None:
                # ``force`` (join_running's visibility barrier) attempts
                # NOW even inside the backoff window — the sink may have
                # healed, and the barrier contract says records must be
                # visible on return whenever writing is possible at all
                early = self.clock() < self._rec_retry_at
                if not (final or force) and early:
                    return   # between backoff attempts; fresh waits too
                batch, idem, toks, spans = self._rec_retry
                if self._send_records(batch, idem, toks, spans):
                    self._record_flushed(len(batch))
                    self._rec_retry = None
                    self._rec_flush_fails = 0
                elif force and not final and early:
                    # a forced barrier attempt INSIDE the backoff window
                    # is extra-schedule: it must not burn the retry
                    # budget (a caller polling join_running during a
                    # sink outage would otherwise exhaust
                    # rec_flush_max_fails in seconds and drop the batch
                    # far earlier than the backoff intends)
                    return
                else:
                    self._rec_flush_fails += 1
                    if final or \
                            self._rec_flush_fails >= self.rec_flush_max_fails:
                        log.errorf(
                            "record flush failed (%d records dropped "
                            "after %d attempts)", len(batch),
                            self._rec_flush_fails)
                        self._bump("rec_dropped_total", len(batch))
                        self._rec_retry = None
                        self._rec_flush_fails = 0
                    else:
                        self._rec_retry_at = self.clock() + \
                            REC_FLUSH.delay(self._rec_flush_fails)
                        log.warnf("record flush failed (%d records held "
                                  "for retry %d/%d)", len(batch),
                                  self._rec_flush_fails,
                                  self.rec_flush_max_fails)
                        return   # sink still down; fresh records wait
            with self._rec_mu:
                batch, self._rec_buf = self._rec_buf, []
                spans, self._span_buf = self._span_buf, []
            if not batch and not spans:
                return
            # batch token + per-record tokens minted ONCE per logical
            # batch: both stay pinned in the retry slot so every
            # re-send (bulk or per-record degraded path) dedups
            # server-side.  Spans ride the same batch (and retry slot);
            # their ingest is last-write-wins per (trace, node), so a
            # replayed batch re-merges identical values.
            idem = uuid.uuid4().hex
            toks = [f"{idem}.{i}" for i in range(len(batch))]
            sent = len(batch)
            if self._send_records(batch, idem, toks, spans):
                self._record_flushed(sent)
            elif final:
                log.errorf("record flush failed (%d records dropped "
                           "at shutdown)", len(batch))
                self._bump("rec_dropped_total", len(batch))
            elif batch or spans:
                self._rec_retry = (batch, idem, toks, spans)
                self._rec_retry_at = self.clock() + REC_FLUSH.delay(1)

    # ---- event processing (synchronous; threads call these) --------------

    def poll(self, wait: float = 0.0) -> int:
        """Drain watchers, spawn executions.  Returns orders handled."""
        n = 0
        deadline = self.clock() + wait
        while True:
            try:
                self._poll_groups()
                self._poll_jobs()
                n += self._poll_dispatch()
                n += self._poll_broadcast()
                n += self._poll_once()
            except WatchLost as e:
                log.warnf("agent watch lost (%s); resynchronizing", e)
                self._bump("watch_losses_total")
                n += self.resync_watches()
            if self.clock() >= deadline:
                break
            time.sleep(0.01)
        return n

    def resync_watches(self) -> int:
        """Rebuild all watch streams after a loss and reconcile from the
        store's current contents: groups reload; still-live dispatch
        orders and broadcasts re-run (exclusive runs are fenced by the
        (job, second) store lock; Common runs by the in-memory _bseen
        dedup — either way the retry is exactly-once).  Pending
        once-triggers are NOT re-run: we cannot know whether the previous
        stream delivered them and run-now has no fence; at-most-once is
        the safe reading."""
        for w in (self._w_dispatch, self._w_broadcast, self._w_groups,
                  self._w_once, self._w_jobs):
            try:
                w.close()
            except Exception:   # noqa: BLE001 — already-dead watchers
                pass
        self._open_watches()
        self.groups.clear()
        self._load_groups()
        self._job_cache.clear()    # invalidations inside the gap are lost
        n = 0
        for kv in self.store.get_prefix(self.ks.dispatch + self.id + "/"):
            n += self._handle_dispatch_kv(kv.key, kv.value,
                                          order_key=kv.key)
        for kv in self.store.get_prefix(self.ks.dispatch_all):
            n += self._handle_broadcast_kv(kv.key)
        return n

    def _handle_dispatch_kv(self, key: str, value: str,
                            order_key: Optional[str] = None) -> int:
        rest = key[len(self.ks.dispatch) + len(self.id) + 1:]
        parts = rest.split("/")
        if len(parts) == 1:
            # coalesced (node, second) bundle: value = the job list.
            # "<epoch>" plain, or the partitioned scheduler's
            # "<epoch>.<partition>" form (the suffix scopes the
            # reservation to its publishing partition; the epoch is
            # what matters here).  A re-delivery (hole-rewind
            # overwrite, resync re-list) is absorbed by the
            # per-(job, second) fences at claim time.
            parsed = Keyspace.split_bundle_epoch(parts[0])
            if parsed is not None:
                return self._handle_bundle(key, parsed[0], value)
            return 0
        if len(parts) != 3:
            return 0
        # legacy per-(node, second, job) order — rollout tolerance for
        # windows published by a pre-coalescing scheduler
        epoch_s, group, job_id = int(parts[0]), parts[1], parts[2]
        job = self._get_job(group, job_id)
        if job is None or job.pause:
            self._ack(key)
            return 0
        # the order key stays in the store until the execution's proc
        # key exists — the scheduler counts it as an outstanding
        # capacity reservation in the meantime
        tr = (None, self.clock(), None) if self.trace_shift >= 0 else None
        self._spawn(job, epoch_s, fenced=True, order_key=order_key,
                    tr=tr)
        return 1

    def _handle_bundle(self, key: str, epoch_s: int, value: str) -> int:
        """Stage one coalesced (node, second) order for its instant.
        The bundle rides ONE staged task; at due time it settles every
        member's fence in one claim_bundle RPC and fans the winners out
        to the exec pool (_run_bundle)."""
        try:
            entries = json.loads(value)
        except (json.JSONDecodeError, TypeError):
            entries = None
        pairs = []
        tb = None
        if isinstance(entries, list):
            for e in entries:
                if isinstance(e, str) and "/" in e:
                    group, _, job_id = e.partition("/")
                    pairs.append((group, job_id))
                elif isinstance(e, dict):
                    # trace header the scheduler appends to a bundle
                    # with >= 1 sampled member (order-build wall time);
                    # spanless legacy bundles simply lack it
                    t = e.get("tb")
                    if isinstance(t, (int, float)):
                        tb = float(t)
        if not pairs:
            self._ack(key)           # malformed/empty: release the
            return 0                 # capacity reservation
        recv = self.clock() if self.trace_shift >= 0 else None
        NodeAgent._spawn_seq += 1
        name = f"bundle-{epoch_s}-{NodeAgent._spawn_seq}"

        def run():
            try:
                self._run_bundle(key, epoch_s, pairs, tb=tb, recv=recv)
            except Exception as e:  # noqa: BLE001 — log, don't die silent
                log.errorf("bundle %s failed: %s", name, e)
            finally:
                self.running.pop(name, None)

        task = _ExecTask(run)
        self.running[name] = task
        self._stage_task(name, task, epoch_s)
        return len(pairs)

    def _run_bundle(self, order_key: str, epoch_s: int, pairs: list,
                    tb: Optional[float] = None,
                    recv: Optional[float] = None):
        """Consume one coalesced order: resolve the bundle's jobs (one
        get_many), settle KindAlone lifetime locks per job (lock FIRST —
        a skip because the previous run is still live must not consume
        the (job, second) fence), then claim every member's fence + the
        winners' proc keys + the bundle key's capacity reservation in
        ONE claim_bundle RPC, and hand the winners to the exec pool.
        Per-job exactly-once is unchanged: it still rests on the
        (job, second) create-if-absent fence, so a duplicate bundle
        delivery (hole-rewind overwrite, resync re-list, leader
        failover) re-claims and loses."""
        if not self._wait_until(epoch_s):
            return
        self._prefetch_pairs(pairs)
        runnable = []   # [job, alone, with_proc, proc_key, proc_val]
        items = []      # parallel (fence_key, nonce, proc_key, proc_val)
        try:
            for group, job_id in pairs:
                job = self._get_job(group, job_id)
                if job is None or job.pause:
                    continue
                alone = None
                if job.kind == KIND_ALONE:
                    alone = self._acquire_alone_lock(job)
                    if alone is None:
                        continue    # previous Alone run still live
                nonce = f"{self.id}@{os.getpid()}-{next(self._claim_seq)}"
                with_proc = self.proc_req <= 0 or \
                    job.avg_time >= self.proc_req
                proc_key = self.ks.proc_key(self.id, job.group, job.id,
                                            f"{epoch_s}-{os.getpid()}")
                proc_val = json.dumps({"time": self.clock()})
                items.append((self.ks.lock_key(job.id, epoch_s), nonce,
                              proc_key if with_proc else "", proc_val))
                runnable.append([job, alone, with_proc, proc_key,
                                 proc_val])
            if not items:
                # nothing claimable (paused/missing/Alone-skipped):
                # release the capacity reservation via the ack flusher
                self._ack(order_key)
                return
            wins = self._claim_bundle(order_key, items)
            if wins is None:
                # store unreachable: do NOT run unfenced.  Stop the
                # Alone keepalives so the locks expire server-side; the
                # leased bundle key ages out and a resync re-delivers.
                for ent in runnable:
                    if ent[1] is not None:
                        ent[1][1].set()
                        ent[1] = None
                return
            self._bump("orders_consumed_total", len(items))
            # fence settled for the whole bundle: the claim-lag stamp
            # every member's span shares
            claim_ts = self.clock() if self.trace_shift >= 0 else None
            for won, ent in zip(wins, runnable):
                job, alone, with_proc, proc_key, proc_val = ent
                if not won:
                    # another node (or an earlier duplicate) ran this
                    # (job, second)
                    if alone is not None:
                        lease, stop = alone
                        stop.set()
                        ent[1] = None
                        self.store.revoke(lease)
                    continue
                if with_proc:
                    with self._procs_mu:
                        self._procs[proc_key] = proc_val
                ent[1] = None   # the execution owns the lock from here
                self._spawn(job, epoch_s, fenced=True,
                            pre=(with_proc, alone),
                            tr=(tb, recv, claim_ts))
        except BaseException:
            # an escaping error (a transport hiccup mid-acquire, a
            # degraded-path claim failure) must not leak a live Alone
            # keepalive — the lock would outlive this bundle and block
            # the job fleet-wide until the agent restarts.  Release
            # every lock not yet handed to an execution; revoke may
            # fail (store down) but the stopped keepalive lets the
            # lease expire.
            for ent in runnable:
                if ent[1] is not None:
                    lease, stop = ent[1]
                    stop.set()
                    try:
                        self.store.revoke(lease)
                    except Exception:  # noqa: BLE001 — TTL cleans up
                        pass
            raise

    def _claim_bundle(self, order_key: str, items: list):
        """One-RPC bundle consume with the degraded-store ladder:

        - ``claim_bundle`` op (normal path; expired shared leases are
          rotated/repaired and retried once), group-committed: several
          bundles due at once — a catch-up backlog — ride ONE
          ``claim_bundle_many`` round trip (``_claim_bundle_rpc``);
        - unknown op (a store predating the format): per-item legacy
          fences, then the reservation delete — N+1 RPCs, correct;
        - transport error (INDETERMINATE — the claim may have applied
          with the reply lost): read the fences back by nonce exactly
          like _claim's recovery — our nonce means the claim DID apply
          (incl. its proc puts and the order delete); another value is
          a loss; absent falls to a legacy fence with the SAME nonce.

        Returns per-item wins, or None when the store is unreachable
        (callers must not run unfenced)."""
        try:
            return self._claim_bundle_rpc(order_key, items)
        except Exception as e:  # noqa: BLE001 — degrade, never unfenced
            unsupported = isinstance(e, AttributeError) or \
                "unknown op" in str(e)
            if unsupported:
                log.warnf("store lacks claim_bundle; using per-item "
                          "fences")
                wins = [self._fence_item(it) for it in items]
                try:
                    self.store.delete(order_key)
                except Exception:  # noqa: BLE001 — leased key ages out
                    pass
                return wins
        # indeterminate: read back, waiting out the client's auto-heal
        kvs = None
        for _ in range(12):
            try:
                if hasattr(self.store, "get_many"):
                    kvs = self.store.get_many([it[0] for it in items])
                else:
                    kvs = [self.store.get(it[0]) for it in items]
                break
            except Exception:  # noqa: BLE001 — still healing
                time.sleep(0.5)
        if kvs is None:
            return None     # store unreachable
        wins = []
        for it, kv in zip(items, kvs):
            if kv is not None:
                wins.append(kv.value == it[1])
            elif self._fence_item(it):
                wins.append(True)
            else:
                # the in-flight claim can still apply between the
                # read-back and the fence put: a loss to OUR OWN nonce
                # is the claim's win
                try:
                    kv2 = self.store.get(it[0])
                    wins.append(kv2 is not None and kv2.value == it[1])
                except Exception:  # noqa: BLE001 — stay with the loss
                    wins.append(False)
        try:
            self.store.delete(order_key)
        except Exception:  # noqa: BLE001 — leased key ages out
            pass
        return wins

    def _claim_bundle_rpc(self, order_key: str, items: list):
        """One LOGICAL claim_bundle round trip.  Concurrent callers
        (pool workers draining a backlog of due bundles) group-commit:
        whatever piles up during the in-flight RPC settles in one
        ``claim_bundle_many`` call.  A lone bundle uses the plain
        ``claim_bundle`` op — equally one RPC, and single-bundle error
        behavior (the degraded ladder's contract) stays byte-identical.
        Wire errors propagate to the caller's ladder."""
        if not (self._bundle_many_supported
                and hasattr(self.store, "claim_bundle_many")):
            return self._claim_bundle_direct(order_key, items)
        done = threading.Event()
        slot = [None, None]             # [wins, exception]
        with self._bundle_cv:
            self._bundle_pending.append((order_key, items, done, slot))
            if self._bundle_thread is None or \
                    not self._bundle_thread.is_alive():
                self._bundle_thread = threading.Thread(
                    target=self._bundle_flush_loop, daemon=True,
                    name=f"bundles-{self.id}")
                self._bundle_thread.start()
            self._bundle_cv.notify()
        if not done.wait(timeout=30):
            # indeterminate: the caller's read-back recovery decides
            raise RuntimeError("bundle claim batch timed out")
        if slot[1] is not None:
            raise slot[1]
        return slot[0]

    def _claim_bundle_direct(self, order_key: str, items: list):
        fence_lease = self._fence_lease()
        with self._procs_mu:
            proc_lease = self._proc_lease or 0
        try:
            return self.store.claim_bundle(order_key, items,
                                           fence_lease, proc_lease)
        except KeyError:
            fence_lease = self._rotate_fence_lease()
            with self._procs_mu:
                self._repair_proc_lease_locked()
                proc_lease = self._proc_lease or 0
            return self.store.claim_bundle(order_key, items,
                                           fence_lease, proc_lease)

    def _bundle_flush_loop(self):
        """Group-commit loop for bundle claims: every pending bundle
        settles in one claim_bundle_many RPC; bundles arriving during
        the in-flight RPC form the next batch."""
        while True:
            with self._bundle_cv:
                while not self._bundle_pending:
                    if self._stop.is_set():
                        return
                    self._bundle_cv.wait(timeout=0.5)
                batch, self._bundle_pending = self._bundle_pending, []
            if len(batch) == 1:
                order_key, items, done, slot = batch[0]
                try:
                    slot[0] = self._claim_bundle_direct(order_key, items)
                except Exception as e:  # noqa: BLE001 — caller's ladder
                    slot[1] = e
                done.set()
                continue
            try:
                results = self._bundle_many_rpc(
                    [(ok, its) for ok, its, _d, _s in batch])
                for res, (_ok, _its, done, slot) in zip(results, batch):
                    slot[0] = res
                    done.set()
            except Exception as e:  # noqa: BLE001
                if "unknown op" in str(e):
                    # server predates claim_bundle_many: settle this
                    # batch one RPC each and stop batching
                    log.warnf("store lacks claim_bundle_many; settling "
                              "bundles one RPC each")
                    self._bundle_many_supported = False
                    for order_key, its, done, slot in batch:
                        try:
                            slot[0] = self._claim_bundle_direct(order_key,
                                                                its)
                        except Exception as e2:  # noqa: BLE001
                            slot[1] = e2
                        done.set()
                else:
                    for _ok, _its, done, slot in batch:
                        slot[1] = e     # each caller's ladder recovers
                        done.set()

    def _bundle_many_rpc(self, bundles: list):
        fence_lease = self._fence_lease()
        with self._procs_mu:
            proc_lease = self._proc_lease or 0
        try:
            return self.store.claim_bundle_many(bundles, fence_lease,
                                                proc_lease)
        except KeyError:
            # a shared lease expired under us (suspended VM, clock
            # jump): rotate/repair both, retry once
            fence_lease = self._rotate_fence_lease()
            with self._procs_mu:
                self._repair_proc_lease_locked()
                proc_lease = self._proc_lease or 0
            return self.store.claim_bundle_many(bundles, fence_lease,
                                                proc_lease)

    def _fence_item(self, item) -> bool:
        """Legacy per-item settle for a bundle member: fence
        put_if_absent under the shared rotating lease, plus the winner's
        proc put — the degraded path when claim_bundle is unavailable."""
        fence_key, nonce, proc_key, proc_val = item
        try:
            won = self.store.put_if_absent(fence_key, nonce,
                                           lease=self._fence_lease())
        except KeyError:
            won = self.store.put_if_absent(fence_key, nonce,
                                           lease=self._rotate_fence_lease())
        if won and proc_key:
            with self._procs_mu:
                try:
                    self.store.put(proc_key, proc_val,
                                   lease=self._proc_lease or 0)
                except KeyError:
                    self._repair_proc_lease_locked()
                    self.store.put(proc_key, proc_val,
                                   lease=self._proc_lease or 0)
        return won

    def _prefetch_jobs(self, keys):
        """Batch-fill the job cache for a drained burst of order keys:
        cold jobs cost ONE get_many round trip per drain, not one
        synchronous get (plus a reply-wait thread handoff) per order —
        a measured top cost of the dispatch plane."""
        pairs = []
        for rest in keys:
            parts = rest.split("/")
            if len(parts) == 3:
                pairs.append((parts[1], parts[2]))
        self._prefetch_pairs(pairs)

    def _prefetch_pairs(self, pairs):
        """Batch-fill the job cache for explicit (group, job_id) pairs —
        the bundle consumer's one-get_many-per-bundle fill."""
        want = []
        seen = set()
        for gk in pairs:
            if gk not in seen and gk not in self._job_cache:
                seen.add(gk)
                want.append(gk)
        if not want or not hasattr(self.store, "get_many"):
            return
        try:
            kvs = self.store.get_many(
                [self.ks.job_key(g, j) for g, j in want])
        except Exception as e:  # noqa: BLE001 — per-order gets still work
            log.warnf("job prefetch failed (%s); falling back to "
                      "per-order fetches", e)
            return
        if len(self._job_cache) + len(want) > self._job_cache_cap:
            self._job_cache.clear()
        for (group, job_id), kv in zip(want, kvs):
            if kv is None:
                continue
            try:
                job = Job.from_json(kv.value)
            except (json.JSONDecodeError, TypeError):
                continue
            job.group, job.id = group, job_id
            self._job_cache[(group, job_id)] = job

    def _poll_dispatch(self) -> int:
        n = 0
        evs = [ev for ev in self._w_dispatch.drain() if ev.type != DELETE]
        if len(evs) > 1:
            off = len(self.ks.dispatch) + len(self.id) + 1
            self._prefetch_jobs(ev.kv.key[off:] for ev in evs)
        for ev in evs:
            n += self._handle_dispatch_kv(ev.kv.key, ev.kv.value,
                                          order_key=ev.kv.key)
        return n

    def _handle_broadcast_kv(self, key: str) -> int:
        rest = key[len(self.ks.dispatch_all):]
        parts = rest.split("/")
        if len(parts) != 3:
            return 0
        epoch_s, group, job_id = int(parts[0]), parts[1], parts[2]
        # Common runs have no store fence; this in-memory (job, second)
        # dedup keeps the resync re-list (and any stream re-delivery)
        # from double-running a broadcast this agent already took
        if (job_id, epoch_s) in self._bseen:
            return 0
        job = self._get_job(group, job_id)
        if job is None or job.pause or not self.is_run_on(job):
            return 0
        self._bseen[(job_id, epoch_s)] = self.clock()
        if len(self._bseen) > 8192:     # prune half-hour-old entries
            cut = self.clock() - 1800
            for k2 in [k2 for k2, ts in self._bseen.items() if ts < cut]:
                del self._bseen[k2]
        tr = (None, self.clock(), None) if self.trace_shift >= 0 else None
        self._spawn(job, epoch_s, fenced=True, tr=tr)
        return 1

    def _poll_broadcast(self) -> int:
        """Common-kind fan-out: one order per (second, job) for the whole
        fleet; this node runs it iff it is eligible (local IsRunOn).  The
        key is shared — never deleted by a consumer; its lease GCs it."""
        n = 0
        evs = [ev for ev in self._w_broadcast.drain() if ev.type != DELETE]
        if len(evs) > 1:
            off = len(self.ks.dispatch_all)
            self._prefetch_jobs(ev.kv.key[off:] for ev in evs)
        for ev in evs:
            n += self._handle_broadcast_kv(ev.kv.key)
        return n

    def _poll_once(self) -> int:
        n = 0
        for ev in self._w_once.drain():
            if ev.type == DELETE:
                continue
            if ev.kv.value not in ("", self.id):
                continue
            rest = ev.kv.key[len(self.ks.once):]
            if "/" not in rest:
                continue
            group, job_id = rest.split("/", 1)
            job = self._get_job(group, job_id)
            if job is None:
                continue
            # run-now bypasses locks and the parallels gate
            # (reference job.go:472-482) — and the exec pool: it must
            # start immediately even with a full order backlog
            self._spawn(job, int(self.clock()), fenced=False,
                        use_gate=False, immediate=True)
            n += 1
        return n

    _spawn_seq = 0

    def _ensure_pool(self) -> _ExecPool:
        if self._pool is None:
            self._pool = _ExecPool(self.max_inflight, f"exec-{self.id}")
        return self._pool

    def _spawn(self, job: Job, epoch_s: int, fenced: bool,
               use_gate: bool = True, order_key: Optional[str] = None,
               immediate: bool = False, pre: Optional[tuple] = None,
               tr: Optional[tuple] = None):
        NodeAgent._spawn_seq += 1
        name = f"exec-{job.id}-{epoch_s}-{NodeAgent._spawn_seq}"

        def run():
            try:
                self._execute(job, epoch_s, fenced, use_gate, order_key,
                              pre=pre, tr=tr)
            except Exception as e:  # noqa: BLE001 — log, don't die silent
                log.errorf("execution %s failed: %s", name, e)
            finally:
                # self-prune: a long-running agent must not accumulate one
                # finished task record per execution
                self.running.pop(name, None)

        task = _ExecTask(run)
        self.running[name] = task
        if immediate:
            # run-now bypasses the pool entirely: a backlog of queued or
            # long-running work must not delay an operator's trigger
            # (reference go job.RunWithRecovery(), node/node.go:423-442)
            t = threading.Thread(target=task.run, daemon=True, name=name)
            t.start()
            return
        self._stage_task(name, task, epoch_s)

    def _stage_task(self, name: str, task: _ExecTask, epoch_s: int):
        # future-epoch orders (the scheduler publishes whole windows
        # ahead of wall-clock) must not occupy pool workers sleeping in
        # _wait_until — they'd starve due work behind them; stage until
        # due.  One monitor thread scans the backlog with bounded naps
        # (injected virtual clocks still make progress, and K staged
        # orders cost zero extra threads); the stage lock makes stop()
        # vs due-enqueue atomic, so a stopping agent can never enqueue
        # into (or resurrect) a shut-down pool.
        with self._stage_mu:
            if self._stop.is_set():
                self.running.pop(name, None)
                task.finished.set()
                return
            if epoch_s - self.clock() <= 0.02:
                self._ensure_pool().enqueue(task)
                return
            self._staged[name] = (task, epoch_s)
            if self._stage_monitor is None or \
                    not self._stage_monitor.is_alive():
                self._stage_monitor = threading.Thread(
                    target=self._stage_loop, daemon=True,
                    name=f"stage-{self.id}")
                self._stage_monitor.start()

    def _stage_loop(self):
        while True:
            with self._stage_mu:
                if self._stop.is_set() or not self._staged:
                    # clear the handle UNDER the lock before exiting: a
                    # concurrent _stage serialized behind us must see
                    # "no monitor" and spawn a fresh one, not skip on an
                    # is_alive() thread that has already decided to die
                    self._stage_monitor = None
                    return
                now = self.clock()
                for name, (task, epoch_s) in list(self._staged.items()):
                    if epoch_s - now <= 0.02:
                        self._staged.pop(name)
                        self._ensure_pool().enqueue(task)
            time.sleep(0.1)


    def join_running(self, timeout: float = 10.0):
        deadline = time.monotonic() + timeout
        while True:
            tasks = list(self.running.items())
            if not tasks:
                break
            for name, t in tasks:
                t.finished.wait(timeout=max(0.0,
                                            deadline - time.monotonic()))
                if t.done():
                    self.running.pop(name, None)
            if time.monotonic() >= deadline:
                break
            # re-snapshot: a bundle task that just finished fans its
            # member executions out to the pool — the barrier must cover
            # work spawned while it waited, not just the first snapshot
        # joined executions' records must be visible in the sink — and
        # their consumed order keys gone from the store — once this
        # returns (callers treat join as the completion barrier); force
        # past any retry backoff — the sink may have healed
        self._flush_acks()
        self._flush_records(force=True)

    # ---- background loop -------------------------------------------------

    def start(self):
        self.register()

        def keepalive_loop():
            # a transient store failure must not permanently kill the node
            # (the lease would expire and the fleet would mark it dead) —
            # but losing the identity to ANOTHER live agent is fatal: keep
            # running and this process ghost-executes orders meant for the
            # replacement
            while not self._stop.wait(max(1.0, self.ttl / 3)):
                try:
                    self.keepalive_once()
                except DuplicateNode as e:
                    log.errorf("node identity lost to a live replacement; "
                               "shutting down: %s", e)
                    self._stop.set()
                    if self.on_fatal is not None:
                        self.on_fatal(e)
                    return
                except Exception as e:  # noqa: BLE001
                    log.warnf("keepalive failed (retrying): %s", e)

        def poll_loop():
            while not self._stop.is_set():
                try:
                    self.poll()
                except Exception as e:  # noqa: BLE001
                    log.warnf("poll failed (retrying): %s", e)
                    time.sleep(0.5)
                time.sleep(0.05)

        for fn in (keepalive_loop, poll_loop):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"agent-{fn.__name__}")
            t.start()
            self._threads.append(t)

    def stop(self):
        self._stop.set()
        # drop staged future orders FIRST: their leases/fences belong to
        # a node that is going away, and join_running must not wait on
        # work that was never due.  Under the stage lock, so the monitor
        # cannot concurrently enqueue one of them.
        with self._stage_mu:
            for name, (task, _epoch) in list(self._staged.items()):
                self._staged.pop(name, None)
                self.running.pop(name, None)
                task.finished.set()
        with self._claim_cv:       # wake the claim flusher so it drains
            self._claim_cv.notify_all()   # pending claims, then exits
        with self._bundle_cv:      # likewise the bundle-claim flusher
            self._bundle_cv.notify_all()
        for t in self._threads:
            t.join(timeout=3)
        self._threads.clear()
        self.join_running()
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None
        # final synchronous drains; anything the store/sink won't take
        # now is lost with the process — order keys age out by lease,
        # records are logged at error level, not "retry"
        self._flush_acks()
        self._flush_records(final=True)
        self.unregister()


def _local_id() -> str:
    """Node identity: first non-loopback IPv4, like the reference
    (utils/local_ip.go:10-31); falls back to hostname."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return socket.gethostname()
