"""Single-process demo: store + scheduler + agents + API + noticer.

    python -m cronsun_tpu.demo [--nodes N] [--port P] [--conf file.json]

Brings the whole system up in one process (the in-memory store plays etcd),
seeds a couple of example jobs, and serves the management UI at
http://127.0.0.1:<port>/ui/ (login admin@admin.com / admin).
"""

from __future__ import annotations

import argparse
import sys
import time

from .conf import parse as parse_conf
from .core import Job, JobRule, Keyspace, KIND_ALONE, KIND_COMMON
from .logsink import JobLogStore
from .node.agent import NodeAgent
from .noticer import Notice, NoticerHost
from .sched import SchedulerService
from .store import MemStore
from .web import ApiServer


class PrintSender:
    def send(self, notice: Notice):
        print(f"[notice] {notice.subject}: {notice.body}", flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=2)
    ap.add_argument("--port", type=int, default=7079)
    ap.add_argument("--conf", default=None)
    ap.add_argument("--seconds", type=float, default=0,
                    help="run for N seconds then exit (0 = forever)")
    args = ap.parse_args(argv)

    cfg = parse_conf(args.conf)
    ks = Keyspace(cfg.prefix)
    store = MemStore()
    store.start_sweeper()
    sink = JobLogStore()  # in-memory for the demo

    agents = [NodeAgent(store, sink, node_id=f"node-{i}", ks=ks,
                        ttl=cfg.node_ttl, proc_ttl=cfg.proc_ttl,
                        lock_ttl=cfg.lock_ttl)
              for i in range(args.nodes)]
    for a in agents:
        a.start()

    sched = SchedulerService(store, ks=ks, job_capacity=cfg.job_capacity,
                             node_capacity=cfg.node_capacity,
                             window_s=cfg.window_s,
                             default_node_cap=cfg.default_node_cap)
    sched.start()

    api = ApiServer(store, sink, ks=ks, security=cfg.security,
                    host="127.0.0.1", port=args.port).start()
    noticer = NoticerHost(store, sink, PrintSender(), ks=ks)
    noticer.start()

    node_ids = [a.id for a in agents]
    for name, cmd, kind in (
            ("heartbeat", "echo beat", KIND_COMMON),
            ("singleton-date", "date", KIND_ALONE)):
        job = Job(name=name, command=cmd, kind=kind, fail_notify=True,
                  rules=[JobRule(timer="*/5 * * * * *", nids=node_ids)])
        job.check()
        store.put(ks.job_key(job.group, job.id), job.to_json())

    print(f"cronsun-tpu demo up: {args.nodes} agents, scheduler leader="
          f"{sched.is_leader}, UI http://127.0.0.1:{api.port}/ui/ "
          f"(admin@admin.com / admin)", flush=True)
    try:
        if args.seconds:
            time.sleep(args.seconds)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        print("shutting down...", flush=True)
        noticer.stop()
        api.stop()
        sched.stop()
        for a in agents:
            a.stop()
        store.close()
        logs, total = sink.query_logs()
        print(f"executed {total} runs across "
              f"{len({l.node for l in logs})} nodes", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
