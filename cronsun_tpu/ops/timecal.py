"""Host-side calendar decomposition: epoch seconds -> cron field indices.

The device kernels test bitmask membership; *what* the wall-clock fields of a
given instant are is decided here on the host, once per window second.  This
is how the TPU path stays timezone- and DST-correct: the reference's cron loop
is TZ-aware (node/cron/cron.go:212-215 uses ``time.Now().In(loc)``), so the
host enumerates actual wall instants in the target zone — a DST spring-forward
gap simply never appears in the enumeration, and a fall-back fold appears
twice, exactly as real wall clocks do.

Two paths:

- fixed-offset zones (UTC or any constant offset): fully vectorized numpy
  civil-from-days math (Howard Hinnant's algorithm) — O(W) numpy ops, no
  Python per-instant loop; this is the hot path for the 1M-job tick bench.
- DST zones (zoneinfo): per-instant Python ``datetime`` loop; windows on the
  tick path are short (W <= a few hundred), so this stays off the critical
  budget.
"""

from __future__ import annotations

import datetime as _dt
from datetime import timezone, timedelta

import numpy as np

__all__ = ["window_fields", "decompose_utc", "tz_fixed_offset_seconds"]

_UTC = timezone.utc


_probe_cache: dict = {}


def tz_fixed_offset_seconds(tz) -> "int | None":
    """Return the zone's constant UTC offset in seconds, or None if the zone
    has transitions (DST or historical offset changes) we must honor."""
    if tz is _UTC or tz == _UTC:
        return 0
    if isinstance(tz, timezone):  # datetime.timezone is always fixed
        return int(tz.utcoffset(None).total_seconds())
    try:
        # probe result cached per zone object (ZoneInfo instances are
        # interned per key); unhashable custom tzinfo just re-probes
        return _probe_cache[tz]
    except KeyError:
        pass
    except TypeError:
        return _probe_tz(tz)
    off = _probe_tz(tz)
    _probe_cache[tz] = off
    return off


def _probe_tz(tz) -> "int | None":
    # zoneinfo / pytz style: probe DETERMINISTIC instants — twice a month
    # over 2020..2031 (288 probes, ~0.4 ms, cached per zone).  The
    # density matters: quarterly sampling misses short offset excursions
    # (Africa/Casablanca leaves +01 for ~1 month each Ramadan), and any
    # wall-clock-dependent probe would make the classification flip
    # day-to-day and diverge across multi-host mesh ranks (hostsync
    # requires bit-identical planner inputs per rank).  Residual
    # assumption (documented): a transition legislated for after 2031,
    # or one published into the tzdb mid-process, is not seen until the
    # probe range is extended / the process restarts.
    probes = [
        _dt.datetime(year, month, day, 12, tzinfo=_UTC)
        for year in range(2020, 2032)
        for month in range(1, 13)
        for day in (1, 15)
    ]
    offs = {p.astimezone(tz).utcoffset() for p in probes}
    if len(offs) == 1:
        return int(offs.pop().total_seconds())
    return None


def decompose_utc(epoch_s: np.ndarray, offset_s: int = 0):
    """Vectorized civil decomposition of epoch seconds (+ fixed offset).

    Returns (sec, min, hour, dom, month, dow) int32 arrays, dow Sunday==0
    (Go's time.Weekday numbering, node/cron/spec.go:41-46).
    """
    t = np.asarray(epoch_s, dtype=np.int64) + offset_s
    days, rem = np.divmod(t, 86400)
    hour, rem = np.divmod(rem, 3600)
    minute, sec = np.divmod(rem, 60)
    # 1970-01-01 was a Thursday; Sunday==0 indexing puts Thursday at 4.
    dow = (days + 4) % 7
    # Howard Hinnant civil_from_days, vectorized.
    z = days + 719468
    era = np.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097                                    # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)           # [0, 365]
    mp = (5 * doy + 2) // 153                                 # [0, 11]
    dom = doy - (153 * mp + 2) // 5 + 1                       # [1, 31]
    month = np.where(mp < 10, mp + 3, mp - 9)                 # [1, 12]
    i32 = np.int32
    return (sec.astype(i32), minute.astype(i32), hour.astype(i32),
            dom.astype(i32), month.astype(i32), dow.astype(i32))


def window_fields(start_epoch_s: int, count: int, step_s: int = 1, tz=_UTC):
    """Field table for a window of ``count`` instants starting at
    ``start_epoch_s`` spaced ``step_s`` apart, decomposed in ``tz``.

    Returns a dict of numpy int32 arrays with keys
    ``sec/min/hour/dom/month/dow``, each shape [count].
    """
    off = tz_fixed_offset_seconds(tz)
    if off is not None:
        epochs = start_epoch_s + step_s * np.arange(count, dtype=np.int64)
        s, m, h, d, mo, w = decompose_utc(epochs, off)
    else:
        s = np.empty(count, np.int32); m = np.empty(count, np.int32)
        h = np.empty(count, np.int32); d = np.empty(count, np.int32)
        mo = np.empty(count, np.int32); w = np.empty(count, np.int32)
        t = _dt.datetime.fromtimestamp(start_epoch_s, _UTC)
        delta = timedelta(seconds=step_s)
        for i in range(count):
            loc = t.astimezone(tz)
            s[i] = loc.second; m[i] = loc.minute; h[i] = loc.hour
            d[i] = loc.day; mo[i] = loc.month; w[i] = (loc.weekday() + 1) % 7
            t += delta
    return {"sec": s, "min": m, "hour": h, "dom": d, "month": mo, "dow": w}
