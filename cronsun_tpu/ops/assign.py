"""Load-balanced, capacity-constrained job->node assignment.

Replaces the reference's *implicit* placement protocol — every eligible node
races for an etcd lock at fire time and an arbitrary winner runs the job
(job.go:243-271, client.go:95-109) — with one deterministic batched solve:

- jobs of kind Alone/Interval ("exclusive") are placed on exactly one
  eligible node, chosen by least load with capacity rationing;
- jobs of kind Common fan out to every eligible node (the reference's
  semantics: no lock, all eligible nodes fire — job.go:141-147), and their
  cost is accumulated into node loads in one fused pass.

The solve runs ``rounds`` bid/accept rounds over the whole fired bucket:

  bid:    every unplaced job picks its least-loaded open eligible node
          (argmin over load + deterministic tie-hash).
  accept: bidders on the same node are ranked (stable sort by node) and
          accepted up to (a) remaining node capacity and (b) a waterfill
          quota — the chunk's target load level — so one min-load node is
          never dogpiled; losers rebid against updated loads.  The final
          round accepts anything within capacity.

The bid and the Common fan-out are the bandwidth-critical steps; on TPU they
run as Pallas kernels over the *bitpacked* eligibility (see pallas_kernels:
~30x less HBM traffic than materializing [K, N] floats).  A jnp reference
path (same tie-hash, bit-identical choices) serves CPU tests and the
multichip dry-run.

Capacity semantics: a -1 result for an exclusive fired job with eligible
nodes means every one of them filled up — the reference's Parallels-gate
"skip this run" outcome (job.go:176-180).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .pallas_kernels import _TJ, _tie, bid_argmin, fanout_add

__all__ = ["assign", "unpack_tile"]


def unpack_tile(packed: jax.Array, n_nodes: int) -> jax.Array:
    """[K, W32] uint32 -> [K, n_nodes] bool eligibility tile (reference path;
    materializes the dense matrix — test/CPU scale only)."""
    cols = jnp.arange(n_nodes, dtype=jnp.int32)
    words = packed[:, cols // 32]
    return ((words >> (cols % 32).astype(jnp.uint32)) & 1) != 0


def bid_block_jnp(packed, load_blk, col0=0, bitplane_ties=True):
    """Dense-reference bid over a node-column block.

    ``col0`` puts the tie-hash and the returned choice in GLOBAL node
    coordinates (the 2-D mesh shards columns).  Exact-score ties (16-bit
    tie-hash collisions happen at 10k nodes) resolve per
    ``bitplane_ties``:

    - True: the pallas kernel's scan order — bit planes b=0..31 outer,
      words w inner, i.e. lexicographic (score, b, w) with n = w*32 + b.
      Required wherever jnp and pallas paths must pick bit-identically.
    - False: natural column order (lowest global node id).  This order is
      invariant to how columns are split across a nodes axis — the 2-D
      mesh's cross-shard argmin reduce composes with it exactly.
    """
    K = packed.shape[0]
    w32 = packed.shape[1]
    n = w32 * 32
    elig = unpack_tile(packed, n)
    jix = jnp.arange(K, dtype=jnp.uint32)[:, None]
    nix = (col0 + jnp.arange(n)).astype(jnp.uint32)[None, :]
    score = jnp.where(elig, load_blk[None, :] + _tie(jix, nix), jnp.inf)
    if bitplane_ties:
        score_bw = score.reshape(K, w32, 32).transpose(0, 2, 1).reshape(K, n)
        p = jnp.argmin(score_bw, axis=1).astype(jnp.int32)
        choice = (p % w32) * 32 + p // w32
    else:
        choice = jnp.argmin(score, axis=1).astype(jnp.int32)
    return jnp.min(score, axis=1), choice + col0


def _bid_jnp(packed, load_eff):
    return bid_block_jnp(packed, load_eff, col0=0, bitplane_ties=True)


def _fanout_jnp(packed, w):
    n = packed.shape[1] * 32
    elig = unpack_tile(packed, n)
    return jnp.einsum("jn,j->n", elig.astype(jnp.float32), w,
                      preferred_element_type=jnp.float32)


def _steps(impl: str):
    if impl == "jnp":
        return _bid_jnp, _fanout_jnp
    if impl == "mixed":
        # the measured-on-v5e sweet spot below ~32k nodes/device: the
        # bid rides the MXU einsum (slightly faster while the [K, N]
        # score tile is cheap), the fanout stays on the bit-plane
        # kernel (3-50x faster at every scale — its jnp fallback
        # materializes the dense matrix just to weigh it once)
        return _bid_jnp, fanout_add
    interp = impl == "interpret"
    return (functools.partial(bid_argmin, interpret=interp),
            functools.partial(fanout_add, interpret=interp))


def choose_impl(n_per_device: int, *bucket_ks: int) -> str:
    """THE auto heuristic, shared by assign(), TickPlanner and the mesh
    planners (three hand-rolled copies drifted once already).  Measured
    on v5e (bench.py kernel_*_ms): the jnp/MXU bid wins wherever its
    [K, N] f32 score tile is affordable, while the bit-plane pallas
    fanout wins at scale (its jnp fallback materializes the dense
    matrix just to weigh it once) — so "mixed" is the default.  Past
    ~2 GB of score tile the pallas bid takes over: not for speed but to
    BOUND memory next to 1M-row schedule state.  Everything falls back
    to jnp off-TPU or when a bucket breaks the 256-row alignment the
    kernels require.

    Shapes are PER-DEVICE, always: the bid tile a device materializes
    is [its bucket rows, its node columns], so mesh planners must pass
    ``k_local`` (the J/D-sharded bucket — never the global K) and
    ``N // Dn`` — with bucket-sharded bidding the local bucket is also
    what the reconcile sorts, so a global-K call would overshoot the
    2 GB cutover Dj-fold and pick pallas where mixed wins.  The
    planners' ``_resolve_impl`` owns that division; pinned by
    tests/test_assign.py::test_choose_impl_boundaries."""
    if jax.default_backend() != "tpu" or any(k % _TJ for k in bucket_ks):
        return "jnp"
    tile_bytes = max(bucket_ks, default=0) * n_per_device * 4
    return "pallas" if tile_bytes > (2 << 30) else "mixed"


def _rank_within_choice(key: jax.Array):
    """Stable sort by key; returns (rank within equal keys, sort order,
    sorted keys, segment-start positions).

    Segment starts come from a cummax over change points — one sort total
    per round (searchsorted would be a second O(K log K) pass; sorts are
    the TPU-expensive step here)."""
    K = key.shape[0]
    order = jnp.argsort(key, stable=True)
    sorted_key = key[order]
    pos = jnp.arange(K, dtype=jnp.int32)
    is_first = jnp.concatenate([jnp.ones(1, bool),
                                sorted_key[1:] != sorted_key[:-1]])
    first = jax.lax.cummax(jnp.where(is_first, pos, 0))
    rank = pos - first
    return rank, order, sorted_key, first


def _assign_excl(valid, elig_packed, load, rem_cap, cost, rounds: int,
                 impl: str):
    """Bid/accept rounds for a bucket of EXCLUSIVE fired jobs only.

    The split-bucket planner path: Common fan-out is a single
    :func:`fanout` pass over its own bucket, so the expensive [K, N] bid
    sweep runs ``rounds`` times over just the exclusive fires (typically
    a fraction of all fires).  load/rem_cap must already be padded to the
    bitpacked width.  Traced inside the caller's jit.
    """
    K = valid.shape[0]
    bid, _ = _steps(impl)
    cost = cost.astype(jnp.float32)
    assigned = jnp.full(K, -1, dtype=jnp.int32)
    for r in range(rounds):
        load_eff = jnp.where(rem_cap > 0, load, jnp.inf)
        best, choice = bid(elig_packed, load_eff)
        cand = valid & (assigned < 0) & jnp.isfinite(best)
        accept, load, rem_cap = waterfill_accept(
            cand, choice, cost, load, rem_cap, r == rounds - 1)
        assigned = jnp.where(accept, choice, assigned)
    return assigned, load, rem_cap


def _fanout_load(elig_packed, valid, cost, load, impl: str):
    """Accumulate Common-bucket cost into per-node load (one fused pass)."""
    _, fanout = _steps(impl)
    w = jnp.where(valid, cost.astype(jnp.float32), 0.0)
    return load + fanout(elig_packed, w)


@functools.partial(jax.jit, static_argnames=("rounds", "impl"))
def _assign_impl(fire, elig_packed, exclusive, load, rem_cap, cost,
                 rounds: int, impl: str):
    K = fire.shape[0]
    n_nodes = rem_cap.shape[0]
    n_padded = elig_packed.shape[1] * 32
    bid, fanout = _steps(impl)

    # Pad node vectors to the bitpacked width; pad columns have zero
    # capacity so they are never chosen.
    pad = n_padded - n_nodes
    load = jnp.pad(load, (0, pad))
    rem_cap = jnp.pad(rem_cap, (0, pad))

    cost = cost.astype(jnp.float32)
    common_w = jnp.where(fire & ~exclusive, cost, 0.0)
    load = load + fanout(elig_packed, common_w)

    need0 = fire & exclusive
    assigned = jnp.full(K, -1, dtype=jnp.int32)

    # NOTE (measured, don't re-attempt): a lax.cond early-exit that skips
    # later rounds "when round r settled everything" never fires in
    # practice — the waterfill quota deliberately rejects over-level
    # candidates on every non-final round (anti-dogpile) — and the cond
    # itself cost ~+3 ms/solve at a 16k bucket on v5e.
    for r in range(rounds):
        load_eff = jnp.where(rem_cap > 0, load, jnp.inf)
        best, choice = bid(elig_packed, load_eff)
        cand = need0 & (assigned < 0) & jnp.isfinite(best)
        accept, load, rem_cap = waterfill_accept(
            cand, choice, cost, load, rem_cap, r == rounds - 1)
        assigned = jnp.where(accept, choice, assigned)

    return assigned, load[:n_nodes], rem_cap[:n_nodes]


def local_bid_demand(cand, choice, cost, n_padded: int):
    """Per-shard half of the bucket-sharded waterfill reconcile.

    Within THIS shard's candidate bucket: rank among same-node candidates
    (stable, original-index order) and the exclusive cumulative cost of
    the earlier same-node candidates — plus the per-node demand totals
    (candidate count, candidate cost sum) that shards exchange instead of
    the candidates themselves.  Counts ride f32 so the [2, N] demand
    block is ONE array on the wire; exact below 2^24 candidates per node
    (J tops out at 1M).

    Returns (rank [K] i32, cum_in_seg [K] f32, demand [2, N] f32).
    """
    K = cand.shape[0]
    key = jnp.where(cand, choice, n_padded)
    rank_s, order, _sorted_key, first = _rank_within_choice(key)
    w = jnp.where(cand, cost, 0.0)
    w_sorted = w[order]
    cum_excl = jnp.cumsum(w_sorted) - w_sorted
    cum_seg_s = cum_excl - cum_excl[first]
    rank = jnp.zeros(K, jnp.int32).at[order].set(rank_s)
    cum = jnp.zeros(K, jnp.float32).at[order].set(cum_seg_s)
    safe = jnp.clip(choice, 0, n_padded - 1)
    cnt = jnp.zeros(n_padded, jnp.float32).at[safe].add(
        cand.astype(jnp.float32))
    wn = jnp.zeros(n_padded, jnp.float32).at[safe].add(w)
    return rank, cum, jnp.stack([cnt, wn])


def compact_demand(demand, k_comp: int):
    """Compact a dense [2, N] per-node demand block (count, cost-sum)
    into [3, k_comp] f32 triples (node_idx, count, cost_sum) — the
    sparse-tick wire format the mesh reconcile gathers instead of the
    dense block.

    A shard's demand has at most min(#candidates, N) nonzero nodes, so
    ``k_comp = min(k_local, N)`` NEVER truncates: every nonzero entry
    survives compaction by construction.  Node indices ride f32 (exact
    below 2^24 — N tops out at ~100k), so the gathered payload is ONE
    [3, k_comp] array: 12 B x k_comp per shard vs 8 B x N dense.  Pad
    entries carry distinct zero-demand node ids with count = cost = 0,
    so the scatter-add in :func:`scatter_demand` is a no-op for them.
    """
    nz = demand[0] > 0
    # stable argsort of the ~nonzero mask: nonzero node ids first, in
    # ascending node order (the planner's _compact idiom)
    order = jnp.argsort(~nz, stable=True)
    idx = order[:k_comp]
    take = nz[idx]
    cnt = jnp.where(take, demand[0][idx], 0.0)
    w = jnp.where(take, demand[1][idx], 0.0)
    return jnp.stack([idx.astype(jnp.float32), cnt, w]), idx


def scatter_demand(comp, n_padded: int):
    """Gathered compacted triples [D, 3, k_comp] -> dense [D, 2, N]
    per-shard demand blocks, scatter-added back so downstream prefix
    sums see BYTE-identical inputs to the dense all_gather path.

    Within one shard the compacted node ids are distinct (they come
    from a permutation), so the scatter-add never accumulates twice
    into one slot — the dense block it rebuilds equals the block
    :func:`compact_demand` started from, value for value, and the
    shard-major prefix reduction over it is bit-identical to the dense
    path's."""
    D = comp.shape[0]
    idx = jnp.clip(comp[:, 0].astype(jnp.int32), 0, n_padded - 1)
    rows = jnp.arange(D, dtype=jnp.int32)[:, None]
    dense = jnp.zeros((D, 2, n_padded), jnp.float32)
    dense = dense.at[rows, 0, idx].add(comp[:, 1])
    dense = dense.at[rows, 1, idx].add(comp[:, 2])
    return dense


def waterfill_accept_presplit(cand, choice, cost, load, rem_cap, is_final,
                              rank_g, cum_g, tot_w):
    """Accept decision for candidates whose GLOBAL within-node rank and
    cumulative-demand cost are already known (local half + earlier
    shards' per-node prefix).  The same accept predicate as
    :func:`waterfill_accept` — ``rank < rem_cap`` capacity rationing,
    waterfill quota against the global target level, rank-0 progress
    guarantee — just evaluated per shard instead of on a gathered
    bucket, so reconciling costs O(nodes) of exchange, not O(bucket).

    The equivalence is EXACT, not approximate: the replicated
    waterfill's rank/cum-cost are computed over candidate DEMAND (every
    bid in the segment, accepted or not), so earlier shards' influence
    summarizes into two per-node prefix scalars with no circular
    dependency on their accept outcomes.  Bit-identical accepts
    whenever the cost sums are exact in f32 (integer costs; float costs
    can differ by accumulation-order ulps at exact quota boundaries).

    Returns accept [K] bool; the caller owns the load/rem_cap update
    (locally scattered, then psum'd back to replicated).
    """
    n_padded = load.shape[0]
    safe = jnp.clip(choice, 0, n_padded - 1)
    cap_at = rem_cap[safe]
    open_n = rem_cap > 0
    n_open = jnp.maximum(jnp.sum(open_n), 1)
    level = (jnp.sum(jnp.where(open_n, load, 0.0)) + tot_w) / n_open
    w = jnp.where(cand, cost, 0.0)
    headroom = level - load[safe]
    fits = (rank_g == 0) | (cum_g + w <= headroom)
    return cand & (rank_g < cap_at) & (is_final | fits)


def waterfill_accept(cand, choice, cost, load, rem_cap, is_final):
    """One accept step: ration candidate bids per node.

    Accept per node only up to remaining capacity AND (unless final) a
    waterfill quota — the global target load level — so a min-load node is
    never dogpiled; rank 0 always lands (progress guarantee).

    Pure function of replicated state: the multichip path runs it
    identically on every shard after all-gathering the candidate bids.

    Returns (accept [K] bool, new load [N'], new rem_cap [N']).
    """
    K = cand.shape[0]
    n_padded = load.shape[0]
    key = jnp.where(cand, choice, n_padded)
    rank, order, sorted_key, first = _rank_within_choice(key)
    safe_key = jnp.clip(sorted_key, 0, n_padded - 1)
    cap_at = rem_cap[safe_key]

    w = jnp.where(cand, cost, 0.0)
    open_n = rem_cap > 0
    n_open = jnp.maximum(jnp.sum(open_n), 1)
    level = (jnp.sum(jnp.where(open_n, load, 0.0)) + jnp.sum(w)) / n_open
    w_sorted = w[order]
    cum_excl = jnp.cumsum(w_sorted) - w_sorted
    cum_in_seg = cum_excl - cum_excl[first]
    headroom = level - load[safe_key]
    fits = (rank == 0) | (cum_in_seg + w_sorted <= headroom)
    accept_sorted = (sorted_key < n_padded) & (rank < cap_at) & (is_final | fits)
    accept = jnp.zeros(K, dtype=bool).at[order].set(accept_sorted)
    load = load.at[choice].add(jnp.where(accept, cost, 0.0))
    rem_cap = rem_cap.at[choice].add(-accept.astype(jnp.int32))
    return accept, load, rem_cap


def assign(fire: jax.Array, elig_packed: jax.Array, exclusive: jax.Array,
           load: jax.Array, rem_cap: jax.Array, cost: jax.Array,
           rounds: int = 3, impl: str = "auto"):
    """Place all fired jobs for one tick.

    Args:
      fire: [K] bool — jobs firing this tick (K = fired bucket or full J).
      elig_packed: [K, W32] uint32 bitpacked eligibility.
      exclusive: [K] bool — Alone/Interval kinds (exactly-one placement).
      load: [N] f32 per-node load; rem_cap: [N] i32 remaining slots (0 for
        dead columns); cost: [K] f32 per-job expected cost (the reference's
        AvgTime EWMA, job.go:581-589).
      rounds: bid/accept rounds.
      impl: "auto" (choose_impl's measured heuristic), "pallas", "jnp",
        "mixed" (jnp bid + pallas fanout), or "interpret" (pallas
        interpreter — tests).

    Returns: (assigned [K] i32 node column or -1, new load, new rem_cap).
    """
    if impl == "auto":
        impl = choose_impl(elig_packed.shape[1] * 32, fire.shape[0])
    return _assign_impl(fire, elig_packed, exclusive, load, rem_cap, cost,
                        rounds, impl)
