"""Multi-tenant admission control: token buckets in the batched tick +
host-side weighted max-min fair share.

Two cooperating mechanisms, one per resource:

- **Fire-rate token buckets** (device, :func:`admit`): every tenant with
  a quota carries one token-bucket column — ``tokens`` [T] float32,
  refilled by ``rate`` and capped at ``burst`` per scheduled second —
  and the batched tick admits at most ``floor(tokens)`` of the tenant's
  fires per second, in row order.  The pass composes into the planner's
  fused window scan (ops/planner.py) exactly like the DAG plane: a
  handful of elementwise ops per second, compiled OUT entirely
  (``use_tenants`` static arg) while no limited tenant exists, so
  single-tenant tables run the exact pre-tenancy program.

- **Fair-share dispatch** (host, :func:`weighted_max_min` +
  :func:`select_fair`): when a second's aggregate EXCLUSIVE demand
  exceeds the fleet's remaining agent capacity, the order build clamps
  each tenant to its weighted max-min share of the available slots
  instead of letting whoever fired first (i.e. the biggest tenant)
  take everything.  Vectorized numpy in the scheduler's
  ``_build_plan_orders`` path — never a per-fire Python loop.

Which fires get refused, and what happens to them:

- admission picks the FIRST ``allowed`` fired rows of each tenant in
  table-row order (deterministic; pinned by the reference evaluator);
- a refused **time-triggered** fire is SHED — cron semantics, a missed
  second does not come back (counted ``shed_fires``);
- a refused **dep-triggered** fire is THROTTLED — its ``last_fire``
  does not advance, so it retries next tick when the bucket refills
  (counted in ``throttled_fires`` only);
- both are loud: per-tenant counters in scheduler stats, rendered at
  ``/v1/metrics`` as ``cronsun_tenant_*{tenant=...}``.

The per-tenant rank needed to pick "first k fires of tenant t" is
computed WITHOUT a [J, T] one-hot or a sort per tick: the planner keeps
a host-snapshotted permutation grouping rows by tenant (recomputed only
on tenant churn); inside the jit the rank is one gathered cumsum over
the permuted fire column.

:class:`ReferenceAdmission` is the pure-Python spec of the bucket
semantics; :func:`reference_max_min` the fair-share oracle — both drive
the randomized differential tests in tests/test_tenancy.py.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


def tenant_order(tenants: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                               np.ndarray]:
    """Precompute the admission permutation for a row->tenant map:
    ``(perm, sorted_tenant, segbase)`` where ``perm`` stably sorts rows
    by tenant, ``sorted_tenant[i] = tenants[perm[i]]`` and
    ``segbase[i]`` is the permuted index where ``i``'s tenant segment
    begins.  Host-side, O(J log J), recomputed only on tenant churn."""
    t = np.asarray(tenants, np.int32)
    perm = np.argsort(t, kind="stable").astype(np.int32)
    ts = t[perm]
    n = len(ts)
    segbase = np.zeros(n, np.int32)
    if n > 1:
        new = ts[1:] != ts[:-1]
        starts = np.concatenate([[0], np.flatnonzero(new) + 1])
        seg_id = np.concatenate([[0], np.cumsum(new.astype(np.int64))])
        segbase = starts[seg_id].astype(np.int32)
    return perm, ts.astype(np.int32), segbase


def fair_shares(demand, weight, capacity):
    """Device weighted max-min (pure jnp): per-tenant shares of
    ``capacity`` slots — maximize the minimum share/weight subject to
    ``share <= demand`` and ``sum(share) <= capacity``.  Continuous
    waterfill, floored, then the stranded remainder (< 1 slot per
    unsaturated tenant) is granted one unit each to the tenants with
    the smallest floored share/weight (ties to the lowest id) — no
    scarce slot is wasted.  :func:`weighted_max_min` is the same spec
    on the host; ``demand`` [T] int32, ``weight`` [T] f32,
    ``capacity`` f32 scalar."""
    import jax.numpy as jnp
    T = demand.shape[0]
    d = demand.astype(jnp.float32)
    cap = jnp.maximum(capacity, 0.0)
    r = d / weight
    order = jnp.argsort(r)
    d_s = d[order]
    w_s = weight[order]
    cum_d = jnp.cumsum(d_s)
    cum_w = jnp.cumsum(w_s)
    rem_cap = cap - jnp.concatenate([jnp.zeros(1, jnp.float32),
                                     cum_d[:-1]])
    rem_w = (cum_w[-1] - jnp.concatenate([jnp.zeros(1, jnp.float32),
                                          cum_w[:-1]]))
    level_k = rem_cap / jnp.maximum(rem_w, 1e-9)
    saturates = d_s <= level_k * w_s
    # tenants saturate in a prefix of the demand/weight order; cumprod
    # finds its length robustly (spurious saturations past the split
    # don't count)
    k = jnp.sum(jnp.cumprod(saturates.astype(jnp.int32)))
    level = level_k[jnp.minimum(k, T - 1)]
    in_prefix = jnp.arange(T) < k
    shares_s = jnp.where(in_prefix | (k >= T), d_s,
                         jnp.minimum(d_s, jnp.floor(level * w_s)))
    shares = jnp.zeros(T, jnp.int32).at[order].set(
        shares_s.astype(jnp.int32))
    # top-up: flooring strands < 1 unit per unsaturated tenant; grant
    # the leftover one unit each by smallest floored share/weight
    # (stable argsort: ties resolve to the lowest tenant id).  With
    # abundant capacity nothing is eligible and the grant is empty.
    eligible = shares < demand.astype(jnp.int32)
    leftover = jnp.clip(jnp.floor(cap).astype(jnp.int32)
                        - jnp.sum(shares), 0, T)
    leftover = jnp.minimum(leftover,
                           jnp.sum(eligible.astype(jnp.int32)))
    key = jnp.where(eligible, shares.astype(jnp.float32) / weight,
                    jnp.inf)
    order2 = jnp.argsort(key)
    grant = jnp.zeros(T, bool).at[order2].set(jnp.arange(T) < leftover)
    return shares + (grant & eligible).astype(jnp.int32)


def admit(fire, time_fire, exclusive, tokens, rate, burst, limited,
          weight, rem_cap, perm, sorted_tenant, segbase,
          n_tenants: int):
    """One second of tenant admission (pure jnp, traced inside the
    planner's jitted window scan), two clamps:

    1. **token bucket** — each LIMITED tenant's fires clamp to
       ``floor(tokens)`` after this second's refill, first fires in
       row order winning;
    2. **fair share** — when the surviving EXCLUSIVE demand exceeds
       the fleet's remaining slots (``sum(rem_cap)``), each tenant
       clamps to its weighted max-min share (:func:`fair_shares`), so
       the scarce slots spread by weight instead of first-come.  Runs
       BEFORE the capacity-constrained assign, which then places a
       fair mix.  With abundant capacity shares == demand and the
       clamp is inert.

    ``fire`` [J] bool — all fires this second (time + dep);
    ``time_fire`` [J] bool — the time-triggered subset (refusals are
    shed, not retried); ``exclusive`` [J] bool; ``tokens``/``rate``/
    ``burst``/``limited``/``weight`` [T]; ``rem_cap`` [N] int32;
    ``perm``/``sorted_tenant``/``segbase`` from :func:`tenant_order`.

    Tokens are spent by FINALLY admitted fires only (a fire the fair
    clamp refused did not run).  Returns ``(admitted [J] bool,
    new_tokens [T] f32, throttled [T] i32, shed [T] i32)``."""
    import jax.numpy as jnp
    T = n_tenants
    # refill first: a second's own refill is spendable in that second
    tokens = jnp.minimum(burst, tokens + rate)
    allowed = jnp.floor(tokens).astype(jnp.int32)
    fp = fire[perm].astype(jnp.int32)
    c = jnp.cumsum(fp)
    base = jnp.where(segbase > 0, c[jnp.maximum(segbase - 1, 0)], 0)
    rank = c - base                       # 1-based among my tenant's fires
    lim_row = limited[sorted_tenant]
    a1_p = (fp > 0) & (~lim_row | (rank <= allowed[sorted_tenant]))
    # fair share over the rate-admitted exclusive demand
    ex_p = exclusive[perm]
    fx = (a1_p & ex_p).astype(jnp.int32)
    cx = jnp.cumsum(fx)
    base_x = jnp.where(segbase > 0, cx[jnp.maximum(segbase - 1, 0)], 0)
    rank_x = cx - base_x
    demand_x = jnp.zeros(T, jnp.int32).at[sorted_tenant].add(fx)
    cap = jnp.sum(jnp.maximum(rem_cap, 0).astype(jnp.float32))
    shares = fair_shares(demand_x, weight, cap)
    admit_p = a1_p & (~ex_p | (rank_x <= shares[sorted_tenant]))
    admitted = jnp.zeros_like(fire).at[perm].set(admit_p)
    fired_t = jnp.zeros(T, jnp.int32).at[sorted_tenant].add(fp)
    adm_t = jnp.zeros(T, jnp.int32).at[sorted_tenant].add(
        admit_p.astype(jnp.int32))
    shed_p = (fp > 0) & ~admit_p & time_fire[perm]
    shed_t = jnp.zeros(T, jnp.int32).at[sorted_tenant].add(
        shed_p.astype(jnp.int32))
    tokens = jnp.where(limited, tokens - adm_t.astype(jnp.float32),
                       tokens)
    return admitted, tokens, fired_t - adm_t, shed_t


class ReferenceAdmission:
    """Pure-Python spec of the token-bucket admission (the differential
    oracle).  ``quotas``: {tenant_id: (rate, burst)}; absent tenants are
    unlimited."""

    def __init__(self, quotas: Dict[int, Tuple[float, float]]):
        self.quotas = dict(quotas)
        self.tokens = {t: b for t, (_r, b) in quotas.items()}

    def tick(self, fires: Sequence[Tuple[int, int]]) -> List[bool]:
        """``fires`` = [(row, tenant)] in ROW order; returns the admit
        decision per fire after one second's refill."""
        for t, (r, b) in self.quotas.items():
            self.tokens[t] = min(b, self.tokens[t] + r)
        allowed = {t: int(np.floor(v)) for t, v in self.tokens.items()}
        taken: Dict[int, int] = {}
        out = []
        for _row, ten in sorted(fires):
            if ten not in self.quotas:
                out.append(True)
                continue
            k = taken.get(ten, 0)
            ok = k < allowed[ten]
            if ok:
                taken[ten] = k + 1
                self.tokens[ten] -= 1.0
            out.append(ok)
        return out


# ---------------------------------------------------------------------------
# fair share (host, vectorized)
# ---------------------------------------------------------------------------

def weighted_max_min(demand: np.ndarray, weight: np.ndarray,
                     capacity: int) -> np.ndarray:
    """Integer weighted max-min shares: maximize the minimum
    ``share/weight`` subject to ``share_t <= demand_t`` and
    ``sum(share) <= capacity``.

    Vectorized waterfill: tenants sorted by ``demand/weight`` saturate
    in that order; the rest split the remaining capacity by weight.
    Fractional remainders are granted one unit each in ascending tenant
    order (deterministic).  Returns int64 shares, same shape as demand.
    """
    d = np.asarray(demand, np.int64)
    w = np.asarray(weight, np.float64)
    n = len(d)
    shares = np.zeros(n, np.int64)
    if capacity <= 0 or n == 0:
        return shares
    if d.sum() <= capacity:
        return d.copy()
    active = d > 0
    idx = np.flatnonzero(active)
    r = d[idx] / w[idx]
    order = idx[np.argsort(r, kind="stable")]
    # walk saturation points: after the k cheapest tenants saturate,
    # the level is (capacity - sum of their demands) / remaining weight;
    # the first k where the next tenant would NOT saturate is the split
    d_sorted = d[order].astype(np.float64)
    w_sorted = w[order]
    cum_d = np.concatenate([[0.0], np.cumsum(d_sorted)])
    cum_w = np.concatenate([[0.0], np.cumsum(w_sorted)])
    total_w = cum_w[-1]
    rem_cap = capacity - cum_d[:-1]                    # before tenant k
    rem_w = total_w - cum_w[:-1]
    level = rem_cap / np.maximum(rem_w, 1e-12)
    saturates = d_sorted <= level * w_sorted
    # tenants saturate in a prefix (level is monotone non-increasing
    # past the true split); the first non-saturating index is the split
    ns = np.flatnonzero(~saturates)
    k = int(ns[0]) if len(ns) else len(order)
    sat = order[:k]
    uns = order[k:]
    shares[sat] = d[sat]
    if len(uns):
        lvl = (capacity - d[sat].sum()) / w[uns].sum()
        frac = lvl * w[uns]
        base = np.floor(frac).astype(np.int64)
        base = np.minimum(base, d[uns])
        shares[uns] = base
        # flooring strands < 1 unit per unsaturated tenant; grant the
        # leftover ONE unit each to the tenants with the smallest
        # floored share/weight (ties to the lowest id) — the exact
        # rule the device :func:`fair_shares` applies, single pass.
        left = int(capacity - shares.sum())
        if left > 0:
            cands = np.flatnonzero(shares < d)
            order2 = cands[np.argsort(shares[cands] / w[cands],
                                      kind="stable")]
            shares[order2[:left]] += 1
    return shares


def reference_max_min(demand, weight, capacity) -> np.ndarray:
    """O(T^2) oracle for :func:`weighted_max_min` — the same spec
    (continuous weighted max-min, then floor + smallest-share/weight
    top-up) computed the obviously-correct way: iterative saturation
    with no sort, no prefix algebra.  Differential target for the
    vectorized version."""
    d = np.asarray(demand, np.int64)
    w = np.asarray(weight, np.float64)
    n = len(d)
    shares = np.zeros(n, np.int64)
    cap = float(capacity)
    if capacity <= 0 or n == 0:
        return shares
    if d.sum() <= capacity:
        return d.copy()
    active = {t for t in range(n) if d[t] > 0}
    # peel off saturating tenants until the level is below everyone
    level = 0.0
    while active:
        level = cap / sum(w[t] for t in active)
        sat = [t for t in active if d[t] <= level * w[t]]
        if not sat:
            break
        for t in sat:
            shares[t] = d[t]
            cap -= float(d[t])
            active.discard(t)
    for t in active:
        shares[t] = min(d[t], int(np.floor(level * w[t])))
    left = int(capacity - shares.sum())
    if left > 0:
        cands = sorted((t for t in range(n) if shares[t] < d[t]),
                       key=lambda t: (shares[t] / w[t], t))
        for t in cands[:left]:
            shares[t] += 1
    return np.asarray(shares, np.int64)


def select_fair(tenants: np.ndarray, caps: np.ndarray) -> np.ndarray:
    """Keep mask selecting the FIRST ``caps[t]`` entries of each tenant
    in input order (vectorized: stable argsort + per-segment rank).
    ``tenants`` [F] int32 ids; ``caps`` [T] int64 (index by id)."""
    t = np.asarray(tenants, np.int64)
    n = len(t)
    if n == 0:
        return np.zeros(0, bool)
    order = np.argsort(t, kind="stable")
    ts = t[order]
    # rank within segment, in input order (stable sort preserves it)
    new = np.concatenate([[True], ts[1:] != ts[:-1]])
    starts = np.flatnonzero(new)
    seg_id = np.cumsum(new) - 1
    rank = np.arange(n, dtype=np.int64) - starts[seg_id]
    keep_sorted = rank < np.asarray(caps, np.int64)[ts]
    keep = np.zeros(n, bool)
    keep[order] = keep_sorted
    return keep
