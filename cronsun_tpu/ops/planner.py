"""TickPlanner: the device-resident scheduling state + one-call tick plan.

This is the TPU replacement for the reference's entire per-node hot loop
(node/cron/cron.go:210-275): instead of N nodes each sorting entries and
walking ``Schedule.Next`` per job, one planner holds ALL jobs' compiled
schedules, the bitpacked eligibility matrix, per-node loads and capacities on
device, and answers "who fires this second, and where does each run" in a
single fused dispatch chain:

    fire_mask [J] -> compact fired rows into a fixed bucket [K] ->
    capacity-constrained waterfill assign on the bucket -> scatter back [J]

Compaction is the key asymmetry: fire rates are sparse (a second matches few
schedules), so the expensive [K, N] solve runs on the fired bucket, not all
J rows.  Bucket sizes snap to powers of two so XLA compiles a handful of
variants, never per-tick.

State updates (job churn, node churn, load decay, completed executions) are
in-place scatters at fixed shapes — no recompiles.
"""

from __future__ import annotations

import dataclasses
from datetime import timezone
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .assign import assign
from .schedule_table import ScheduleTable, build_table
from .tick import fire_mask

_UTC = timezone.utc

def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


@partial(jax.jit, static_argnames=("k",))
def _compact(fire: jax.Array, k: int):
    """Indices of up to k fired jobs + validity mask + overflow count."""
    total = jnp.sum(fire.astype(jnp.int32))
    idx = jnp.nonzero(fire, size=k, fill_value=0)[0].astype(jnp.int32)
    valid = jnp.arange(k, dtype=jnp.int32) < total
    return idx, valid, total


def _bucket_assign(idx, valid, elig_packed, exclusive, cost, load, rem_cap,
                   rounds, impl):
    packed_k = elig_packed[idx]
    excl_k = exclusive[idx]
    cost_k = cost[idx]
    return assign(valid, packed_k, excl_k, load, rem_cap, cost_k,
                  rounds=rounds, impl=impl)


def _tick_body(table, fields, elig, exclusive, cost, load, rem_cap,
               k: int, rounds: int, impl: str):
    """One second: fire -> compact -> solve -> pack [3, k] int32
    (fired idx / total at [1,0] / assignment)."""
    from .tick import _fire_mask_jit
    f = [fields[i:i + 1] for i in range(7)]
    fire = _fire_mask_jit(table, *f)[:, 0]
    idx, valid, total = _compact(fire, k)
    assigned_k, load, rem_cap = _bucket_assign(
        idx, valid, elig, exclusive, cost, load, rem_cap, rounds, impl)
    total_row = jnp.zeros_like(idx).at[0].set(total)
    packed_out = jnp.stack([idx, total_row, assigned_k], axis=0)
    return packed_out, load, rem_cap


@partial(jax.jit, static_argnames=("k", "rounds", "impl"),
         donate_argnames=("load", "rem_cap"))
def _plan_window_step(table: ScheduleTable, fields_w, elig, exclusive, cost,
                      load, rem_cap, k: int, rounds: int, impl: str):
    """W seconds in one dispatch: lax.scan over the window, exactly the
    semantics of W consecutive single ticks (load/capacity carry through),
    but one dispatch + one [W, 3, k] fetch — the host round-trip amortizes
    over the window.  This is how the production loop plans ahead of
    wall-clock (window [t+1, t+W] is solved while t executes)."""
    def body(carry, fvec):
        load, rem_cap = carry
        out, load, rem_cap = _tick_body(
            table, fvec, elig, exclusive, cost, load, rem_cap,
            k, rounds, impl)
        return (load, rem_cap), out

    (load, rem_cap), outs = jax.lax.scan(body, (load, rem_cap), fields_w)
    return outs, load, rem_cap


@dataclasses.dataclass
class TickPlan:
    """Result of one planning step (host-side views)."""
    epoch_s: int
    fired: np.ndarray        # [F] job rows that fired (valid entries)
    assigned: np.ndarray     # [F] node column for exclusive jobs, -1 for
                             #     Common (fan-out) or no-capacity skips
    overflow: int            # fired jobs beyond the bucket SLA (dropped)


class TickPlanner:
    """Owns device state; call :meth:`plan` once per second (or window).

    Capacity model: ``rem_cap[n]`` is the node's remaining concurrency
    budget for *exclusive* placements.  The solve reserves a slot at plan
    time (rem_cap decremented inside assign); executors release it with
    :meth:`job_finished` at completion — the batched analogue of the
    reference's in-process Parallels accounting (job.go:165-187).
    Common-kind fan-out runs never consume rem_cap; they contribute load
    only (via the fanout kernel at plan time, released with
    :meth:`common_finished`).
    """

    def __init__(self, job_capacity: int, node_capacity: int,
                 tz=_UTC, rounds: int = 3, impl: str = "auto",
                 max_fire_bucket: int = 65536):
        self.tz = tz
        self.impl = impl
        self.rounds = rounds
        self.max_fire_bucket = max_fire_bucket
        self.J = _next_pow2(job_capacity)
        self.N = ((node_capacity + 31) // 32) * 32
        self.table: ScheduleTable = build_table([], capacity=self.J)
        self.elig = jnp.zeros((self.J, self.N // 32), jnp.uint32)
        self.exclusive = jnp.zeros(self.J, bool)
        self.cost = jnp.ones(self.J, jnp.float32)
        self.load = jnp.zeros(self.N, jnp.float32)
        self.rem_cap = jnp.zeros(self.N, jnp.int32)   # dead columns stay 0
        # Adaptive fired-bucket: sized from the last observed fire count so
        # quiet tables don't pay the max-SLA solve.  Starts at max.  Shrinks
        # only after a long streak of small ticks (hysteresis — every bucket
        # change recompiles the plan step, ~20s on TPU).
        self._last_total = max_fire_bucket
        self._cur_k = 0
        self._shrink_streak = 0
        self._ticks_pending = 0

    # -- state maintenance (all fixed-shape scatters) ----------------------

    def set_table(self, table: ScheduleTable):
        if table.capacity != self.J:
            raise ValueError(f"table capacity {table.capacity} != {self.J}")
        self.table = table

    def set_eligibility_rows(self, rows: np.ndarray, values: np.ndarray):
        if len(rows):
            self.elig = self.elig.at[jnp.asarray(rows)].set(jnp.asarray(values))

    def set_job_meta(self, rows: np.ndarray, exclusive: np.ndarray,
                     cost: np.ndarray):
        if len(rows):
            r = jnp.asarray(np.asarray(rows, np.int32))
            self.exclusive = self.exclusive.at[r].set(jnp.asarray(exclusive))
            self.cost = self.cost.at[r].set(jnp.asarray(cost, ).astype(jnp.float32))

    def set_node_capacity(self, cols: Sequence[int], caps: Sequence[int]):
        if len(cols):
            c = jnp.asarray(np.asarray(cols, np.int32))
            self.rem_cap = self.rem_cap.at[c].set(
                jnp.asarray(np.asarray(caps, np.int32)))

    def job_finished(self, node_col: int, cost: float):
        """Exclusive execution completed: release the capacity slot the
        solve reserved and retire its load."""
        self.rem_cap = self.rem_cap.at[node_col].add(1)
        self.load = self.load.at[node_col].add(-float(cost))

    def common_finished(self, node_col: int, cost: float):
        """Common (fan-out) execution completed: retire load only — Common
        runs never held a capacity slot."""
        self.load = self.load.at[node_col].add(-float(cost))

    def decay_load(self, factor: float = 0.99):
        self.load = self.load * factor

    def _bucket(self, sla_bucket: Optional[int]) -> int:
        """Adaptive fired-bucket size: ~1.3x headroom over the last observed
        fire count (overflowed ticks bounce back to the max SLA because
        ``_last_total`` reports the true total, not the truncated bucket).
        Grows immediately; shrinks only after 300 consecutive smaller ticks
        (seconds of planned time, regardless of window size), so the bucket
        (and the compiled plan step) doesn't flap."""
        if sla_bucket is not None:
            return min(_next_pow2(min(sla_bucket, self.max_fire_bucket)),
                       self.J)
        ticks = max(1, self._ticks_pending)
        self._ticks_pending = 0
        want = max(2048, self._last_total + (self._last_total >> 2)
                   + (self._last_total >> 4))
        want = min(_next_pow2(min(want, self.max_fire_bucket)), self.J)
        if not self._cur_k or want > self._cur_k:
            self._cur_k = want
            self._shrink_streak = 0
        elif want < self._cur_k:
            self._shrink_streak += ticks
            if self._shrink_streak >= 300:
                self._cur_k = want
                self._shrink_streak = 0
        else:
            self._shrink_streak = 0
        return self._cur_k

    def _impl(self, k: int) -> str:
        if self.impl != "auto":
            return self.impl
        return ("pallas" if jax.default_backend() == "tpu" and k % 256 == 0
                else "jnp")

    # -- the tick ----------------------------------------------------------

    def plan_async(self, epoch_s: int, sla_bucket: Optional[int] = None):
        """Dispatch one tick (a one-second window).  Does not synchronize —
        callers can pipeline several ticks and materialize with
        :meth:`gather`.  ``plan`` is the sync convenience."""
        return self.plan_window_async(epoch_s, 1, sla_bucket)

    def gather(self, handle) -> TickPlan:
        """Materialize a plan_async result (the single host transfer)."""
        return self.gather_window(handle)[0]

    def plan(self, epoch_s: int, sla_bucket: Optional[int] = None) -> TickPlan:
        """Fire + place every job due at ``epoch_s`` (one-second tick)."""
        return self.gather(self.plan_async(epoch_s, sla_bucket))

    # -- windowed planning -------------------------------------------------

    def plan_window_async(self, epoch_s: int, window_s: int,
                          sla_bucket: Optional[int] = None):
        """Dispatch one window of ``window_s`` consecutive seconds."""
        from .schedule_table import FRAMEWORK_EPOCH
        from .timecal import window_fields
        k = self._bucket(sla_bucket)
        impl = self._impl(k)
        f = window_fields(epoch_s, window_s, tz=self.tz)
        fields_w = np.stack([
            f["sec"], f["min"], f["hour"], f["dom"], f["month"], f["dow"],
            np.arange(window_s, dtype=np.int64) + (epoch_s - FRAMEWORK_EPOCH),
        ], axis=1).astype(np.int32)                     # [W, 7]
        outs, self.load, self.rem_cap = _plan_window_step(
            self.table, jnp.asarray(fields_w),
            self.elig, self.exclusive, self.cost, self.load, self.rem_cap,
            k, self.rounds, impl)
        return epoch_s, k, outs

    def gather_window(self, handle):
        """Materialize a window dispatch into a list of TickPlans."""
        epoch_s, k, outs = handle
        o = np.asarray(outs)                            # [W, 3, k]
        plans = []
        for w in range(o.shape[0]):
            total_h = int(o[w, 1, 0])
            n_valid = min(total_h, k)
            plans.append(TickPlan(
                epoch_s=epoch_s + w,
                fired=o[w, 0, :n_valid],
                assigned=o[w, 2, :n_valid],
                overflow=max(0, total_h - k)))
        if o.shape[0]:
            # adaptive bucket sizing tracks the window's worst second; the
            # shrink hysteresis counts *ticks*, not calls
            self._last_total = int(o[:, 1, 0].max())
            self._ticks_pending += o.shape[0]
        return plans

    def plan_window(self, epoch_s: int, window_s: int,
                    sla_bucket: Optional[int] = None):
        return self.gather_window(
            self.plan_window_async(epoch_s, window_s, sla_bucket))
