"""TickPlanner: the device-resident scheduling state + one-call tick plan.

This is the TPU replacement for the reference's entire per-node hot loop
(node/cron/cron.go:210-275): instead of N nodes each sorting entries and
walking ``Schedule.Next`` per job, one planner holds ALL jobs' compiled
schedules, the bitpacked eligibility matrix, per-node loads and capacities on
device, and answers "who fires this second, and where does each run" in a
single fused dispatch chain:

    fire_mask [J] -> compact fired rows into a fixed bucket [K] ->
    capacity-constrained waterfill assign on the bucket -> scatter back [J]

Compaction is the key asymmetry: fire rates are sparse (a second matches few
schedules), so the expensive [K, N] solve runs on the fired bucket, not all
J rows.  Bucket sizes snap to powers of two so XLA compiles a handful of
variants, never per-tick.

State updates (job churn, node churn, load decay, completed executions) are
in-place scatters at fixed shapes — no recompiles.
"""

from __future__ import annotations

import dataclasses
import threading
from datetime import timezone
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .assign import _assign_excl, _fanout_load, assign
from .schedule_table import ScheduleTable, build_table
from .tick import fire_mask

_UTC = timezone.utc

def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


_CB = 256   # compact block width


@partial(jax.jit, static_argnames=("k",))
def _compact(fire: jax.Array, k: int):
    """Indices of up to k fired jobs + validity mask + overflow count.

    NOT ``jnp.nonzero``: XLA lowers nonzero-with-size through a full sort
    of all J rows (~9 ms/tick at 1M on v5e — measured, it dominated the
    plan step).  Two-level counting instead: per-block fire counts + a
    short block-level cumsum locate each output's block by binary search;
    a [k, block] gather + row-wise running count finds the exact element.
    Sort-free, no J-length cumsum, identical output order to nonzero."""
    J = fire.shape[0]
    if J % _CB:
        # small/odd tables: plain cumsum + searchsorted (still sort-free)
        total = jnp.sum(fire.astype(jnp.int32))
        counts = jnp.cumsum(fire.astype(jnp.int32))
        t = jnp.arange(1, k + 1, dtype=jnp.int32)
        idx = jnp.searchsorted(counts, t, side="left").astype(jnp.int32)
        valid = t <= total
        return jnp.where(valid, idx, 0), valid, total
    nb = J // _CB
    f2 = fire.reshape(nb, _CB).astype(jnp.int32)
    bcum = jnp.cumsum(f2.sum(axis=1))                       # [nb]
    total = bcum[-1]
    t = jnp.arange(1, k + 1, dtype=jnp.int32)
    blk = jnp.minimum(jnp.searchsorted(bcum, t, side="left"),
                      nb - 1).astype(jnp.int32)             # [k]
    rows = f2[blk]                                          # [k, _CB]
    rcum = jnp.cumsum(rows, axis=1)
    prev = jnp.where(blk > 0, bcum[jnp.maximum(blk - 1, 0)], 0)
    tin = (t - prev)[:, None]
    off = jnp.sum((rcum < tin).astype(jnp.int32), axis=1)
    idx = blk * _CB + off
    valid = t <= total
    return jnp.where(valid, idx, 0), valid, total


@partial(jax.jit, static_argnames=("kx", "kc", "rounds", "impl",
                                   "use_deps", "use_tenants"),
         donate_argnames=("load", "rem_cap", "dep_last_fire"))
def _plan_window_step(table: ScheduleTable, fields_w, elig, exclusive, cost,
                      load, rem_cap, dep_succ, dep_fail, dep_block,
                      dep_last_fire, kx: int, kc: int, rounds: int,
                      impl: str, use_deps: bool,
                      tn_perm=None, tn_sorted=None, tn_segbase=None,
                      tb_rate=None, tb_burst=None, tb_limited=None,
                      tb_weight=None, tb_tokens=None,
                      use_tenants: bool = False):
    """W seconds in one dispatch: lax.scan over the window, exactly the
    semantics of W consecutive single ticks (load/capacity carry through),
    but one dispatch + one fetch — the host round-trip amortizes over the
    window.  This is how the production loop plans ahead of wall-clock
    (window [t+1, t+W] is solved while t executes).

    Two latency asymmetries exploited:
    - the fire mask for ALL W seconds is one fused pass before the scan —
      the schedule table (the big [J]-width read) streams from HBM once
      per window, not once per second;
    - fired jobs compact into SEPARATE buckets by kind: only exclusive
      fires (bucket kx) pay the ``rounds``x [K, N] bid sweep; Common
      fires (bucket kc) need exactly one fan-out pass for their load.

    ``use_deps`` (static) folds the workflow-DAG trigger into the same
    scan: per second, one masked gather over the padded dep matrix ORs
    dep fires into the time fires, and the carried ``dep_last_fire``
    advances so a row fires once per upstream round.  False compiles the
    dep ops OUT — a dep-free table runs the exact pre-DAG program (the
    differential test pins bit-identity).

    ``use_tenants`` (static) folds per-tenant token-bucket admission in
    after the dep OR (ops/tenancy.py): refill + rank + clamp per second,
    the ``tb_tokens`` column carried through the scan, per-tenant
    throttle/shed counts a third scan output.  False compiles ALL of it
    out — carry, outputs and every tenant operand vanish from the
    lowered module (they default to None), so a tenant-free table runs
    the exact pre-tenancy program (pinned like the dep test).

    The herd-smearing ``table.jitter`` column never appears in this
    function: plans are built at logical seconds and the deterministic
    per-fire shift is applied by the scheduler host at emission, so
    jitter needs no static arm at all — the unused leaf is pruned by
    jit and the lowered module is identical with or without it (pinned
    in tests/test_jitter.py)."""
    from .tick import _fire_mask_jit
    cols = [fields_w[:, i] for i in range(7)]
    t_rel_w = fields_w[:, 6]
    with jax.named_scope("cronsun.fire_mask"):
        fire_w = _fire_mask_jit(table, *cols)              # [J, W]

    # assigned rides int16 when node columns fit: it halves that output's
    # bytes, and the host fetches both arrays in one materialize
    # (device_get of a tuple is a single tunnel transaction — measured)
    n_cols = elig.shape[1] * 32
    adt = jnp.int16 if n_cols <= 32767 else jnp.int32

    def body(carry, xs):
        if use_tenants:
            load, rem_cap, last_fire, tokens = carry
        else:
            load, rem_cap, last_fire = carry
        fire_col, t_rel = xs
        time_col = fire_col
        dep_f = dep_consume = round_max = None
        if use_deps:
            with jax.named_scope("cronsun.deps"):
                from .deps import dep_ready
                dep_f, dep_consume, round_max = dep_ready(
                    table, dep_succ, dep_fail, dep_block, last_fire)
                fire_col = fire_col | dep_f
        if use_tenants:
            with jax.named_scope("cronsun.tenants"):
                from .tenancy import admit
                admitted, tokens, thr_t, shed_t = admit(
                    fire_col, time_col, exclusive, tokens, tb_rate,
                    tb_burst, tb_limited, tb_weight, rem_cap,
                    tn_perm, tn_sorted, tn_segbase, tb_rate.shape[0])
                fire_col = fire_col & admitted
        if use_deps:
            # advance to the newest consumed upstream epoch, not just
            # the tick: a round scheduled ahead of the firing tick must
            # not re-satisfy the next window.  A THROTTLED dep fire
            # (admission refused it) does NOT advance — it retries when
            # the bucket refills, late-never-lost like every other gate.
            eff_dep = (dep_f & fire_col) if use_tenants else dep_f
            last_fire = jnp.where(
                eff_dep | dep_consume,
                jnp.maximum(t_rel, round_max), last_fire)
        with jax.named_scope("cronsun.compact"):
            xidx, xvalid, xtotal = _compact(fire_col & exclusive, kx)
            cidx, cvalid, ctotal = _compact(fire_col & ~exclusive, kc)
        with jax.named_scope("cronsun.fanout"):
            load = _fanout_load(elig[cidx], cvalid, cost[cidx], load, impl)
        with jax.named_scope("cronsun.assign"):
            assigned, load, rem_cap = _assign_excl(
                xvalid, elig[xidx], load, rem_cap, cost[xidx], rounds, impl)
        out32 = jnp.concatenate([
            jnp.asarray([xtotal, ctotal], jnp.int32),
            xidx, cidx])                               # [2 + kx + kc]
        if use_tenants:
            return (load, rem_cap, last_fire, tokens), \
                (out32, assigned.astype(adt),
                 jnp.stack([thr_t, shed_t]))           # [2, T]
        return (load, rem_cap, last_fire), (out32, assigned.astype(adt))

    if use_tenants:
        (load, rem_cap, dep_last_fire, tb_tokens), \
            (outs32, outs16, outs_t) = jax.lax.scan(
                body, (load, rem_cap, dep_last_fire, tb_tokens),
                (fire_w.T, t_rel_w))
    else:
        (load, rem_cap, dep_last_fire), (outs32, outs16) = \
            jax.lax.scan(body, (load, rem_cap, dep_last_fire),
                         (fire_w.T, t_rel_w))
        outs_t = tb_tokens = None
    return outs32, outs16, outs_t, load, rem_cap, dep_last_fire, tb_tokens


class _AdaptiveBucket:
    """Adaptive fired-bucket size: ~1.3x headroom over the last observed
    fire count (overflowed ticks bounce back because ``feed`` reports the
    true total, not the truncated bucket).  Grows immediately; shrinks
    only after 300 consecutive smaller ticks (seconds of planned time,
    regardless of window size), so the bucket — and the compiled plan
    step — doesn't flap (a bucket change recompiles, ~20s on TPU)."""

    def __init__(self, max_bucket: int, cap: int):
        self.max_bucket = max_bucket
        self.cap = cap
        self.last_total = max_bucket
        self.cur_k = 0
        self._shrink_streak = 0
        self._ticks_pending = 0
        # sizes this bucket has already run at: shrinking BACK to one is
        # free (its executable is cached), so the hysteresis only gates
        # shrinks to never-seen sizes.  Without this, one cron-herd
        # minute boundary pins the bucket at its burst size for 300
        # planned seconds and every steady window pays the burst-sized
        # output fetch (~10 MB/window over the tunnel — measured).
        self.seen: set = set()

    def feed(self, total: int, ticks: int):
        self.last_total = total
        self._ticks_pending += ticks

    def _want(self) -> int:
        """~1.3x headroom over the last observed fire count, snapped to
        a power of two within [2048, min(max_bucket->pow2, cap)] — THE
        sizing formula, shared by size() and peek() so a standby's
        warm-compile always targets the executable a fresh leader's
        first plan will actually request."""
        want = max(2048, self.last_total + (self.last_total >> 2)
                   + (self.last_total >> 4))
        return min(_next_pow2(min(want, self.max_bucket)), self.cap)

    def peek(self) -> int:
        """The size the next ``size(None)`` call would return, without
        mutating the hysteresis state (standby warm-compile)."""
        return self.cur_k or self._want()

    def size(self, sla: Optional[int]) -> int:
        if sla is not None:
            # an explicit SLA is a true override, clamped only by the
            # structural cap (J): the scheduler's overflow re-plan
            # escalates PAST max_bucket so a burst second becomes
            # latency, never loss — and multi-host workers, which
            # receive the sla via the broadcast header, clamp
            # identically without sharing max_bucket state
            return min(_next_pow2(sla), self.cap)
        ticks = max(1, self._ticks_pending)
        self._ticks_pending = 0
        want = self._want()
        if not self.cur_k or want > self.cur_k:
            self.cur_k = want
            self._shrink_streak = 0
        elif want < self.cur_k:
            self._shrink_streak += ticks
            if want in self.seen or self._shrink_streak >= 300:
                self.cur_k = want
                self._shrink_streak = 0
        else:
            self._shrink_streak = 0
        self.seen.add(self.cur_k)
        return self.cur_k


@dataclasses.dataclass
class TickPlan:
    """Result of one planning step (host-side views)."""
    epoch_s: int
    fired: np.ndarray        # [F] job rows that fired (valid entries)
    assigned: np.ndarray     # [F] node column for exclusive jobs, -1 for
                             #     Common (fan-out) or no-capacity skips
    overflow: int            # fired jobs beyond the bucket SLA (absent
                             #     from `fired`; the scheduler re-plans
                             #     the second with an escalated bucket)
    total_fired: int = 0     # TRUE fire count this second (>= len(fired);
                             #     sizes the escalation re-plan)
    n_excl: int = 0          # fired[:n_excl] are the exclusive
                             #     placements (assigned valid);
                             #     fired[n_excl:] are Common fan-outs —
                             #     dispatchers iterate each half without
                             #     a per-fire kind branch
    # multi-tenant admission: per-tenant-id refusal counts this second
    # (None on tenant-free tables — the ops are compiled out).
    # throttled = all refused fires; shed = the time-triggered subset
    # (permanently dropped; throttled dep fires retry next tick).
    tenant_throttled: Optional[np.ndarray] = None   # [T] int32
    tenant_shed: Optional[np.ndarray] = None        # [T] int32


class TickPlanner:
    """Owns device state; call :meth:`plan` once per second (or window).

    Capacity model: ``rem_cap[n]`` is the node's remaining concurrency
    budget for *exclusive* placements.  The solve reserves a slot at plan
    time (rem_cap decremented inside assign); executors release it with
    :meth:`job_finished` at completion — the batched analogue of the
    reference's in-process Parallels accounting (job.go:165-187).
    Common-kind fan-out runs never consume rem_cap; they contribute load
    only (via the fanout kernel at plan time, released with
    :meth:`common_finished`).
    """

    def __init__(self, job_capacity: int, node_capacity: int,
                 tz=_UTC, rounds: int = 2, impl: str = "auto",
                 max_fire_bucket: int = 65536,
                 tenant_capacity: int = 64):
        # rounds=2 (one waterfill-quota round + one capacity-final round)
        # is the latency/balance sweet spot on v5e: each extra round costs
        # ~5 ms/tick at 10k nodes for marginal placement-spread gains.
        # The reference has NO load balancing at all (lock races,
        # job.go:243-271), so even rounds=1 dominates it on balance.
        self.tz = tz
        self.impl = impl
        self.rounds = rounds
        self.max_fire_bucket = max_fire_bucket
        self.J = _next_pow2(job_capacity)
        self.N = ((node_capacity + 31) // 32) * 32
        self.table: ScheduleTable = build_table([], capacity=self.J)
        self.elig = jnp.zeros((self.J, self.N // 32), jnp.uint32)
        self.exclusive = jnp.zeros(self.J, bool)
        self.cost = jnp.ones(self.J, jnp.float32)
        self.load = jnp.zeros(self.N, jnp.float32)
        self.rem_cap = jnp.zeros(self.N, jnp.int32)   # dead columns stay 0
        # workflow DAG state: per-row latest-round epochs (monotone max
        # fold of dep/ completion events), the last-fire vector the scan
        # carries, and the host-computed max_in_flight gate.  The dep
        # ops stay compiled OUT (use_deps static arg) until the
        # scheduler installs the first dep row — dep-free tables run the
        # exact pre-DAG program.
        from .deps import NEVER
        self.dep_succ = jnp.full(self.J, NEVER, jnp.int32)
        self.dep_fail = jnp.full(self.J, NEVER, jnp.int32)
        self.dep_last_fire = jnp.zeros(self.J, jnp.int32)
        self.dep_block = jnp.zeros(self.J, bool)
        self._dep_enabled = False
        # multi-tenant admission state: per-tenant token-bucket columns
        # (rate/burst/limited scattered from quota records, tokens
        # carried through the window scan) and the host row->tenant
        # snapshot the admission permutation derives from.  Compiled
        # OUT (use_tenants static arg) until the scheduler arms it —
        # tenant-free tables run the exact pre-tenancy program.
        self.T = _next_pow2(max(2, tenant_capacity))
        self.tb_rate = jnp.zeros(self.T, jnp.float32)
        self.tb_burst = jnp.zeros(self.T, jnp.float32)
        self.tb_limited = jnp.zeros(self.T, bool)
        self.tb_weight = jnp.ones(self.T, jnp.float32)
        self.tb_tokens = jnp.zeros(self.T, jnp.float32)
        self._tenants_enabled = False
        self._tenant_np = np.zeros(self.J, np.int32)
        self._tn_dirty = True
        self._tn_perm = self._tn_sorted = self._tn_segbase = None
        # Adaptive fired-buckets (one per kind — exclusive fires pay the
        # bid rounds, Common fires only the fan-out): sized from the last
        # observed fire count so quiet tables don't pay the max-SLA solve.
        self._bx = _AdaptiveBucket(max_fire_bucket, self.J)
        self._bc = _AdaptiveBucket(max_fire_bucket, self.J)
        # Double-buffered handles: the scheduler DISPATCHES window N+1
        # (plan_window_async, step thread) while window N is still being
        # GATHERED on the pipeline's build worker.  Each handle freezes
        # its own (kx, kc), so a later bucket resize never corrupts an
        # in-flight gather; this lock is only for the adaptive buckets'
        # hysteresis counters, which the two threads would otherwise
        # read-modify-write concurrently.
        self._bucket_mu = threading.Lock()
        # single-second bucket sizes warmed by warm_escalation: overflow
        # replans snap UP to one of these so a herd burst hits a cached
        # executable instead of compiling mid-step
        self._warmed_single: set = set()

    # -- state maintenance (all fixed-shape scatters) ----------------------

    def set_table(self, table: ScheduleTable):
        if table.capacity != self.J:
            raise ValueError(f"table capacity {table.capacity} != {self.J}")
        self.table = table

    def update_table_rows(self, rows: np.ndarray, vals) -> None:
        """Scatter schedule-row updates — the planner-agnostic mutator
        the scheduler (and the mesh-sync replay) drive; subclasses
        re-pin sharding in their set_table."""
        from .schedule_table import update_rows
        self.set_table(update_rows(self.table, rows, vals))

    def set_load(self, loads: np.ndarray) -> None:
        self.load = jnp.asarray(np.asarray(loads, np.float32))

    def set_eligibility_rows(self, rows: np.ndarray, values: np.ndarray):
        if len(rows):
            self.elig = self.elig.at[jnp.asarray(rows)].set(jnp.asarray(values))

    def set_job_meta(self, rows: np.ndarray, exclusive: np.ndarray,
                     cost: np.ndarray):
        if len(rows):
            r = jnp.asarray(np.asarray(rows, np.int32))
            self.exclusive = self.exclusive.at[r].set(jnp.asarray(exclusive))
            self.cost = self.cost.at[r].set(jnp.asarray(cost, ).astype(jnp.float32))

    def set_node_capacity(self, cols: Sequence[int], caps: Sequence[int]):
        if len(cols):
            c = jnp.asarray(np.asarray(cols, np.int32))
            self.rem_cap = self.rem_cap.at[c].set(
                jnp.asarray(np.asarray(caps, np.int32)))

    # -- workflow DAG state (scheduler-driven scatters) --------------------

    @property
    def dep_enabled(self) -> bool:
        return self._dep_enabled

    def set_dep_enabled(self, flag: bool = True):
        """Arm (or disarm) the dep ops in the plan program.  Flipping
        recompiles the window executable once (a static jit arg) — the
        scheduler arms it when the first dep row lands and leaves it on
        (disarming mid-flight would churn executables for no win)."""
        self._dep_enabled = bool(flag)

    def set_dep_epochs(self, rows, succ, fail):
        """Fold completion-round epochs into the per-row vectors —
        MONOTONE max, so duplicate watch deliveries, multi-node Common
        completions of one round and pad_pow2's repeated rows are all
        idempotent."""
        if len(rows):
            r = jnp.asarray(np.asarray(rows, np.int32))
            self.dep_succ = self.dep_succ.at[r].max(
                jnp.asarray(np.asarray(succ, np.int32)))
            self.dep_fail = self.dep_fail.at[r].max(
                jnp.asarray(np.asarray(fail, np.int32)))

    def reset_dep_rows(self, rows, last_fire_rel=0):
        """Row (re)initialization: epochs back to NEVER and last_fire to
        the registration anchor (a fresh dep row only reacts to upstream
        rounds NEWER than its registration — an upstream success from an
        hour ago must not fire a just-created chain)."""
        if len(rows):
            from .deps import NEVER
            r = jnp.asarray(np.asarray(rows, np.int32))
            self.dep_succ = self.dep_succ.at[r].set(NEVER)
            self.dep_fail = self.dep_fail.at[r].set(NEVER)
            self.dep_last_fire = self.dep_last_fire.at[r].set(
                jnp.asarray(np.asarray(last_fire_rel, np.int32)))
            self.dep_block = self.dep_block.at[r].set(False)

    def set_dep_block(self, rows, vals):
        """max_in_flight saturation gate (host-computed per step)."""
        if len(rows):
            r = jnp.asarray(np.asarray(rows, np.int32))
            self.dep_block = self.dep_block.at[r].set(
                jnp.asarray(np.asarray(vals, bool)))

    def dep_state(self) -> dict:
        """Host copies of the mutable dep vectors (checkpoint capture)."""
        return dict(succ=np.asarray(self.dep_succ),
                    fail=np.asarray(self.dep_fail),
                    last_fire=np.asarray(self.dep_last_fire),
                    block=np.asarray(self.dep_block))

    def set_dep_state(self, succ, fail, last_fire, block):
        """Install checkpointed dep vectors whole (restore path)."""
        self.dep_succ = jnp.asarray(np.asarray(succ, np.int32))
        self.dep_fail = jnp.asarray(np.asarray(fail, np.int32))
        self.dep_last_fire = jnp.asarray(
            np.asarray(last_fire, np.int32))
        self.dep_block = jnp.asarray(np.asarray(block, bool))

    # -- multi-tenant admission state (scheduler-driven) -------------------

    @property
    def tenants_enabled(self) -> bool:
        return self._tenants_enabled

    def set_tenants_enabled(self, flag: bool = True):
        """Arm (or disarm) the admission ops in the plan program.  Like
        the dep plane, flipping recompiles the window executable once
        (a static jit arg); the scheduler arms it when the first
        LIMITED tenant quota lands and leaves it on."""
        self._tenants_enabled = bool(flag)

    def set_row_tenants(self, rows, tids):
        """Update the host row->tenant snapshot (the device ``tenant``
        table column rides the normal row scatters; THIS copy feeds the
        admission permutation, recomputed lazily on the next dispatch).
        """
        if len(rows):
            self._tenant_np[np.asarray(rows, np.int32)] = \
                np.asarray(tids, np.int32)
            self._tn_dirty = True

    def set_tenant_quota(self, tid: int, rate: float, burst: float,
                         weight: float = 1.0):
        """Install/refresh one tenant's bucket column.  Tokens reset to
        a FULL bucket (a fresh/raised quota must not inherit a starved
        bucket; a lowered one clamps at the next refill's min)."""
        t = jnp.asarray([int(tid)], jnp.int32)
        limited = rate > 0
        self.tb_rate = self.tb_rate.at[t].set(np.float32(rate))
        self.tb_burst = self.tb_burst.at[t].set(np.float32(burst))
        self.tb_limited = self.tb_limited.at[t].set(bool(limited))
        self.tb_weight = self.tb_weight.at[t].set(
            np.float32(max(weight, 1e-6)))
        self.tb_tokens = self.tb_tokens.at[t].set(
            np.float32(burst if limited else 0.0))

    def clear_tenant_quota(self, tid: int):
        """Quota record deleted: the tenant reverts to unlimited."""
        self.set_tenant_quota(tid, 0.0, 0.0, 1.0)

    def _tenant_args(self):
        """The admission operands for a plan dispatch: a consistent
        device snapshot of (perm, sorted tenant, segment base),
        recomputed host-side only when the row->tenant map changed."""
        if self._tn_dirty:
            from .tenancy import tenant_order
            perm, ts, segbase = tenant_order(self._tenant_np)
            self._tn_perm = jnp.asarray(perm)
            self._tn_sorted = jnp.asarray(ts)
            self._tn_segbase = jnp.asarray(segbase)
            self._tn_dirty = False
        return dict(tn_perm=self._tn_perm, tn_sorted=self._tn_sorted,
                    tn_segbase=self._tn_segbase, tb_rate=self.tb_rate,
                    tb_burst=self.tb_burst, tb_limited=self.tb_limited,
                    tb_weight=self.tb_weight)

    def tenant_state(self) -> dict:
        """Host copies of the mutable tenant vectors (checkpoint
        capture).  Rate/burst/limited re-derive from the quota registry
        the scheduler checkpoints; tokens are the dynamic state."""
        return dict(tokens=np.asarray(self.tb_tokens))

    def set_tenant_state(self, tokens):
        """Install checkpointed token columns whole (restore path)."""
        self.tb_tokens = jnp.asarray(np.asarray(tokens, np.float32))

    def job_finished(self, node_col: int, cost: float):
        """Exclusive execution completed: release the capacity slot the
        solve reserved and retire its load."""
        self.rem_cap = self.rem_cap.at[node_col].add(1)
        self.load = self.load.at[node_col].add(-float(cost))

    def common_finished(self, node_col: int, cost: float):
        """Common (fan-out) execution completed: retire load only — Common
        runs never held a capacity slot."""
        self.load = self.load.at[node_col].add(-float(cost))

    def decay_load(self, factor: float = 0.99):
        self.load = self.load * factor

    def _impl(self, kx: int, kc: int) -> str:
        if self.impl != "auto":
            return self.impl
        from .assign import choose_impl
        return choose_impl(self.N, kx, kc)

    # -- the tick ----------------------------------------------------------

    def plan_async(self, epoch_s: int, sla_bucket: Optional[int] = None):
        """Dispatch one tick (a one-second window).  Does not synchronize —
        callers can pipeline several ticks and materialize with
        :meth:`gather`.  ``plan`` is the sync convenience."""
        return self.plan_window_async(epoch_s, 1, sla_bucket)

    def gather(self, handle) -> TickPlan:
        """Materialize a plan_async result (the single host transfer)."""
        return self.gather_window(handle)[0]

    def plan(self, epoch_s: int, sla_bucket: Optional[int] = None) -> TickPlan:
        """Fire + place every job due at ``epoch_s`` (one-second tick)."""
        return self.gather(self.plan_async(epoch_s, sla_bucket))

    # -- windowed planning -------------------------------------------------

    def plan_window_async(self, epoch_s: int, window_s: int,
                          sla_bucket: Optional[int] = None):
        """Dispatch one window of ``window_s`` consecutive seconds.

        ``sla_bucket`` pins both buckets: an int pins each to it, a
        (kx, kc) tuple pins them separately.

        Handles may be double-buffered: a second window may be
        dispatched before the first is gathered (the returned handle
        carries its own kx/kc and output futures; carried load/capacity
        state chains in dispatch order on device).  Dispatch must stay
        on ONE thread; gather may run on another."""
        from .schedule_table import FRAMEWORK_EPOCH
        from .timecal import window_fields
        if isinstance(sla_bucket, tuple):
            sla_x, sla_c = sla_bucket
        else:
            sla_x = sla_c = sla_bucket
        with self._bucket_mu:
            kx = self._bx.size(sla_x)
            kc = self._bc.size(sla_c)
        impl = self._impl(kx, kc)
        f = window_fields(epoch_s, window_s, tz=self.tz)
        fields_w = np.stack([
            f["sec"], f["min"], f["hour"], f["dom"], f["month"], f["dow"],
            np.arange(window_s, dtype=np.int64) + (epoch_s - FRAMEWORK_EPOCH),
        ], axis=1).astype(np.int32)                     # [W, 7]
        with jax.profiler.TraceAnnotation("cronsun.plan.dispatch"):
            # + 0.0 / | 0: the jit donates its load/rem_cap/last_fire
            # args, and the dispatch may run on the scheduler's dispatch
            # thread while the step thread scatters capacity/load
            # updates onto the SAME buffers — donating the live buffer
            # would leave the step holding a deleted one.  Donating a
            # fresh copy costs three [N]/[J] ops; a concurrently-landing
            # scatter can at worst be lost for one window, and the
            # scheduler's reconcile rewrites load/capacity absolutely
            # every step (dep epoch folds are monotone max — a lost
            # window re-applies at the next drain's scatter).
            tkw = {}
            if self._tenants_enabled:
                tkw = dict(self._tenant_args(),
                           tb_tokens=self.tb_tokens + 0.0,
                           use_tenants=True)
            outs32, outs16, outs_t, self.load, self.rem_cap, \
                self.dep_last_fire, tokens = _plan_window_step(
                    self.table, jnp.asarray(fields_w),
                    self.elig, self.exclusive, self.cost, self.load + 0.0,
                    self.rem_cap | 0, self.dep_succ, self.dep_fail,
                    self.dep_block, self.dep_last_fire | 0,
                    kx, kc, self.rounds, impl, self._dep_enabled, **tkw)
            # overflow-escalation replans (sla_bucket set) RE-plan
            # seconds whose refill/spend already advanced the carried
            # bucket: persisting a second pass would permanently drift
            # a throttled tenant below its quota (spend exceeds the
            # burst-clamped refill on exactly the herd seconds that
            # overflow) — replans read the bucket, never write it back
            if tokens is not None and sla_bucket is None:
                self.tb_tokens = tokens
        return epoch_s, kx, kc, outs32, outs16, outs_t

    def gather_window(self, handle):
        """Materialize a window dispatch into a list of TickPlans.

        Exclusive placements come first in ``fired``/``assigned``; Common
        fires follow with assigned = -1 (fan-out is the dispatcher's job).
        """
        epoch_s, kx, kc, outs32, outs16, outs_t = handle
        with jax.profiler.TraceAnnotation("cronsun.plan.gather"):
            # one tunnel transaction for all arrays
            o, oa, ot = jax.device_get((outs32, outs16, outs_t))
        plans = []
        W = o.shape[0]
        for w in range(W):
            xt, ct = int(o[w, 0]), int(o[w, 1])
            nx, nc = min(xt, kx), min(ct, kc)
            xidx = o[w, 2:2 + nx]
            assigned_x = oa[w, :nx].astype(np.int32)
            cidx = o[w, 2 + kx:2 + kx + nc]
            fired = np.concatenate([xidx, cidx])
            assigned = np.concatenate(
                [assigned_x, np.full(nc, -1, np.int32)])
            plans.append(TickPlan(
                epoch_s=epoch_s + w, fired=fired, assigned=assigned,
                overflow=max(0, xt - kx) + max(0, ct - kc),
                total_fired=xt + ct, n_excl=nx,
                tenant_throttled=(ot[w, 0] if ot is not None else None),
                tenant_shed=(ot[w, 1] if ot is not None else None)))
        if W:
            # adaptive sizing tracks each bucket's worst second; the shrink
            # hysteresis counts *ticks*, not calls.  Gather may run on the
            # pipeline's build worker while the step thread sizes the next
            # dispatch — the bucket lock keeps the counters coherent.
            with self._bucket_mu:
                self._bx.feed(int(o[:, 0].max()), W)
                self._bc.feed(int(o[:, 1].max()), W)
        return plans

    def plan_window(self, epoch_s: int, window_s: int,
                    sla_bucket: Optional[int] = None):
        return self.gather_window(
            self.plan_window_async(epoch_s, window_s, sla_bucket))

    def warm_window(self, epoch_s: int, window_s: int) -> None:
        """Compile (and cache) the windowed plan executable WITHOUT
        mutating carried state — warm standbys call this once so their
        first LEADING step doesn't pay the XLA compile (measured: tens
        of seconds of takeover outage at 1M-job shapes).  Bucket sizes
        are derived the same way a fresh leader's first plan would
        derive them, so the warmed executable is the one the takeover
        actually runs."""
        from .schedule_table import FRAMEWORK_EPOCH
        from .timecal import window_fields
        with self._bucket_mu:
            kx, kc = self._bx.peek(), self._bc.peek()
        impl = self._impl(kx, kc)
        f = window_fields(epoch_s, window_s, tz=self.tz)
        fields_w = np.stack([
            f["sec"], f["min"], f["hour"], f["dom"], f["month"], f["dow"],
            np.arange(window_s, dtype=np.int64)
            + (epoch_s - FRAMEWORK_EPOCH),
        ], axis=1).astype(np.int32)
        # + 0.0 / | 0: fresh buffers so the jit's donation can't
        # invalidate the planner's live load/rem_cap/last_fire
        outs32 = _plan_window_step(
            self.table, jnp.asarray(fields_w), self.elig, self.exclusive,
            self.cost, self.load + 0.0, self.rem_cap | 0, self.dep_succ,
            self.dep_fail, self.dep_block, self.dep_last_fire | 0, kx, kc,
            self.rounds, impl, self._dep_enabled, **self._warm_tkw())[0]
        np.asarray(outs32[0, 0])   # a data fetch truly syncs the tunnel

    def warm_escalation(self, epoch_s: int, factor: int = 4) -> int:
        """Compile the single-second overflow-replan executable at the
        escalated bucket a cron-herd burst will request (the scheduler's
        ``_replan_overflow`` plans W=1 at pow2(true fire count)).  The
        first minute-boundary herd otherwise pays this compile INSIDE a
        live step — measured as tens of seconds of p99 at 1M jobs.
        Returns the warmed bucket size."""
        from .schedule_table import FRAMEWORK_EPOCH
        from .timecal import window_fields
        with self._bucket_mu:
            k = min(_next_pow2(max(self._bx.peek(),
                                   self._bc.peek()) * factor),
                    self.J)
        impl = self._impl(k, k)
        f = window_fields(epoch_s, 1, tz=self.tz)
        fields_w = np.stack([
            f["sec"], f["min"], f["hour"], f["dom"], f["month"], f["dow"],
            np.asarray([epoch_s - FRAMEWORK_EPOCH], np.int64),
        ], axis=1).astype(np.int32)
        outs32 = _plan_window_step(
            self.table, jnp.asarray(fields_w), self.elig, self.exclusive,
            self.cost, self.load + 0.0, self.rem_cap | 0, self.dep_succ,
            self.dep_fail, self.dep_block, self.dep_last_fire | 0, k, k,
            self.rounds, impl, self._dep_enabled, **self._warm_tkw())[0]
        np.asarray(outs32[0, 0])
        self._warmed_single.add(k)
        return k

    def _warm_tkw(self) -> dict:
        """Tenant operands for the warm-compile paths: fresh token
        copies so the warm run can't mutate carried bucket state."""
        if not self._tenants_enabled:
            return {}
        return dict(self._tenant_args(),
                    tb_tokens=self.tb_tokens + 0.0, use_tenants=True)

    def snap_escalation(self, want: int) -> int:
        """Smallest warmed single-second bucket >= ``want``, else
        ``want`` itself — an oversized-but-compiled bucket beats a
        right-sized compile inside a live burst step."""
        cands = [s for s in self._warmed_single if s >= want]
        return min(cands) if cands else want
