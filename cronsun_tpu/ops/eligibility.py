"""Job x node eligibility: bitpacked placement masks.

The reference resolves placement per rule as
``include-node-ids ∪ (nodes of include-group-ids) − exclude-node-ids``
(web/job.go:244-253 — the correct subtractive semantics; the node-agent path
job.go:597-601,618-622 has a no-op exclude bug we deliberately do NOT
reproduce, see SURVEY.md §7).

On device the whole relation is one bitpacked matrix ``[J, ceil(N/32)]``
uint32 — 1M jobs x 10k nodes is ~1.25 GB of HBM instead of 10 GB of bools.
The matrix is built and patched host-side with vectorized numpy bit ops
(group edits touch only member rows, mirroring the reference's link index
node/group.go:9-82) and lives on device between ticks; per-tick traffic is
zero unless rules changed.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

__all__ = ["NodeUniverse", "pack_eligibility", "EligibilityBuilder"]


class NodeUniverse:
    """Stable node-id -> column-index mapping with fixed capacity.

    Columns are never reused while a node id is live; freed columns are
    recycled after explicit removal.  Fixed capacity keeps device shapes
    static across node churn.
    """

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.index: Dict[str, int] = {}
        self._free = list(range(capacity - 1, -1, -1))

    @property
    def n_words(self) -> int:
        return (self.capacity + 31) // 32

    def add(self, node_id: str) -> int:
        if node_id in self.index:
            return self.index[node_id]
        if not self._free:
            raise RuntimeError(f"node capacity {self.capacity} exhausted")
        col = self._free.pop()
        self.index[node_id] = col
        return col

    def remove(self, node_id: str) -> Optional[int]:
        col = self.index.pop(node_id, None)
        if col is not None:
            self._free.append(col)
        return col

    def cols(self, node_ids: Iterable[str]) -> List[int]:
        return [self.index[n] for n in node_ids if n in self.index]


def pack_bitmask(cols: Sequence[int], n_words: int) -> np.ndarray:
    """One bitpacked row: uint32[n_words] with the given column bits set."""
    row = np.zeros(n_words, dtype=np.uint32)
    if len(cols):
        c = np.asarray(cols, dtype=np.int64)
        np.bitwise_or.at(row, c // 32, (np.uint32(1) << (c % 32).astype(np.uint32)))
    return row


def pack_eligibility(include_cols: Sequence[int], group_rows: Sequence[np.ndarray],
                     exclude_cols: Sequence[int], n_words: int) -> np.ndarray:
    """Eligibility row for one job: (includes ∪ groups) − excludes.

    Empty includes and no groups means eligible nowhere — the reference's
    ``included()`` returns false when a rule names no nodes and no groups
    (job.go:274-288).
    """
    row = pack_bitmask(include_cols, n_words)
    for g in group_rows:
        row |= g
    row &= ~pack_bitmask(exclude_cols, n_words)
    return row


class EligibilityBuilder:
    """Incrementally maintained host mirror of the [J, W32] matrix.

    Tracks per-job rule inputs and per-group membership so a group edit
    rebuilds only the affected job rows (a reverse group->jobs index, like
    the reference's ``link`` map node/group.go:9-17).  Call :meth:`dirty_rows`
    to collect changed rows for a device scatter.
    """

    def __init__(self, universe: NodeUniverse, job_capacity: int):
        self.u = universe
        self.matrix = np.zeros((job_capacity, universe.n_words), dtype=np.uint32)
        self.job_rules: Dict[int, dict] = {}          # row -> rule inputs
        self.group_mask: Dict[str, np.ndarray] = {}   # gid -> packed row
        self.group_jobs: Dict[str, set] = {}          # gid -> {row}
        self._dirty: set = set()

    def set_group(self, gid: str, node_ids: Sequence[str]):
        self.group_mask[gid] = pack_bitmask(self.u.cols(node_ids), self.u.n_words)
        for row in self.group_jobs.get(gid, ()):  # rebuild member jobs
            self._rebuild(row)

    def del_group(self, gid: str):
        self.group_mask.pop(gid, None)
        # Keep the reverse index: member jobs still name the gid in their
        # rules, and must re-gain eligibility if the group id is recreated.
        for row in self.group_jobs.get(gid, set()).copy():
            self._rebuild(row)

    def set_job(self, row: int, include_nids: Sequence[str], gids: Sequence[str],
                exclude_nids: Sequence[str]):
        """Set one job row's rule inputs and rebuild its mask.

        OWNERSHIP TRANSFER: the three lists are stored by REFERENCE,
        not copied — the caller hands them over and must never mutate
        (or reuse) them afterwards, or eligibility rows silently
        corrupt without a rebuild.  Every current caller passes
        freshly-parsed rule lists (JobRule.from_dict allocates per
        document); the aliasing is deliberate — a copy per job was
        measurable at the 1M cold-load scale."""
        old = self.job_rules.get(row)
        if old:
            for g in old["gids"]:
                self.group_jobs.get(g, set()).discard(row)
        # lists are referenced, not copied: callers hand over freshly
        # parsed rule lists (JobRule.from_dict allocates per document),
        # and a copy per job was measurable at the 1M cold-load scale
        self.job_rules[row] = dict(nids=include_nids, gids=gids,
                                   ex=exclude_nids)
        for g in gids:
            self.group_jobs.setdefault(g, set()).add(row)
        self._rebuild(row)

    def del_job(self, row: int):
        old = self.job_rules.pop(row, None)
        if old:
            for g in old["gids"]:
                self.group_jobs.get(g, set()).discard(row)
        self.matrix[row] = 0
        self._dirty.add(row)

    def node_added(self, node_id: str):
        """New node: groups referencing it by id and jobs including it by id
        gain the column."""
        self.u.add(node_id)
        for row, r in self.job_rules.items():
            if node_id in r["nids"] or node_id in r["ex"]:
                self._rebuild(row)
        # group masks must be re-derived by the caller via set_group (it owns
        # the gid -> node_ids source of truth).

    def node_removed(self, node_id: str):
        """Node gone: free its column and scrub the bit everywhere, so a
        later recycled column never leaks old eligibility onto a new node."""
        col = self.u.remove(node_id)
        if col is None:
            return
        word, bit = col // 32, np.uint32(1 << (col % 32))
        for g in self.group_mask.values():
            g[word] &= ~bit
        affected = np.nonzero(self.matrix[:, word] & bit)[0]
        self.matrix[:, word] &= ~bit
        self._dirty.update(int(r) for r in affected)

    def _rebuild(self, row: int):
        r = self.job_rules.get(row)
        m = self.matrix
        if r is None:
            m[row] = 0
        elif not r["gids"] and not r["ex"]:
            # fast path — plain include list, the dominant fleet shape:
            # set bits directly in the matrix row instead of allocating
            # two scratch rows per job (pack_bitmask for includes AND
            # excludes was ~40% of the 1M cold load)
            m[row] = 0
            idx = self.u.index
            mrow = m[row]
            for n in r["nids"]:
                c = idx.get(n)
                if c is not None:
                    mrow[c >> 5] |= np.uint32(1 << (c & 31))
        else:
            groups = [self.group_mask[g] for g in r["gids"] if g in self.group_mask]
            m[row] = pack_eligibility(
                self.u.cols(r["nids"]), groups, self.u.cols(r["ex"]),
                self.u.n_words)
        self._dirty.add(row)

    def dirty_rows(self):
        """(rows, values) of changed rows since last call; resets the set."""
        rows = np.array(sorted(self._dirty), dtype=np.int32)
        self._dirty.clear()
        return rows, self.matrix[rows] if len(rows) else np.zeros((0, self.u.n_words), np.uint32)
