"""Pallas TPU kernels for the assignment solve's hot steps.

The jnp reference path materializes a [K, N] float score tile per bid round —
at 64k fired jobs x 10k nodes that's ~2.7 GB of HBM traffic per round, and the
solve is pure bandwidth.  These kernels keep the eligibility BITPACKED all the
way to the compute units: per job tile only the [TJ, W32] uint32 words ever
leave HBM (~30x less traffic), and unpacking happens in-register as a loop
over the 32 bit planes.

Layout trick: node ``n`` lives at (word w, bit b) with ``n = w*32 + b``.
Rather than unpacking to a [TJ, N] matrix (which needs an in-kernel reshape
across lanes), the kernel iterates b = 0..31; at each step
``(words >> b) & 1`` is a [TJ, W32] plane whose column w corresponds to node
``w*32+b``, so per-node operands (loads) are passed pre-transposed as
[32, W32] planes.  All plane ops are native VPU shapes.

Kernels:
- :func:`bid_argmin` — per job, min/argmin of (load + tie-hash) over its
  eligible open nodes.
- :func:`fanout_add` — per node, total cost of Common-kind fired jobs
  eligible there (an MXU [1,TJ]x[TJ,W32] matmul per bit plane).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import numpy as np

_HASH_A = np.uint32(2654435761)
_HASH_B = np.uint32(40503)
_HASH_C = np.uint32(2246822519)
_HASH_D = np.uint32(3266489917)
_TJ = 256  # job rows per program


def _tie(jix_u32, n_u32):
    """Deterministic per-(job, node) tie-break in [0, 1): multiply-xorshift."""
    h = (jix_u32 * _HASH_A) ^ (n_u32 * _HASH_B)
    h = h * _HASH_C
    h = h ^ (h >> 15)
    h = h * _HASH_D
    # uint32 -> int32 -> f32: Mosaic has no direct uint32->f32 cast, and the
    # value fits in 16 bits so the int32 detour is lossless.
    return (h >> 16).astype(jnp.int32).astype(jnp.float32) * (1.0 / 65536.0)


def _bid_kernel(packed_ref, load_t_ref, best_ref, choice_ref):
    tj, w32 = packed_ref.shape
    packed = packed_ref[:]                                   # [TJ, W32] u32
    base = pl.program_id(0) * tj
    jix = (base + jax.lax.broadcasted_iota(jnp.int32, (tj, w32), 0)
           ).astype(jnp.uint32)
    wix = jax.lax.broadcasted_iota(jnp.int32, (tj, w32), 1)

    best = jnp.full((tj,), jnp.inf, jnp.float32)
    choice = jnp.zeros((tj,), jnp.int32)
    # Unrolled over the 32 bit planes: Mosaic has no dynamic_slice, so the
    # plane index must be static (constant shifts + static row reads).
    for b in range(32):
        bits = ((packed >> np.uint32(b)) & 1) != 0           # [TJ, W32]
        n_ix = (wix * 32 + b).astype(jnp.uint32)
        score = jnp.where(bits, load_t_ref[b, :][None, :] + _tie(jix, n_ix),
                          jnp.inf)
        m = jnp.min(score, axis=1)                           # [TJ]
        a = jnp.argmin(score, axis=1).astype(jnp.int32) * 32 + b
        better = m < best
        best = jnp.where(better, m, best)
        choice = jnp.where(better, a, choice)
    best_ref[0, :] = best
    choice_ref[0, :] = choice


@functools.partial(jax.jit, static_argnames=("interpret",))
def bid_argmin(packed: jax.Array, load_eff: jax.Array, interpret: bool = False):
    """Per-job best node by load.

    Args:
      packed: [K, W32] uint32 eligibility rows (K % 256 == 0).
      load_eff: [N] f32 effective load per node (+inf for closed/dead nodes),
        N == W32 * 32.
    Returns:
      (best [K] f32 — min load+tie, inf if no eligible open node;
       choice [K] int32 — argmin node column).
    """
    K, w32 = packed.shape
    n = w32 * 32
    if K % _TJ:
        raise ValueError(f"K={K} must be a multiple of {_TJ}")
    load_t = load_eff.reshape(w32, 32).T                     # [32, W32]
    grid = (K // _TJ,)
    best, choice = pl.pallas_call(
        _bid_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TJ, w32), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((32, w32), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, _TJ), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _TJ), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, K), jnp.float32),
            jax.ShapeDtypeStruct((1, K), jnp.int32),
        ],
        interpret=interpret,
    )(packed, load_t)
    return best.reshape(K), choice.reshape(K)


def _fanout_kernel(packed_ref, w_ref, out_ref):
    tj, w32 = packed_ref.shape
    packed = packed_ref[:]
    w = w_ref[0, :][None, :]                                 # [1, TJ]

    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    rows = []
    for b in range(32):
        bits = (((packed >> np.uint32(b)) & 1) != 0).astype(jnp.float32)  # [TJ, W32]
        contrib = jax.lax.dot_general(
            w, bits, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [1, W32]
        rows.append(contrib)
    out_ref[:] = out_ref[:] + jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fanout_add(packed: jax.Array, weights: jax.Array, interpret: bool = False):
    """Per-node total weight of jobs eligible there: out[n] = sum_j w_j*bit(j,n).

    Args:
      packed: [K, W32] uint32; weights: [K] f32 (0 for non-participating jobs).
    Returns: [N] f32 additive load contribution.
    """
    K, w32 = packed.shape
    if K % _TJ:
        raise ValueError(f"K={K} must be a multiple of {_TJ}")
    grid = (K // _TJ,)
    out_t = pl.pallas_call(
        _fanout_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TJ, w32), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _TJ), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((32, w32), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((32, w32), jnp.float32),
        interpret=interpret,
    )(packed, weights.reshape(1, K))
    return out_t.T.reshape(w32 * 32)
