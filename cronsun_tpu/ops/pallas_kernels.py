"""Pallas TPU kernels for the assignment solve's hot steps.

The jnp reference path materializes a [K, N] float score tile per bid round —
at 64k fired jobs x 10k nodes that's ~2.7 GB of HBM traffic per round, and the
solve is pure bandwidth.  These kernels keep the eligibility BITPACKED all the
way to the compute units: per job tile only the [TJ, W32] uint32 words ever
leave HBM (~30x less traffic), and unpacking happens in-register as a loop
over the 32 bit planes.

Layout trick: node ``n`` lives at (word w, bit b) with ``n = w*32 + b``.
Rather than unpacking to a [TJ, N] matrix (which needs an in-kernel reshape
across lanes), the kernel iterates b = 0..31; at each step
``(words >> b) & 1`` is a [TJ, W32] plane whose column w corresponds to node
``w*32+b``, so per-node operands (loads) are passed pre-transposed as
[32, W32] planes.  All plane ops are native VPU shapes.

Both kernels tile the NODE axis as well (``_TW`` words per program) and
accumulate across node tiles in their output blocks — without this the
whole [TJ, W32] row must fit scoped VMEM, which OOMs around N ≈ 64k
(measured: 20.8 MB needed vs the 16 MB limit at N = 102400).  Wide-fleet
support is the reason these kernels exist: the jnp path's [K, N] f32
scores are outright infeasible there (16k x 102k ≈ 6.7 GB per round).

Kernels:
- :func:`bid_argmin` — per job, min/argmin of (load + tie-hash) over its
  eligible open nodes.
- :func:`fanout_add` — per node, total cost of Common-kind fired jobs
  eligible there (an MXU [1,TJ]x[TJ,W32] matmul per bit plane).

When to use which: on v5e the MXU-heavy jnp path measures ~equal or
faster up to ~10k nodes (bench.py ``kernel_bid_*_ms`` re-measures every
round); the bit-plane kernels win where the unpacked matrix stops
fitting.  ``impl="auto"`` encodes that threshold (ops/planner.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import numpy as np

_HASH_A = np.uint32(2654435761)
_HASH_B = np.uint32(40503)
_HASH_C = np.uint32(2246822519)
_HASH_D = np.uint32(3266489917)
_TJ = 256   # job rows per program
_TW = 512   # node words per program (16384 nodes); bounds scoped VMEM


def _tie(jix_u32, n_u32):
    """Deterministic per-(job, node) tie-break in [0, 1): multiply-xorshift."""
    h = (jix_u32 * _HASH_A) ^ (n_u32 * _HASH_B)
    h = h * _HASH_C
    h = h ^ (h >> 15)
    h = h * _HASH_D
    # uint32 -> int32 -> f32: Mosaic has no direct uint32->f32 cast, and the
    # value fits in 16 bits so the int32 detour is lossless.
    return (h >> 16).astype(jnp.int32).astype(jnp.float32) * (1.0 / 65536.0)


def _bid_kernel(packed_ref, load_t_ref, best_ref, choice_ref):
    tj, tw32 = packed_ref.shape
    packed = packed_ref[:]                                   # [TJ, TW32] u32
    base = pl.program_id(0) * tj
    col0 = pl.program_id(1) * tw32                           # word offset
    jix = (base + jax.lax.broadcasted_iota(jnp.int32, (tj, tw32), 0)
           ).astype(jnp.uint32)
    wix = col0 + jax.lax.broadcasted_iota(jnp.int32, (tj, tw32), 1)

    # node tiles accumulate into the output block (resident across the
    # inner grid axis); tile 0 initializes
    @pl.when(pl.program_id(1) == 0)
    def _():
        best_ref[:] = jnp.full(best_ref.shape, jnp.inf, jnp.float32)
        choice_ref[:] = jnp.zeros(choice_ref.shape, jnp.int32)

    best = best_ref[0, :]
    choice = choice_ref[0, :]

    def prio(c):
        # exact-score ties resolve by (bit plane, word) — the order the
        # single-tile kernel scanned in and _bid_jnp reproduces; node id
        # c = w*32 + b maps to comparable priority (b << 17) | w
        # (w < 2^17 covers 4M nodes)
        return ((c & 31) << 17) | jax.lax.shift_right_logical(c, 5)

    # Unrolled over the 32 bit planes: Mosaic has no dynamic_slice, so the
    # plane index must be static (constant shifts + static row reads).
    for b in range(32):
        bits = ((packed >> np.uint32(b)) & 1) != 0           # [TJ, TW32]
        n_ix = (wix * 32 + b).astype(jnp.uint32)
        score = jnp.where(bits, load_t_ref[b, :][None, :] + _tie(jix, n_ix),
                          jnp.inf)
        m = jnp.min(score, axis=1)                           # [TJ]
        a = ((col0 + jnp.argmin(score, axis=1)).astype(jnp.int32)) * 32 + b
        better = (m < best) | ((m == best) & (prio(a) < prio(choice)))
        best = jnp.where(better, m, best)
        choice = jnp.where(better, a, choice)
    best_ref[0, :] = best
    choice_ref[0, :] = choice


def _pad_words(arr2d, tw: int):
    """Pad the word axis (last dim) to a multiple of tw with zeros
    (zero words = no eligible nodes there — semantics-neutral)."""
    w32 = arr2d.shape[-1]
    pad = (-w32) % tw
    if pad:
        arr2d = jnp.pad(arr2d, ((0, 0), (0, pad)))
    return arr2d, w32 + pad


@functools.partial(jax.jit, static_argnames=("interpret",))
def bid_argmin(packed: jax.Array, load_eff: jax.Array, interpret: bool = False):
    """Per-job best node by load.

    Args:
      packed: [K, W32] uint32 eligibility rows (K % 256 == 0).
      load_eff: [N] f32 effective load per node (+inf for closed/dead nodes),
        N == W32 * 32.
    Returns:
      (best [K] f32 — min load+tie, inf if no eligible open node;
       choice [K] int32 — argmin node column).
    """
    K, w32 = packed.shape
    if K % _TJ:
        raise ValueError(f"K={K} must be a multiple of {_TJ}")
    tw = min(_TW, w32)
    packed, w32p = _pad_words(packed, tw)
    load_t = load_eff.reshape(w32, 32).T                     # [32, W32]
    # the load pad value (0.0) is irrelevant: padded PACKED words are
    # zero bits, so the where() emits +inf for every padded column —
    # eligibility, not load, is what protects the pad
    load_t, _ = _pad_words(load_t, tw)
    grid = (K // _TJ, w32p // tw)
    best, choice = pl.pallas_call(
        _bid_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TJ, tw), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((32, tw), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, _TJ), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _TJ), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, K), jnp.float32),
            jax.ShapeDtypeStruct((1, K), jnp.int32),
        ],
        interpret=interpret,
    )(packed, load_t)
    return best.reshape(K), choice.reshape(K)


def _fanout_kernel(packed_ref, w_ref, out_ref):
    tj, tw32 = packed_ref.shape
    packed = packed_ref[:]
    w = w_ref[0, :][None, :]                                 # [1, TJ]

    @pl.when(pl.program_id(1) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    rows = []
    for b in range(32):
        bits = (((packed >> np.uint32(b)) & 1) != 0).astype(jnp.float32)  # [TJ, TW32]
        contrib = jax.lax.dot_general(
            w, bits, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [1, TW32]
        rows.append(contrib)
    out_ref[:] = out_ref[:] + jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fanout_add(packed: jax.Array, weights: jax.Array, interpret: bool = False):
    """Per-node total weight of jobs eligible there: out[n] = sum_j w_j*bit(j,n).

    Args:
      packed: [K, W32] uint32; weights: [K] f32 (0 for non-participating jobs).
    Returns: [N] f32 additive load contribution.
    """
    K, w32 = packed.shape
    if K % _TJ:
        raise ValueError(f"K={K} must be a multiple of {_TJ}")
    tw = min(_TW, w32)
    packed, w32p = _pad_words(packed, tw)
    # grid order: node tile OUTER, job tile INNER — each out block stays
    # resident while every job tile accumulates into it
    grid = (w32p // tw, K // _TJ)
    out_t = pl.pallas_call(
        _fanout_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TJ, tw), lambda j, i: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _TJ), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((32, tw), lambda j, i: (0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((32, w32p), jnp.float32),
        interpret=interpret,
    )(packed, weights.reshape(1, K))
    return out_t.T.reshape(w32p * 32)[:w32 * 32]
