"""Workflow DAG plane: dependency-trigger evaluation for the batched tick.

A dep-triggered job "fires the tick after ALL upstream columns' success
epochs pass its own last-fire epoch".  The upstream references live as a
CSR-style padded column block in the packed :class:`ScheduleTable`
(``dep_cols`` [J, MAX_DEPS], see ops/schedule_table.py); the mutable
per-row state lives beside the planner's load/capacity vectors:

- ``succ``/``fail`` [J] int32 — latest completed round's SCHEDULED epoch
  (framework-relative) per outcome, folded from the store's ``dep/``
  completion events by the scheduler (monotone max, so multi-node
  Common completions and replayed watch events are idempotent);
- ``last_fire`` [J] int32 — the epoch this dep row last fired (or
  consumed a skipped round); carried THROUGH the window scan so a row
  fires once per upstream round, not once per window second;
- ``block`` [J] bool — host-computed max_in_flight saturation gate.

:func:`dep_ready` is one masked gather + compare over the padded block —
it composes into the planner's fused window scan (ops/planner.py) as a
handful of elementwise ops per second, no graph walk, and is compiled
OUT entirely (``use_deps`` static arg) while no dep rows exist, keeping
dep-free tables bit-identical to the pre-DAG program.

Misfire semantics per upstream round (``dep_policy``):

- POLICY_FIRE: any completed round (success or failure) satisfies;
- POLICY_HOLD: only success satisfies — a failed round parks the job
  until a later success arrives;
- POLICY_SKIP (default): a round where every upstream completed but at
  least one upstream's LATEST outcome is a failure is CONSUMED
  (last_fire advances, no fire) — the chain re-arms on the next round.

A round whose scheduled epoch predates the downstream's last fire
coalesces into it (epochs are compared, not counted): upstreams that
complete slower than they are scheduled collapse their backlog into one
downstream fire.

:class:`ReferenceDagEvaluator` is the pure-Python spec of the same
semantics, used by the randomized differential test in tests/test_dag.py.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set, Tuple

import numpy as np

from .schedule_table import DEP_EMPTY

POLICY_SKIP = 0
POLICY_FIRE = 1
POLICY_HOLD = 2

POLICY_BY_NAME = {"skip": POLICY_SKIP, "fire": POLICY_FIRE,
                  "hold": POLICY_HOLD}
POLICY_NAMES = {v: k for k, v in POLICY_BY_NAME.items()}

# "never completed" sentinel for the success/fail epoch vectors: below
# any real framework-relative epoch and any last_fire anchor
NEVER = int(np.iinfo(np.int32).min)


def dep_ready(table, succ, fail, block, last_fire):
    """[J] dep-trigger decisions at one instant: ``(fire, consume,
    round_max)``.

    Pure jnp — traced inside the planner's jitted window scan.  A slot is
    satisfied when it is padding (DEP_EMPTY) or its upstream's epoch
    passed ``last_fire``; DEP_BROKEN slots never satisfy.  ``consume``
    marks POLICY_SKIP rows whose round completed with a failure: the
    caller advances last_fire without firing.  ``round_max`` is the
    newest upstream epoch the decision consumed: the caller advances
    last_fire to ``max(tick, round_max)`` so a round whose scheduled
    epoch runs AHEAD of the firing tick (clock skew, compressed virtual
    time) is consumed whole instead of re-satisfying every later tick —
    one fire per visible backlog, never one per window."""
    import jax.numpy as jnp
    cols = table.dep_cols                           # [J, D]
    valid = cols >= 0
    up = jnp.maximum(cols, 0)
    s = succ[up]                                    # [J, D]
    f = fail[up]
    latest = jnp.maximum(s, f)
    lf = last_fire[:, None]
    pad_ok = cols == DEP_EMPTY                      # DEP_BROKEN stays False
    sat_succ = jnp.where(valid, s > lf, pad_ok)
    sat_any = jnp.where(valid, latest > lf, pad_ok)
    all_succ = jnp.all(sat_succ, axis=1)
    all_any = jnp.all(sat_any, axis=1)
    # an upstream's round "ended in failure" iff its latest outcome is a
    # failure newer than both our last fire and its own latest success
    has_fail = jnp.any(valid & (f > lf) & (f > s), axis=1)
    live = (table.has_dep & jnp.any(valid, axis=1)
            & table.active & ~table.paused & ~block)
    pol = table.dep_policy
    fire = jnp.where(pol == POLICY_FIRE, all_any,
                     jnp.where(pol == POLICY_HOLD, all_succ,
                               all_any & ~has_fail))
    consume = (pol == POLICY_SKIP) & all_any & has_fail
    round_max = jnp.max(jnp.where(valid, latest, NEVER), axis=1)
    return fire & live, consume & live, round_max


class ReferenceDagEvaluator:
    """Pure-Python reference of the dep-trigger semantics (the
    differential-test oracle and the plain-language spec).

    ``deps``: {row: (upstream_cols, policy)} where upstream_cols entries
    are table rows or DEP_BROKEN; rows absent from ``deps`` never
    dep-fire.  Epoch state mirrors the device vectors."""

    def __init__(self, deps: Dict[int, Tuple[List[int], int]],
                 last_fire: Dict[int, int] = None):
        self.deps = {r: (list(c), p) for r, (c, p) in deps.items()}
        self.succ: Dict[int, int] = {}
        self.fail: Dict[int, int] = {}
        self.last_fire: Dict[int, int] = dict(last_fire or {})
        self.blocked: Set[int] = set()

    def complete(self, row: int, epoch: int, ok: bool):
        """Fold one completion event (monotone max, like the device)."""
        d = self.succ if ok else self.fail
        d[row] = max(d.get(row, NEVER), epoch)

    def tick(self, t: int, live_rows: Iterable[int] = None) -> List[int]:
        """Dep fires at instant ``t`` (sorted rows); advances last_fire
        for fires AND consumed skip-policy rounds."""
        PF, PH, PS = POLICY_FIRE, POLICY_HOLD, POLICY_SKIP
        fired = []
        for row, (cols, pol) in sorted(self.deps.items()):
            if live_rows is not None and row not in live_rows:
                continue
            if row in self.blocked or not cols:
                continue
            lf = self.last_fire.get(row, 0)
            sat_succ = sat_any = True
            has_fail = False
            round_max = NEVER
            for c in cols:
                if c == DEP_EMPTY:
                    continue
                if c < 0:                       # DEP_BROKEN
                    sat_succ = sat_any = False
                    break
                s = self.succ.get(c, NEVER)
                f = self.fail.get(c, NEVER)
                sat_succ &= s > lf
                sat_any &= max(s, f) > lf
                has_fail |= f > lf and f > s
                round_max = max(round_max, s, f)
            if pol == PF:
                fire, consume = sat_any, False
            elif pol == PH:
                fire, consume = sat_succ, False
            else:
                assert pol == PS
                fire = sat_any and not has_fail
                consume = sat_any and has_fail
            if fire:
                fired.append(row)
            if fire or consume:
                # consume the whole visible backlog (see dep_ready)
                self.last_fire[row] = max(t, round_max)
        return fired
