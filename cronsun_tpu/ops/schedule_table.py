"""Device-resident batched schedule table.

A compiled cron spec is six uint64 bitmasks (reference: node/cron/spec.go:7-9).
On TPU the native integer width is 32 bits, so each 64-bit mask is stored as a
(lo, hi) uint32 pair and the star bits (bit 63, node/cron/spec.go:48-51) are
hoisted into separate bool columns — they only matter for the day-of-month vs
day-of-week OR/AND rule (node/cron/spec.go:149-158).

``@every`` schedules (node/cron/constantdelay.go) are held in the same table
as (period, phase) rows: a job fires when
``(t - phase) mod period == 0``.  Phase is anchored at registration time, so
the fire train matches the reference's chained ``prev + period`` behaviour as
long as no window is skipped; unlike the reference, a lagging scheduler does
not shift the phase (deliberate divergence — deterministic fire instants).

All epoch arithmetic is relative to :data:`FRAMEWORK_EPOCH` (2020-01-01 UTC)
so device-side seconds fit int32 until 2088 without enabling x64.

Tables are fixed-capacity: allocate for ``capacity`` jobs, mark live rows with
``active``; row churn from watch deltas is in-place buffer donation, never a
reshape, so XLA never recompiles on job add/remove (SURVEY.md §7 "incremental
updates without recompile").
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.models import MAX_DEPS
from ..cron.parser import CronSpec, EverySpec, parse

# 2020-01-01T00:00:00Z — device times are int32 seconds relative to this.
FRAMEWORK_EPOCH = 1577836800

_MASK32 = (1 << 32) - 1
_STAR_OFF = ~(1 << 63)  # strip star bit before splitting

# dependency-column sentinels (the [capacity, MAX_DEPS] dep_cols block):
# >= 0 is the upstream job's table row; DEP_EMPTY pads unused slots
# (always satisfied); DEP_BROKEN marks an unresolvable upstream (job
# missing / no rows) — never satisfied, so the row holds instead of
# firing dep-less.
DEP_EMPTY = -1
DEP_BROKEN = -2


def _split64(mask: int) -> "tuple[int, int]":
    m = mask & _STAR_OFF
    return m & _MASK32, (m >> 32) & _MASK32


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ScheduleTable:
    """Struct-of-arrays schedule batch; every field is shape [capacity]."""

    sec_lo: jax.Array   # uint32
    sec_hi: jax.Array   # uint32 (bits 32..59)
    min_lo: jax.Array   # uint32
    min_hi: jax.Array   # uint32
    hour: jax.Array     # uint32 (bits 0..23)
    dom: jax.Array      # uint32 (bits 1..31)
    month: jax.Array    # uint32 (bits 1..12)
    dow: jax.Array      # uint32 (bits 0..6)
    dom_star: jax.Array  # bool
    dow_star: jax.Array  # bool
    is_every: jax.Array  # bool
    period: jax.Array    # int32, >=1 always (1 for cron rows: no div-by-zero)
    phase_mod: jax.Array  # int32, phase mod period (framework-epoch relative)
    active: jax.Array    # bool — live row
    paused: jax.Array    # bool — Job.Pause (reference job.go:53)
    # workflow DAG plane: the padded dependency matrix beside the cron
    # masks.  has_dep marks dep-triggered rows (their cron masks are
    # empty); dep_cols is the [capacity, MAX_DEPS] upstream-row block
    # (DEP_EMPTY pads, DEP_BROKEN never satisfies); dep_policy is the
    # misfire policy (POLICY_* in ops/deps.py).  Success/fail epochs and
    # the last-fire vector are PLANNER state (they mutate on watch
    # events / inside the scan), not table rows.
    has_dep: jax.Array   # bool
    dep_policy: jax.Array  # int32 (POLICY_SKIP/FIRE/HOLD)
    dep_cols: jax.Array    # int32 [capacity, MAX_DEPS]
    # multi-tenant control plane: small tenant id per row (0 = the
    # default, never-limited tenant).  The admission pass itself runs
    # off the planner's host-snapshotted permutation (ops/tenancy.py),
    # so this column is the durable row->tenant record (it rides
    # checkpoints with the table) rather than a per-tick operand.
    tenant: jax.Array      # int32
    # herd smearing: per-row deterministic jitter width in seconds
    # (0..300, 0 = fire exactly at the matched second).  The device tick
    # never reads this column — the smear delta is evaluated on the host
    # at plan emission (sched/service.py) from the cached per-row FNV
    # state, so the lowered program is identical whether or not any row
    # sets jitter.  Riding the table means checkpoints carry it for
    # free, exactly like ``tenant``.
    jitter: jax.Array      # int32

    @property
    def capacity(self) -> int:
        return self.sec_lo.shape[0]


_NO_DEPS = (DEP_EMPTY,) * MAX_DEPS


def make_row(spec: Union[CronSpec, EverySpec, str], phase_epoch_s: int = 0,
             paused: bool = False, tenant: int = 0,
             jitter: int = 0) -> dict:
    """Host-side row dict for one spec (strings are parsed)."""
    if isinstance(spec, str):
        spec = parse(spec)
    if isinstance(spec, EverySpec):
        period = max(1, spec.period_s)
        return dict(
            sec_lo=0, sec_hi=0, min_lo=0, min_hi=0, hour=0, dom=0, month=0,
            dow=0, dom_star=False, dow_star=False, is_every=True,
            period=period,
            phase_mod=int((phase_epoch_s - FRAMEWORK_EPOCH) % period),
            active=True, paused=paused,
            has_dep=False, dep_policy=0, dep_cols=_NO_DEPS, tenant=tenant,
            jitter=int(jitter))
    sec_lo, sec_hi = _split64(spec.second)
    min_lo, min_hi = _split64(spec.minute)
    return dict(
        sec_lo=sec_lo, sec_hi=sec_hi, min_lo=min_lo, min_hi=min_hi,
        hour=spec.hour & _MASK32, dom=spec.dom & _MASK32,
        month=spec.month & _MASK32, dow=spec.dow & _MASK32,
        dom_star=spec.dom_star, dow_star=spec.dow_star,
        is_every=False, period=1, phase_mod=0, active=True, paused=paused,
        has_dep=False, dep_policy=0, dep_cols=_NO_DEPS, tenant=tenant,
        jitter=int(jitter))


def make_dep_row(upstream_rows, policy: int, paused: bool = False,
                 tenant: int = 0) -> dict:
    """Row dict for a dep-triggered job: cron masks empty (the row never
    time-fires), dep columns padded to MAX_DEPS with DEP_EMPTY.
    ``upstream_rows`` entries are table rows or DEP_BROKEN for
    unresolvable upstreams."""
    ups = list(upstream_rows)[:MAX_DEPS]
    cols = tuple(ups) + (DEP_EMPTY,) * (MAX_DEPS - len(ups))
    row = dict(_INACTIVE_ROW)
    row.update(active=True, paused=paused, has_dep=True,
               dep_policy=int(policy), dep_cols=cols, tenant=int(tenant))
    return row


_DTYPES = dict(
    sec_lo=np.uint32, sec_hi=np.uint32, min_lo=np.uint32, min_hi=np.uint32,
    hour=np.uint32, dom=np.uint32, month=np.uint32, dow=np.uint32,
    dom_star=np.bool_, dow_star=np.bool_, is_every=np.bool_,
    period=np.int32, phase_mod=np.int32, active=np.bool_, paused=np.bool_,
    has_dep=np.bool_, dep_policy=np.int32, dep_cols=np.int32,
    tenant=np.int32, jitter=np.int32,
)

# per-field trailing shape beyond [capacity] (only the dep matrix is 2-D)
_SHAPES = {"dep_cols": (MAX_DEPS,)}

_INACTIVE_ROW = dict(
    sec_lo=0, sec_hi=0, min_lo=0, min_hi=0, hour=0, dom=0, month=0, dow=0,
    dom_star=False, dow_star=False, is_every=False, period=1, phase_mod=0,
    active=False, paused=False,
    has_dep=False, dep_policy=0, dep_cols=_NO_DEPS, tenant=0, jitter=0)


def build_table(specs: List[Union[CronSpec, EverySpec, str]],
                capacity: Optional[int] = None,
                phase_epoch_s: int = 0,
                paused: Optional[List[bool]] = None,
                device=None, sharding=None) -> ScheduleTable:
    """Compile a list of specs into a device ScheduleTable.

    ``capacity`` pads the table (inactive rows) to a fixed size; defaults to
    the next power of two >= len(specs) so later growth rarely re-allocates.
    """
    n = len(specs)
    if capacity is None:
        capacity = max(1, 1 << (n - 1).bit_length()) if n else 1
    if capacity < n:
        raise ValueError(f"capacity {capacity} < {n} specs")
    cols = {k: np.full((capacity, *_SHAPES.get(k, ())),
                       DEP_EMPTY if k == "dep_cols" else _INACTIVE_ROW[k],
                       dtype=dt)
            for k, dt in _DTYPES.items()}
    for i, spec in enumerate(specs):
        row = make_row(spec, phase_epoch_s=phase_epoch_s,
                       paused=bool(paused[i]) if paused else False)
        for k, v in row.items():
            cols[k][i] = v
    if sharding is not None:
        arrs = {k: jax.device_put(v, sharding) for k, v in cols.items()}
    elif device is not None:
        arrs = {k: jax.device_put(v, device) for k, v in cols.items()}
    else:
        arrs = {k: jnp.asarray(v) for k, v in cols.items()}
    return ScheduleTable(**arrs)


def update_rows(table: ScheduleTable, indices: np.ndarray,
                rows: List[dict]) -> ScheduleTable:
    """Functionally update rows at ``indices`` (watch-delta path).

    Scatter at fixed shapes — no recompile, and under jit with donated
    buffers this is an in-place update.
    """
    idx = jnp.asarray(np.asarray(indices, dtype=np.int32))
    new = {}
    for k, dt in _DTYPES.items():
        vals = jnp.asarray(np.array([r[k] for r in rows], dtype=dt))
        new[k] = getattr(table, k).at[idx].set(vals)
    return ScheduleTable(**new)


def deactivate_rows(table: ScheduleTable, indices: np.ndarray) -> ScheduleTable:
    return update_rows(table, indices, [_INACTIVE_ROW] * len(indices))
