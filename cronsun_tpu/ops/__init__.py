"""Device-side batched scheduling kernels.

The decision core of the framework: every per-node ``Schedule.Next()`` walk in
the reference (node/cron/spec.go:55-145, node/cron/cron.go:210-275) collapses
into batched JAX programs over dense schedule tables:

- :mod:`timecal` — host-side calendar decomposition (epoch seconds -> cron
  field indices), vectorized for fixed-offset timezones.
- :mod:`schedule_table` — compiled ``CronSpec``/``EverySpec`` batches as
  device-resident struct-of-arrays bitmask tables.
- :mod:`tick` — windowed fire-mask evaluation and batched next-fire.
- :mod:`eligibility` — bitpacked job x node placement masks.
- :mod:`assign` — load-balanced capacity-constrained job->node assignment.
"""

from .schedule_table import ScheduleTable, FRAMEWORK_EPOCH  # noqa: F401
from .tick import fire_mask, next_fire  # noqa: F401
