"""Windowed fire-mask evaluation and batched next-fire.

Replaces the reference's per-entry sequential walk: the cron loop's
``e.Next = e.Schedule.Next(now)`` + O(n log n) sort per tick
(node/cron/cron.go:210-275, node/cron/spec.go:55-145) become one fused
elementwise program over the whole schedule table:

- :func:`fire_mask` — [J, W] bool: which jobs fire at which window instant.
  Pure bit tests against the mask table; XLA fuses the six field tests, the
  DOM/DOW star rule and the ``@every`` modular test into one pass over HBM.
- :func:`next_fire` — batched ``Schedule.Next`` for every job at once:
  a partial-minute second-granularity pass, then escalating minute-granularity
  window chunks (a cron row with a nonempty seconds mask fires in a minute iff
  its min/hour/day/month fields match; the first second is the mask's lowest
  set bit), host-fallback free.  Gives up past a 5-year horizon exactly like
  the reference (node/cron/spec.go:70-75).

All scans are data-independent dense windows — no data-dependent control flow
inside jit; the escalation loop lives on the host.
"""

from __future__ import annotations

import datetime as _dt
from datetime import timezone
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .schedule_table import FRAMEWORK_EPOCH, ScheduleTable
from .timecal import window_fields

_UTC = timezone.utc

# The reference gives up a Next() search after five years (spec.go:70-75).
FIVE_YEARS_S = 5 * 366 * 86400


def _bit60(lo: jax.Array, hi: jax.Array, idx: jax.Array) -> jax.Array:
    """Test bit ``idx`` (0..59) of a (lo, hi) uint32 pair.

    lo/hi are [J], idx is [W]; result [J, W] bool.  Shift amounts are clamped
    to stay in-range (XLA leaves >=width shifts undefined).
    """
    idx = idx[None, :]
    lo_sh = jnp.minimum(idx, 31).astype(jnp.uint32)
    hi_sh = jnp.minimum(jnp.maximum(idx - 32, 0), 31).astype(jnp.uint32)
    lo_bit = (lo[:, None] >> lo_sh) & 1
    hi_bit = (hi[:, None] >> hi_sh) & 1
    return jnp.where(idx < 32, lo_bit, hi_bit) != 0


def _bit32(mask: jax.Array, idx: jax.Array) -> jax.Array:
    """Test bit ``idx`` (0..31) of uint32 mask; [J] x [W] -> [J, W] bool."""
    sh = jnp.minimum(idx[None, :], 31).astype(jnp.uint32)
    return ((mask[:, None] >> sh) & 1) != 0


def _day_ok(t: ScheduleTable, dom_idx: jax.Array, dow_idx: jax.Array) -> jax.Array:
    """DOM/DOW star semantics (node/cron/spec.go:149-158)."""
    dom_ok = _bit32(t.dom, dom_idx)
    dow_ok = _bit32(t.dow, dow_idx)
    either_star = (t.dom_star | t.dow_star)[:, None]
    return jnp.where(either_star, dom_ok & dow_ok, dom_ok | dow_ok)


def _every_rem(t: ScheduleTable, t_rel: jax.Array) -> jax.Array:
    """Seconds until the next @every fire at each instant: [J, W] int32.

    0 means "fires exactly at this instant"."""
    period = t.period[:, None]
    return jnp.mod(t.phase_mod[:, None] - t_rel[None, :], period)


@jax.jit
def _fire_mask_jit(t: ScheduleTable, sec, mnt, hour, dom, month, dow, t_rel):
    cron_ok = (
        _bit60(t.sec_lo, t.sec_hi, sec)
        & _bit60(t.min_lo, t.min_hi, mnt)
        & _bit32(t.hour, hour)
        & _day_ok(t, dom, dow)
        & _bit32(t.month, month)
    )
    every_ok = _every_rem(t, t_rel) == 0
    live = (t.active & ~t.paused)[:, None]
    return live & jnp.where(t.is_every[:, None], every_ok, cron_ok)


def fire_mask(table: ScheduleTable, start_epoch_s: int, window_s: int = 1,
              tz=_UTC) -> jax.Array:
    """[J, window_s] bool: fire decisions for every job over the window of
    seconds [start, start + window_s), wall-decomposed in ``tz``.

    Fires are evaluated at the LOGICAL (cron-matched) second; the
    ``table.jitter`` column is deliberately unread here — herd smearing
    is a host-side shift applied at plan emission (sched/service.py), so
    the lowered program is byte-identical whether or not any row sets
    jitter."""
    f = window_fields(start_epoch_s, window_s, step_s=1, tz=tz)
    t_rel = np.arange(window_s, dtype=np.int64) + (start_epoch_s - FRAMEWORK_EPOCH)
    return _fire_mask_jit(table, jnp.asarray(f["sec"]), jnp.asarray(f["min"]),
                          jnp.asarray(f["hour"]), jnp.asarray(f["dom"]),
                          jnp.asarray(f["month"]), jnp.asarray(f["dow"]),
                          jnp.asarray(t_rel.astype(np.int32)))


@jax.jit
def first_fire_offset(fire_jw: jax.Array):
    """First true offset per row, and whether any exists: ([J] int32, [J] bool)."""
    any_fire = jnp.any(fire_jw, axis=1)
    return jnp.argmax(fire_jw, axis=1).astype(jnp.int32), any_fire


def _ctz64(lo: jax.Array, hi: jax.Array) -> jax.Array:
    """Count trailing zeros of a (lo, hi) uint32 pair; 64 when empty."""
    def ctz32(x):
        lowest = x & (jnp.zeros_like(x) - x)
        return jnp.where(x == 0, 32,
                         jax.lax.population_count(lowest - 1).astype(jnp.int32))
    lo_z = ctz32(lo)
    return jnp.where(lo != 0, lo_z, 32 + ctz32(hi)).astype(jnp.int32)


def _ctz32(x: jax.Array) -> jax.Array:
    lowest = x & (jnp.zeros_like(x) - x)
    return jnp.where(x == 0, 32,
                     jax.lax.population_count(lowest - 1).astype(jnp.int32))


_SEC_PAD = 64      # padded partial-minute window
_MIN_PAD = 3072    # padded minute window (through end of tomorrow, any DST)
_DAY_PAD = 1856    # padded day window (5-year horizon)


# THE single definition of the packed host->device buffer layout: pack
# (next_fire) and unpack (_next_fire_packed) both iterate this, so a
# field reorder cannot silently desynchronize the two sides (all slices
# within a size group share a shape — a drift would be invisible to
# shape checks).
_PACK_LAYOUT = (
    (_SEC_PAD, ("s_sec", "s_min", "s_hour", "s_dom", "s_month", "s_dow",
                "s_rel", "s_ok")),
    (_MIN_PAD, ("m_min", "m_hour", "m_dom", "m_month", "m_dow",
                "m_rel", "m_ok")),
    (_DAY_PAD, ("d_dom", "d_month", "d_dow", "d_rel", "d_ok")),
)


@jax.jit
def _next_fire_packed(t: ScheduleTable, packed, t_rel_start):
    """Unpack the single host->device field buffer and run the fused
    next-fire pass.  One upload instead of twenty: each small transfer
    pays its own latency on a network-tunneled chip, and the whole
    buffer is ~124 KB — measured, this cuts next_fire's wall time ~30%
    through the tunnel (and to one transfer on a local chip)."""
    f = {}
    off = 0
    for size, names in _PACK_LAYOUT:
        for name in names:
            f[name] = jax.lax.slice(packed, (off,), (off + size,))
            off += size
    return _next_fire_fused(
        t, f["s_sec"], f["s_min"], f["s_hour"], f["s_dom"], f["s_month"],
        f["s_dow"], f["s_rel"], f["s_ok"].astype(bool),
        f["m_min"], f["m_hour"], f["m_dom"], f["m_month"], f["m_dow"],
        f["m_rel"], f["m_ok"].astype(bool),
        f["d_dom"], f["d_month"], f["d_dow"], f["d_rel"],
        f["d_ok"].astype(bool), t_rel_start)


@jax.jit
def _next_fire_fused(t: ScheduleTable,
                     s_sec, s_min, s_hour, s_dom, s_month, s_dow, s_rel, s_ok,
                     m_min, m_hour, m_dom, m_month, m_dow, m_rel, m_ok,
                     d_dom, d_month, d_dow, d_rel, d_ok,
                     t_rel_start):
    """ONE dispatch resolving Schedule.Next for every row (SURVEY §7's
    sparse-schedule hard part, done without escalating windows):

    - @every rows: pure modular arithmetic — no scan at all.
    - cron rows, three granularities, coarse-to-fine coverage:
      1. the partial first minute at second granularity ([J, 64]);
      2. minute granularity through the end of tomorrow ([J, ~3k]) — a
         row matches a minute iff min/hour/day/month match; the fire
         second within it is the seconds-mask's lowest bit;
      3. day granularity over the whole 5-year horizon ([J, ~1.8k]) — a
         row matches a day iff dom/month/dow match, and its first fire
         time-of-day is STATIC (lowest hour/min/sec bits), so no finer
         scan is ever needed.
    Returns [J] int32 framework-relative fire seconds, -1 = no fire in
    horizon (the reference's zero time, spec.go:70-75).
    """
    live = t.active & ~t.paused

    # 1) seconds within the partial first minute: full six-field test
    fire_s = (
        _bit60(t.sec_lo, t.sec_hi, s_sec)
        & _bit60(t.min_lo, t.min_hi, s_min)
        & _bit32(t.hour, s_hour)
        & _day_ok(t, s_dom, s_dow)
        & _bit32(t.month, s_month)
    ) & s_ok[None, :]
    any_s = jnp.any(fire_s, axis=1)
    res_s = s_rel[jnp.argmax(fire_s, axis=1)]

    # first fire second / time-of-day per row (static per row)
    sec0 = jnp.minimum(_ctz64(t.sec_lo, t.sec_hi), 59)
    tod = (_ctz32(t.hour) * 3600
           + jnp.minimum(_ctz64(t.min_lo, t.min_hi), 59) * 60 + sec0)

    # 2) minute granularity through end of tomorrow
    match_m = (
        _bit60(t.min_lo, t.min_hi, m_min)
        & _bit32(t.hour, m_hour)
        & _day_ok(t, m_dom, m_dow)
        & _bit32(t.month, m_month)
    ) & m_ok[None, :]
    any_m = jnp.any(match_m, axis=1)
    res_m = m_rel[jnp.argmax(match_m, axis=1)] + sec0

    # 3) day granularity over the horizon
    match_d = (_day_ok(t, d_dom, d_dow) & _bit32(t.month, d_month)
               ) & d_ok[None, :]
    any_d = jnp.any(match_d, axis=1)
    res_d = d_rel[jnp.argmax(match_d, axis=1)] + tod

    res_cron = jnp.where(any_s, res_s,
                         jnp.where(any_m, res_m,
                                   jnp.where(any_d, res_d, -1)))
    # @every: closed form
    rem = jnp.mod(t.phase_mod - t_rel_start, t.period)
    res_every = t_rel_start + rem
    res = jnp.where(t.is_every, res_every, res_cron)
    return jnp.where(live, res, -1), jnp.where(live & ~t.is_every & ~any_s
                                               & ~any_m & any_d,
                                               jnp.argmax(match_d, axis=1),
                                               -1)


def _pad_fields(f: dict, n: int, pad: int):
    """Pad field arrays to a static width with never-matching values
    (month 0 has no bit in any month mask; dow 7 in no dow mask)."""
    out = {}
    for k, v in f.items():
        fill = {"month": 0, "dow": 7, "dom": 0}.get(k, 0)
        out[k] = np.concatenate(
            [v[:n], np.full(pad - min(n, len(v)), fill, np.int32)])
    ok = np.zeros(pad, bool)
    ok[:n] = True
    return out, ok


def next_fire(table: ScheduleTable, after_epoch_s: int, tz=_UTC,
              horizon_s: int = FIVE_YEARS_S,
              chunk_minutes: Optional[int] = None) -> np.ndarray:
    """Batched Schedule.Next: for every job, the first fire instant strictly
    after ``after_epoch_s``.  Returns [J] int64 epoch seconds; -1 where no
    fire occurs within ``horizon_s`` (the reference's zero time).

    One fused device dispatch regardless of schedule sparsity (see
    :func:`_next_fire_fused`); ``chunk_minutes`` is accepted for backward
    compatibility and ignored.  In DST zones, rows resolved by the
    day-granularity scan onto a transition day are re-verified host-side
    with the scalar engine (wall instants shift around the transition).
    """
    del chunk_minutes
    start = after_epoch_s + 1
    t_rel_start = start - FRAMEWORK_EPOCH
    boundary = (start // 60 + 1) * 60
    w0 = boundary - start

    # window shapes (host): partial minute, minutes to end of tomorrow,
    # days across the horizon
    from .timecal import tz_fixed_offset_seconds
    off = tz_fixed_offset_seconds(tz)
    if off is not None:
        day0 = ((boundary + off) // 86400 + 2) * 86400 - off   # day after tomorrow, local midnight
        n_min = (day0 - boundary) // 60
        n_day = min(_DAY_PAD, (horizon_s + 86399) // 86400)
        day_starts = day0 + 86400 * np.arange(n_day, dtype=np.int64)
    else:
        # local midnight of the day after tomorrow, then one local
        # midnight per day (zoneinfo resolves each across transitions)
        loc = _dt.datetime.fromtimestamp(boundary, tz)
        d0 = _dt.datetime(loc.year, loc.month, loc.day) + _dt.timedelta(days=2)
        n_day = min(_DAY_PAD, (horizon_s + 86399) // 86400)
        starts = []
        cur = d0
        for _ in range(n_day):
            starts.append(cur.replace(tzinfo=tz).timestamp())
            cur += _dt.timedelta(days=1)
        day_starts = np.asarray(starts, np.int64)
        n_min = int((day_starts[0] - boundary) // 60)

    sf = window_fields(start, min(w0, _SEC_PAD) or 1, tz=tz)
    sf, s_ok = _pad_fields(sf, w0, _SEC_PAD)
    s_rel = (start + np.arange(_SEC_PAD, dtype=np.int64)
             - FRAMEWORK_EPOCH).astype(np.int32)

    n_min = min(n_min, _MIN_PAD)
    mf = window_fields(boundary, n_min, step_s=60, tz=tz)
    mf, m_ok = _pad_fields(mf, n_min, _MIN_PAD)
    m_rel = (boundary + 60 * np.arange(_MIN_PAD, dtype=np.int64)
             - FRAMEWORK_EPOCH).astype(np.int32)

    dfields = {"dom": np.empty(0, np.int32), "month": np.empty(0, np.int32),
               "dow": np.empty(0, np.int32)}
    if n_day:
        _, _, _, d_dom, d_month, d_dow = _decompose_days(day_starts, tz)
        dfields = {"dom": d_dom, "month": d_month, "dow": d_dow}
    df, d_ok = _pad_fields(dfields, n_day, _DAY_PAD)
    d_rel = np.zeros(_DAY_PAD, np.int64)
    d_rel[:n_day] = day_starts - FRAMEWORK_EPOCH
    d_rel = d_rel.astype(np.int32)

    fields = {
        "s_sec": sf["sec"], "s_min": sf["min"], "s_hour": sf["hour"],
        "s_dom": sf["dom"], "s_month": sf["month"], "s_dow": sf["dow"],
        "s_rel": s_rel, "s_ok": s_ok,
        "m_min": mf["min"], "m_hour": mf["hour"], "m_dom": mf["dom"],
        "m_month": mf["month"], "m_dow": mf["dow"],
        "m_rel": m_rel, "m_ok": m_ok,
        "d_dom": df["dom"], "d_month": df["month"], "d_dow": df["dow"],
        "d_rel": d_rel, "d_ok": d_ok,
    }
    packed = np.concatenate([
        np.asarray(fields[name], np.int32)
        for size, names in _PACK_LAYOUT for name in names])
    res_rel, day_idx = _next_fire_packed(table, jnp.asarray(packed),
                                         np.int32(t_rel_start))
    res_rel = np.asarray(res_rel).astype(np.int64)
    result = np.where(res_rel < 0, -1, res_rel + FRAMEWORK_EPOCH)

    if off is None:
        _fix_dst_days(table, result, np.asarray(day_idx), day_starts, tz)

    # The fused pass scans _DAY_PAD days; an explicit horizon beyond that
    # continues in further day-window chunks (rare — only multi-year
    # horizons with still-unresolved sparse cron rows pay this).
    days_done = n_day
    # int32 framework-relative seconds bound the scan to ~2088; 20 years
    # is already 4x the reference's give-up horizon (spec.go:70-75)
    horizon_days = min((horizon_s + 86399) // 86400, 20 * 366)
    # the row masks live on device; fetching them costs a link round
    # trip each, so they materialize only if the continuation loop is
    # actually entered (at the default 5-year horizon it never is —
    # the fused pass already scanned _DAY_PAD >= horizon days)
    is_cron = live = None
    while days_done < horizon_days:
        if is_cron is None:
            is_cron = ~np.asarray(table.is_every)
            live = np.asarray(table.active & ~table.paused)
        unresolved = (result < 0) & is_cron & live
        if not unresolved.any():
            break
        nd = min(_DAY_PAD, horizon_days - days_done)
        if off is not None:
            chunk_starts = day_starts[0] + 86400 * np.arange(
                days_done, days_done + nd, dtype=np.int64)
        else:
            cur = _dt.datetime.fromtimestamp(int(day_starts[-1]), tz)
            base = _dt.datetime(cur.year, cur.month, cur.day) \
                + _dt.timedelta(days=days_done - n_day + 1)
            starts = []
            c = base
            for _ in range(nd):
                starts.append(c.replace(tzinfo=tz).timestamp())
                c += _dt.timedelta(days=1)
            chunk_starts = np.asarray(starts, np.int64)
        _, _, _, cd_dom, cd_month, cd_dow = _decompose_days(chunk_starts, tz)
        cdf, cd_ok = _pad_fields(
            {"dom": cd_dom, "month": cd_month, "dow": cd_dow}, nd, _DAY_PAD)
        cd_rel = np.zeros(_DAY_PAD, np.int64)
        cd_rel[:nd] = chunk_starts - FRAMEWORK_EPOCH
        found, res_rel2, idx2 = _day_scan_jit(
            table, jnp.asarray(cdf["dom"]), jnp.asarray(cdf["month"]),
            jnp.asarray(cdf["dow"]), jnp.asarray(cd_rel.astype(np.int32)),
            jnp.asarray(cd_ok))
        found = np.asarray(found); res_rel2 = np.asarray(res_rel2)
        hit = unresolved & found
        result[hit] = res_rel2[hit].astype(np.int64) + FRAMEWORK_EPOCH
        if off is None:
            di = np.where(hit, np.asarray(idx2), -1)
            _fix_dst_days(table, result, di, chunk_starts, tz)
        days_done += nd

    # horizon clip (@every with huge periods / last chunk can exceed it)
    result = np.where(result > after_epoch_s + horizon_s, -1, result)
    return result


@jax.jit
def _day_scan_jit(t: ScheduleTable, d_dom, d_month, d_dow, d_rel, d_ok):
    """Day-granularity continuation chunk: first matching day + the row's
    static first time-of-day (see :func:`_next_fire_fused` step 3)."""
    match_d = (_day_ok(t, d_dom, d_dow) & _bit32(t.month, d_month)
               ) & d_ok[None, :]
    any_d = jnp.any(match_d, axis=1)
    idx = jnp.argmax(match_d, axis=1)
    sec0 = jnp.minimum(_ctz64(t.sec_lo, t.sec_hi), 59)
    tod = (_ctz32(t.hour) * 3600
           + jnp.minimum(_ctz64(t.min_lo, t.min_hi), 59) * 60 + sec0)
    return any_d, d_rel[idx] + tod, idx.astype(jnp.int32)


def _decompose_days(day_starts: np.ndarray, tz):
    """Civil fields for local-midnight day starts (noon probe avoids DST
    edge effects on the date itself)."""
    from .timecal import tz_fixed_offset_seconds, decompose_utc
    off = tz_fixed_offset_seconds(tz)
    if off is not None:
        return decompose_utc(day_starts + 43200, off)
    dom = np.empty(len(day_starts), np.int32)
    month = np.empty(len(day_starts), np.int32)
    dow = np.empty(len(day_starts), np.int32)
    for i, s in enumerate(day_starts):
        loc = _dt.datetime.fromtimestamp(int(s) + 43200, tz)
        dom[i] = loc.day
        month[i] = loc.month
        dow[i] = (loc.weekday() + 1) % 7
    return None, None, None, dom, month, dow


def _fix_dst_days(table: ScheduleTable, result: np.ndarray,
                  day_idx: np.ndarray, day_starts: np.ndarray, tz):
    """Rows the day scan resolved onto a DST-transition day get an exact
    scalar re-walk (static time-of-day arithmetic assumes 86400-s days)."""
    if not len(day_starts):
        return
    lengths = np.diff(np.concatenate([day_starts, day_starts[-1:] + 86400]))
    affected = np.nonzero((day_idx >= 0)
                          & (lengths[np.clip(day_idx, 0, len(lengths) - 1)]
                             != 86400))[0]
    if not len(affected):
        return
    from ..cron.parser import CronSpec, STAR_BIT
    from ..cron.schedule import Schedule
    sec_lo = np.asarray(table.sec_lo); sec_hi = np.asarray(table.sec_hi)
    min_lo = np.asarray(table.min_lo); min_hi = np.asarray(table.min_hi)
    hour = np.asarray(table.hour); dom = np.asarray(table.dom)
    month = np.asarray(table.month); dow = np.asarray(table.dow)
    dom_star = np.asarray(table.dom_star); dow_star = np.asarray(table.dow_star)
    for j in affected:
        spec = CronSpec(
            second=int(sec_lo[j]) | int(sec_hi[j]) << 32,
            minute=int(min_lo[j]) | int(min_hi[j]) << 32,
            hour=int(hour[j]), month=int(month[j]),
            dom=int(dom[j]) | (STAR_BIT if dom_star[j] else 0),
            dow=int(dow[j]) | (STAR_BIT if dow_star[j] else 0))
        t0 = _dt.datetime.fromtimestamp(int(day_starts[day_idx[j]]) - 1, tz)
        nxt = Schedule(spec).next(t0)
        result[j] = -1 if nxt is None else int(nxt.timestamp())


def next_fire_one(table: ScheduleTable, job_index: int, after_epoch_s: int,
                  tz=_UTC) -> Optional[int]:
    """Convenience: next fire for one row (None if unsatisfiable)."""
    r = next_fire(table, after_epoch_s, tz=tz)
    v = int(r[job_index])
    return None if v < 0 else v
