"""Windowed fire-mask evaluation and batched next-fire.

Replaces the reference's per-entry sequential walk: the cron loop's
``e.Next = e.Schedule.Next(now)`` + O(n log n) sort per tick
(node/cron/cron.go:210-275, node/cron/spec.go:55-145) become one fused
elementwise program over the whole schedule table:

- :func:`fire_mask` — [J, W] bool: which jobs fire at which window instant.
  Pure bit tests against the mask table; XLA fuses the six field tests, the
  DOM/DOW star rule and the ``@every`` modular test into one pass over HBM.
- :func:`next_fire` — batched ``Schedule.Next`` for every job at once:
  a partial-minute second-granularity pass, then escalating minute-granularity
  window chunks (a cron row with a nonempty seconds mask fires in a minute iff
  its min/hour/day/month fields match; the first second is the mask's lowest
  set bit), host-fallback free.  Gives up past a 5-year horizon exactly like
  the reference (node/cron/spec.go:70-75).

All scans are data-independent dense windows — no data-dependent control flow
inside jit; the escalation loop lives on the host.
"""

from __future__ import annotations

import datetime as _dt
from datetime import timezone
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .schedule_table import FRAMEWORK_EPOCH, ScheduleTable
from .timecal import window_fields

_UTC = timezone.utc

# The reference gives up a Next() search after five years (spec.go:70-75).
FIVE_YEARS_S = 5 * 366 * 86400


def _bit60(lo: jax.Array, hi: jax.Array, idx: jax.Array) -> jax.Array:
    """Test bit ``idx`` (0..59) of a (lo, hi) uint32 pair.

    lo/hi are [J], idx is [W]; result [J, W] bool.  Shift amounts are clamped
    to stay in-range (XLA leaves >=width shifts undefined).
    """
    idx = idx[None, :]
    lo_sh = jnp.minimum(idx, 31).astype(jnp.uint32)
    hi_sh = jnp.minimum(jnp.maximum(idx - 32, 0), 31).astype(jnp.uint32)
    lo_bit = (lo[:, None] >> lo_sh) & 1
    hi_bit = (hi[:, None] >> hi_sh) & 1
    return jnp.where(idx < 32, lo_bit, hi_bit) != 0


def _bit32(mask: jax.Array, idx: jax.Array) -> jax.Array:
    """Test bit ``idx`` (0..31) of uint32 mask; [J] x [W] -> [J, W] bool."""
    sh = jnp.minimum(idx[None, :], 31).astype(jnp.uint32)
    return ((mask[:, None] >> sh) & 1) != 0


def _day_ok(t: ScheduleTable, dom_idx: jax.Array, dow_idx: jax.Array) -> jax.Array:
    """DOM/DOW star semantics (node/cron/spec.go:149-158)."""
    dom_ok = _bit32(t.dom, dom_idx)
    dow_ok = _bit32(t.dow, dow_idx)
    either_star = (t.dom_star | t.dow_star)[:, None]
    return jnp.where(either_star, dom_ok & dow_ok, dom_ok | dow_ok)


def _every_rem(t: ScheduleTable, t_rel: jax.Array) -> jax.Array:
    """Seconds until the next @every fire at each instant: [J, W] int32.

    0 means "fires exactly at this instant"."""
    period = t.period[:, None]
    return jnp.mod(t.phase_mod[:, None] - t_rel[None, :], period)


@jax.jit
def _fire_mask_jit(t: ScheduleTable, sec, mnt, hour, dom, month, dow, t_rel):
    cron_ok = (
        _bit60(t.sec_lo, t.sec_hi, sec)
        & _bit60(t.min_lo, t.min_hi, mnt)
        & _bit32(t.hour, hour)
        & _day_ok(t, dom, dow)
        & _bit32(t.month, month)
    )
    every_ok = _every_rem(t, t_rel) == 0
    live = (t.active & ~t.paused)[:, None]
    return live & jnp.where(t.is_every[:, None], every_ok, cron_ok)


def fire_mask(table: ScheduleTable, start_epoch_s: int, window_s: int = 1,
              tz=_UTC) -> jax.Array:
    """[J, window_s] bool: fire decisions for every job over the window of
    seconds [start, start + window_s), wall-decomposed in ``tz``."""
    f = window_fields(start_epoch_s, window_s, step_s=1, tz=tz)
    t_rel = np.arange(window_s, dtype=np.int64) + (start_epoch_s - FRAMEWORK_EPOCH)
    return _fire_mask_jit(table, jnp.asarray(f["sec"]), jnp.asarray(f["min"]),
                          jnp.asarray(f["hour"]), jnp.asarray(f["dom"]),
                          jnp.asarray(f["month"]), jnp.asarray(f["dow"]),
                          jnp.asarray(t_rel.astype(np.int32)))


@jax.jit
def first_fire_offset(fire_jw: jax.Array):
    """First true offset per row, and whether any exists: ([J] int32, [J] bool)."""
    any_fire = jnp.any(fire_jw, axis=1)
    return jnp.argmax(fire_jw, axis=1).astype(jnp.int32), any_fire


def _ctz64(lo: jax.Array, hi: jax.Array) -> jax.Array:
    """Count trailing zeros of a (lo, hi) uint32 pair; 64 when empty."""
    def ctz32(x):
        lowest = x & (jnp.zeros_like(x) - x)
        return jnp.where(x == 0, 32,
                         jax.lax.population_count(lowest - 1).astype(jnp.int32))
    lo_z = ctz32(lo)
    return jnp.where(lo != 0, lo_z, 32 + ctz32(hi)).astype(jnp.int32)


@jax.jit
def _minute_scan_jit(t: ScheduleTable, mnt, hour, dom, month, dow, m_rel):
    """Minute-granularity matching over Wm minute boundaries.

    A cron row matches a minute iff min/hour/day/month match (its seconds mask
    is nonempty by construction, so some second in the minute fires).  An
    @every row matches iff its remainder at the minute start is < 60.

    Returns (found [J] bool, minute_idx [J] int32, sec_in_minute [J] int32).
    """
    cron_ok = (
        _bit60(t.min_lo, t.min_hi, mnt)
        & _bit32(t.hour, hour)
        & _day_ok(t, dom, dow)
        & _bit32(t.month, month)
    )
    rem = _every_rem(t, m_rel)
    every_ok = rem < 60
    live = (t.active & ~t.paused)[:, None]
    match = live & jnp.where(t.is_every[:, None], every_ok, cron_ok)
    found = jnp.any(match, axis=1)
    idx = jnp.argmax(match, axis=1).astype(jnp.int32)
    sec_cron = _ctz64(t.sec_lo, t.sec_hi)
    sec_every = jnp.take_along_axis(rem, idx[:, None], axis=1)[:, 0]
    sec = jnp.where(t.is_every, sec_every, jnp.minimum(sec_cron, 59))
    return found, idx, sec.astype(jnp.int32)


def next_fire(table: ScheduleTable, after_epoch_s: int, tz=_UTC,
              horizon_s: int = FIVE_YEARS_S,
              chunk_minutes: Optional[int] = None) -> np.ndarray:
    """Batched Schedule.Next: for every job, the first fire instant strictly
    after ``after_epoch_s``.  Returns [J] int64 epoch seconds; -1 where no
    fire occurs within ``horizon_s`` (the reference's zero time).

    ``chunk_minutes`` defaults to an element budget: wide chunks for small
    tables (fewer host round-trips on sparse schedules), narrow for huge
    ones (bounded [J, W] intermediate).
    """
    J = table.capacity
    if chunk_minutes is None:
        chunk_minutes = max(1024, min(16384, (1 << 28) // max(J, 1)))
    result = np.full(J, -1, dtype=np.int64)
    active = np.asarray(table.active & ~table.paused)
    unresolved = active.copy()
    if not unresolved.any():
        return result

    start = after_epoch_s + 1
    # 1) Partial first minute, second granularity.
    boundary = (start // 60 + 1) * 60
    w = boundary - start
    if w > 0:
        fire = fire_mask(table, start, w, tz=tz)
        off, any_f = first_fire_offset(fire)
        off = np.asarray(off); any_f = np.asarray(any_f)
        hit = unresolved & any_f
        result[hit] = start + off[hit]
        unresolved &= ~hit
    # 2) Escalating minute-granularity chunks.
    m0 = boundary
    limit = after_epoch_s + horizon_s
    while unresolved.any() and m0 < limit:
        f = window_fields(m0, chunk_minutes, step_s=60, tz=tz)
        m_rel = (np.arange(chunk_minutes, dtype=np.int64) * 60
                 + (m0 - FRAMEWORK_EPOCH)).astype(np.int32)
        found, idx, sec = _minute_scan_jit(
            table, jnp.asarray(f["min"]), jnp.asarray(f["hour"]),
            jnp.asarray(f["dom"]), jnp.asarray(f["month"]),
            jnp.asarray(f["dow"]), jnp.asarray(m_rel))
        found = np.asarray(found); idx = np.asarray(idx); sec = np.asarray(sec)
        hit = unresolved & found
        result[hit] = m0 + idx[hit] * 60 + sec[hit]
        unresolved &= ~hit
        m0 += chunk_minutes * 60
    return result


def next_fire_one(table: ScheduleTable, job_index: int, after_epoch_s: int,
                  tz=_UTC) -> Optional[int]:
    """Convenience: next fire for one row (None if unsatisfiable)."""
    r = next_fire(table, after_epoch_s, tz=tz)
    v = int(r[job_index])
    return None if v < 0 else v
