"""Domain core: jobs, rules, groups, nodes, accounts, key layout.

The Python analogue of the reference's root package (Job/Group/Node/Process/
JobLog/Account + etcd key helpers).  Storage-agnostic: models serialize to
JSON and live in the coordination store under the same key layout as the
reference (SURVEY.md appendix).
"""

from .errors import (  # noqa: F401
    CronsunError, NotFound, SecurityInvalid, ValidationError)
from .ids import next_id  # noqa: F401
from .keyspace import Keyspace  # noqa: F401
from .models import (  # noqa: F401
    Account, DepSpec, Group, Job, JobRule, KIND_ALONE, KIND_COMMON,
    KIND_INTERVAL, MAX_DEPS, MISFIRE_POLICIES, Node, ROLE_ADMIN,
    ROLE_DEVELOPER, TenantQuota, validate_dag)
