"""Sentinel errors (reference: errors.go:5-20)."""


class CronsunError(Exception):
    pass


class NotFound(CronsunError):
    pass


class ValidationError(CronsunError):
    pass


class SecurityInvalid(ValidationError):
    """Command/user rejected by the security policy (reference
    job.go:633-656)."""
