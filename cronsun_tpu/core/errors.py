"""Sentinel errors (reference: errors.go:5-20)."""


class CronsunError(Exception):
    pass


class NotFound(CronsunError):
    pass


class ValidationError(CronsunError):
    pass


class SecurityInvalid(ValidationError):
    """Command/user rejected by the security policy (reference
    job.go:633-656)."""


class DuplicateNode(CronsunError):
    """A live agent with this node identity is already registered
    (reference node.go:51-79: PID signal-0 probe on register)."""
