"""One retry/backoff schedule for the whole plane.

Before this module the repo carried four hand-rolled copies of the same
exponential ladder — the store client's reconnect loop
(store/remote.py), the agents' record-flush retry slot (node/agent.py),
the noticer's delivery queue (noticer.py), and the publisher's chunk
retry (sched/publisher.py) — each with its own base/cap constants and
its own off-by-one convention.  Ladders that drift silently are a
robustness hazard: a base that shrinks 2x halves outage coverage, a cap
that grows 2x doubles recovery latency, and nothing fails until a real
outage measures it.  This module is the single definition; the chaos
bench and a pinning unit test (tests/test_chaos.py) keep every consumer
on the published schedule.

Schedules are DETERMINISTIC by default (``jitter=0``): the fault drills
must replay byte-identically under a fixed seed.  Consumers that fan
out across a fleet (reconnect herds) can opt into jitter; the RNG is
then seeded explicitly so a drill's schedule is still reproducible.
"""

from __future__ import annotations

import random
import time
from typing import Iterator, Optional


class Backoff:
    """Exponential backoff schedule: ``delay(n)`` for the n-th
    consecutive failure (1-based) is ``min(cap, base * factor**(n-1))``,
    plus up to ``jitter`` fraction of that value when jitter is enabled.

    Instances are immutable descriptions of a schedule; per-retry state
    (the attempt counter) lives with the caller, which keeps one shared
    instance safe across threads.
    """

    __slots__ = ("base", "cap", "factor", "jitter", "_rng")

    def __init__(self, base: float, cap: float, factor: float = 2.0,
                 jitter: float = 0.0, seed: Optional[int] = None):
        if base <= 0 or cap < base or factor < 1.0:
            raise ValueError(
                f"bad backoff schedule: base={base} cap={cap} "
                f"factor={factor}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.base = base
        self.cap = cap
        self.factor = factor
        self.jitter = jitter
        # explicit seed -> reproducible jitter (the chaos drills); no
        # seed -> process-local randomness for production herd spreading
        self._rng = random.Random(seed) if jitter else None

    def delay(self, attempt: int) -> float:
        """Delay before retry ``attempt`` (1-based: the wait after the
        first failure is ``delay(1) == base``).  The exponent is
        clamped: consumers retry UNBOUNDED (a reconnect loop during an
        hours-long outage reaches attempt counts where a float pow
        raises OverflowError — which would kill the very heal thread
        the ladder exists for), and past ~64 doublings every real
        schedule sits at its cap anyway."""
        if attempt < 1:
            attempt = 1
        d = min(self.cap, self.base * self.factor ** min(attempt - 1, 64))
        if self._rng is not None:
            d += d * self.jitter * self._rng.random()
        return d

    def delays(self, max_attempts: int) -> Iterator[float]:
        """The first ``max_attempts`` delays, in order."""
        for n in range(1, max_attempts + 1):
            yield self.delay(n)

    def sleep(self, attempt: int,
              sleep_fn=time.sleep) -> float:
        """Sleep out retry ``attempt``'s delay; returns the delay."""
        d = self.delay(attempt)
        sleep_fn(d)
        return d


# ---------------------------------------------------------------------------
# The plane's published ladders.  These constants are LOAD-BEARING:
# tests/test_chaos.py pins the exact schedules so a consumer can't
# drift away silently.  Change them here, with the test, on purpose.
# ---------------------------------------------------------------------------

#: Store client reconnect (store/remote.py _heal): fast first probe, a
#: couple of doublings, then steady 2 s — a dead store is repolled
#: briskly without a thundering reconnect herd.
RECONNECT = Backoff(base=0.2, cap=2.0)

#: Record-flush retry slot (node/agent.py): 0.5 s .. 10 s between
#: attempts.  With rec_flush_max_fails=30 this covers a ~4-5 minute
#: sink outage before a batch is declared lost.
REC_FLUSH = Backoff(base=0.5, cap=10.0)

#: Noticer delivery retries (noticer.py): alerts re-send briskly at
#: first, then settle to one attempt per 30 s for long SMTP outages.
NOTICER = Backoff(base=0.5, cap=30.0)

#: Publish chunk retries (sched/publisher.py): 4 attempts inside one
#: window's budget — 0.2/0.4/0.8/1.6 s — before the window records a
#: hole and the cursor rewinds.
PUBLISH = Backoff(base=0.2, cap=2.0)
PUBLISH_ATTEMPTS = 4

#: ctl ``logs --follow`` stream reconnects (bin/ctl.py): a transient
#: SSE disconnect resumes from the follower's cursor on 0.5 s .. 30 s,
#: jittered up to 50% — a fleet of followers dropped by one replica
#: restart must not reconnect as a herd.  Unseeded on purpose: nothing
#: replays this ladder, and herd spreading wants real randomness.
SSE_RECONNECT = Backoff(base=0.5, cap=30.0, jitter=0.5)
