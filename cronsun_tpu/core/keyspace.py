"""Key layout — identical shape to the reference's etcd keyspace
(SURVEY.md appendix; conf normalizes the prefixes, conf/conf.go:124-157),
plus the new ``dispatch`` prefix: the central planner's per-node execution
orders, which replace the per-node cron loops.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Keyspace:
    prefix: str = "/cronsun"

    @property
    def cmd(self) -> str:        # job JSON, /cmd/<group>/<jobID>
        return f"{self.prefix}/cmd/"

    @property
    def node(self) -> str:       # node liveness, /node/<id> (leased)
        return f"{self.prefix}/node/"

    @property
    def proc(self) -> str:       # running executions (leased)
        return f"{self.prefix}/proc/"

    @property
    def once(self) -> str:       # run-now triggers
        return f"{self.prefix}/once/"

    @property
    def lock(self) -> str:       # execution fence tokens
        return f"{self.prefix}/lock/"

    @property
    def group(self) -> str:      # node groups
        return f"{self.prefix}/group/"

    @property
    def noticer(self) -> str:    # failure messages node -> web
        return f"{self.prefix}/noticer/"

    @property
    def sess(self) -> str:       # web sessions (leased)
        return f"{self.prefix}/sess/"

    @property
    def dispatch(self) -> str:   # planner -> agent execution orders (leased)
        return f"{self.prefix}/dispatch/"

    @property
    def leader(self) -> str:     # scheduler leader election
        return f"{self.prefix}/leader"

    # -- key builders ------------------------------------------------------

    def job_key(self, group: str, job_id: str) -> str:
        return f"{self.cmd}{group}/{job_id}"

    def node_key(self, node_id: str) -> str:
        return f"{self.node}{node_id}"

    def group_key(self, gid: str) -> str:
        return f"{self.group}{gid}"

    def once_key(self, group: str, job_id: str) -> str:
        return f"{self.once}{group}/{job_id}"

    def lock_key(self, job_id: str, epoch_s: int) -> str:
        """Per-(job, second) execution dedup fence.  ``epoch_s`` is the
        SCHEDULED epoch as emitted by the planner — for jobs with
        ``jitter`` set that is the smeared epoch
        (``s + fnv1a64("<group>/<id>|<s>") % (jitter+1)``), so a
        replayed or
        re-planned window fences against exactly the same key."""
        return f"{self.lock}{job_id}/{epoch_s}"

    @property
    def alone_lock(self) -> str:
        """Prefix of the fleet-wide KindAlone running locks."""
        return f"{self.lock}alone/"

    def alone_lock_key(self, job_id: str) -> str:
        """Fleet-wide running lock for KindAlone jobs — held with keepalive
        for the execution's whole lifetime (reference job.go:87-123), unlike
        the per-(job, second) dedup fence of :meth:`lock_key`."""
        return f"{self.alone_lock}{job_id}"

    @property
    def hwm(self) -> str:        # scheduler planning high-water mark
        return f"{self.prefix}/hwm"

    def hwm_partition_key(self, partition: int) -> str:
        """Per-partition planning high-water mark (partitioned
        scheduler plane): each partition leader resumes from ITS mark.
        The unpartitioned (P=1) scheduler keeps the bare :attr:`hwm`
        key — pure passthrough."""
        return f"{self.prefix}/hwm/p{partition}"

    # -- partitioned scheduler plane --------------------------------------

    def partition_leader_key(self, partition: int) -> str:
        """Leader-election key for ONE scheduler partition.  P
        independent leases, one per job-space slice; the unpartitioned
        scheduler keeps the bare :attr:`leader` key."""
        return f"{self.lock}sched/p{partition}"

    @property
    def partmap(self) -> str:
        """Partition-topology pin (sched/partition.py): the first
        partition leader publishes ``{"p": P, "hash": SCHEME}``; every
        later scheduler verifies its configured partition count against
        it and refuses loudly on mismatch — the shardmap pattern (PR 6)
        lifted to the scheduler plane."""
        return f"{self.prefix}/sched/partmap"

    @property
    def sched_acct(self) -> str:
        """Per-partition node-demand summaries (leased): each partition
        leader periodically publishes its per-node outstanding
        exclusive slots + running load under ``.../acct/p<i>``; every
        other partition folds the summaries into its capacity view, so
        shared node rem_cap stays reconciled without cross-partition
        coordination on the fire path."""
        return f"{self.prefix}/sched/acct/"

    def sched_acct_key(self, partition: int) -> str:
        return f"{self.sched_acct}p{partition}"

    @property
    def shardmap(self) -> str:
        """Shard-topology pin (store/sharded.py): lives on shard 0 by
        fiat; clients verify their configured shard count against it."""
        return f"{self.prefix}/shardmap"

    @property
    def metrics(self) -> str:    # leased per-process metric snapshots
        return f"{self.prefix}/metrics/"

    def metrics_key(self, component: str, instance: str) -> str:
        return f"{self.metrics}{component}/{instance}"

    @property
    def ckpt(self) -> str:       # checkpoint plane control keys
        return f"{self.prefix}/ckpt/"

    @property
    def ckpt_req(self) -> str:
        """Operator checkpoint trigger (``cronsun-ctl checkpoint`` via
        the web API): schedulers watch the ckpt prefix and save on a
        PUT here."""
        return f"{self.ckpt}request"

    @property
    def ckpt_barrier(self) -> str:
        """Watch-quiesce barrier: the scheduler writes a nonce here and
        drains its watches until the nonce arrives, which proves every
        event at or before the write's revision is applied to its
        mirrors — the revision a checkpoint is tagged with."""
        return f"{self.ckpt}barrier"

    def ckpt_done_key(self, node_id: str) -> str:
        """Per-scheduler checkpoint result (JSON: rev/ms/path) written
        after an operator-requested save."""
        return f"{self.ckpt}done/{node_id}"

    @property
    def phase(self) -> str:      # @every phase anchors, survive failover
        return f"{self.prefix}/phase/"

    def phase_key(self, group: str, job_id: str, rule_id: str) -> str:
        return f"{self.phase}{group}/{job_id}/{rule_id}"

    @property
    def dep(self) -> str:
        """Workflow DAG completion events: one persistent key per job,
        last completed round.  Agents write it at execution end; the
        scheduler watches the prefix and folds the events into the
        on-device success-epoch vectors (the dep-trigger edge signal)."""
        return f"{self.prefix}/dep/"

    def dep_key(self, group: str, job_id: str) -> str:
        """Value wire format: ``"<scheduled epoch>|ok"`` or ``"...|fail"``
        — the SCHEDULED second, not completion wall time, so every node
        of a Common fan-out writes the same value for one round
        (last-write-wins is idempotent per round)."""
        return f"{self.dep}{group}/{job_id}"

    def proc_key(self, node_id: str, group: str, job_id: str, pid) -> str:
        return f"{self.proc}{node_id}/{group}/{job_id}/{pid}"

    def noticer_key(self, node_id: str) -> str:
        return f"{self.noticer}{node_id}"

    def dispatch_key(self, node_id: str, epoch_s: int, group: str,
                     job_id: str) -> str:
        """Legacy per-(node, second, job) exclusive order key — still
        consumed by both agents for rollout tolerance; the scheduler
        publishes :meth:`dispatch_bundle_key` for in-window fires, but
        late smeared arrivals (spill-ring entries whose carrying window
        has moved on) are emitted on this per-job form.  ``epoch_s`` is
        always the SMEARED scheduled epoch when the job sets jitter."""
        return f"{self.dispatch}{node_id}/{epoch_s}/{group}/{job_id}"

    @staticmethod
    def split_bundle_epoch(segment: str):
        """Parse a coalesced bundle key's epoch segment — ``<epoch>``
        plain, or the partitioned scheduler's ``<epoch>.<partition>``
        form.  Returns ``(epoch, partition-or-None)``, or None when
        the segment is neither — THE one home of the suffix grammar
        (agents, fsck, mirrors and benches all parse through here;
        native/agentd.cc mirrors it)."""
        ep, dot, part = segment.partition(".")
        if not ep.isdigit() or (dot and not part.isdigit()):
            return None
        return int(ep), (int(part) if part else None)

    def dispatch_bundle_key(self, node_id: str, epoch_s: int) -> str:
        """Coalesced exclusive order: ONE key per (node, second), value =
        JSON array of "group/job_id" strings.  A minute-boundary cron
        herd publishes at most one key per active node instead of one
        per fire (~20x fewer keys at the 1M x 10k scale); the key doubles
        as the scheduler's outstanding-capacity reservation for
        len(value) exclusive slots until the per-job proc keys exist.
        ``epoch_s`` is the scheduled second AFTER herd smearing: a
        jittered job's order coalesces under its smeared epoch, which is
        exactly what flattens the (node, second) key herd."""
        return f"{self.dispatch}{node_id}/{epoch_s}"

    # Common-kind fan-out: ONE broadcast order per (second, job); each
    # agent decides eligibility locally (the reference's IsRunOn,
    # job.go:616-630) instead of the scheduler writing one key per node —
    # a 1M-job burst to 10k nodes must not be 10^10 store writes.
    BROADCAST = "_all"

    @property
    def dispatch_all(self) -> str:
        return f"{self.dispatch}{self.BROADCAST}/"

    def dispatch_all_key(self, epoch_s: int, group: str, job_id: str) -> str:
        """Broadcast Common-kind order.  Like every dispatch/fence key,
        ``epoch_s`` is the smeared scheduled epoch for jittered jobs."""
        return f"{self.dispatch_all}{epoch_s}/{group}/{job_id}"

    def sess_key(self, sid: str) -> str:
        return f"{self.sess}{sid}"

    # -- multi-tenant control plane ---------------------------------------

    @property
    def tenant(self) -> str:
        """Tenancy keyspace family: per-tenant quota records and the
        per-tenant job index markers the web tier maintains so
        ``set_job``'s max_jobs check is one ``count_prefix``, not a
        full ``cmd/`` scan."""
        return f"{self.prefix}/tenant/"

    def tenant_quota_key(self, tenant: str) -> str:
        """Quota record (core.models.TenantQuota JSON); the scheduler
        watches the tenant prefix and folds these into the per-tenant
        token-bucket columns."""
        return f"{self.tenant}{tenant}/quota"

    def tenant_jobs(self, tenant: str) -> str:
        """Prefix of one tenant's job index markers."""
        return f"{self.tenant}{tenant}/job/"

    def tenant_job_key(self, tenant: str, group: str, job_id: str) -> str:
        return f"{self.tenant_jobs(tenant)}{group}/{job_id}"

    # -- SLO engine (trace plane) ------------------------------------------

    @property
    def slo(self) -> str:
        """Declarative SLO records (core.models.SloSpec JSON): the web
        tier lists the prefix each evaluation tick and alerts on
        multi-window burn rates over the scraped execution counters."""
        return f"{self.prefix}/slo/"

    def slo_key(self, name: str) -> str:
        return f"{self.slo}{name}"
