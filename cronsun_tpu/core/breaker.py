"""Per-shard circuit breaker for the sharded fan-out clients.

The classic production failure the sharded planes (store PR 6, logd
PR 7) had no model for is the BROWNED-OUT shard: alive at the TCP
level but slow — every scatter-gather read and every claim fan-out
waits on it, so one shard's 5 s stall becomes the whole plane's 5 s
stall.  A *dead* shard fails fast (connect refused, RPC error); a
*slow* one poisons everything silently.

:class:`CircuitBreaker` bounds that blast radius with the standard
three states:

- **closed** — healthy: calls pass, latencies are measured against the
  per-shard ``deadline``; ``fail_threshold`` consecutive
  deadline-or-error outcomes open the breaker.
- **open** — degraded: calls are refused IMMEDIATELY (fail-fast for
  writes/claims, skip-with-``shard_degraded``-stat for tolerant
  reads) until ``cooldown`` elapses.
- **probing** — after cooldown ONE trial call is let through; success
  closes the breaker, failure re-opens it for another cooldown.

The breaker never retries and never sleeps — policy (what a refused
call means) belongs to the caller; this class only answers "should
this call be attempted, and what happened to the last one".

Enable by deadline: ``deadline <= 0`` disables the breaker entirely
(every call allowed, nothing recorded) — the default, so existing
single-host deployments and the tier-1 suite see zero behavior change;
production fleets and the chaos drills opt in via
``CRONSUN_SHARD_DEADLINE_S`` (see store/sharded.py).
"""

from __future__ import annotations

import inspect
import json
import threading
import time
from typing import Callable, List, Optional

from .. import log

CLOSED, OPEN, PROBING = "closed", "open", "probing"


class CircuitBreaker:
    __slots__ = ("deadline", "fail_threshold", "cooldown", "clock",
                 "_mu", "_state", "_fails", "_opened_at", "_probe_out",
                 "opens_total", "refused_total", "on_open")

    def __init__(self, deadline: float = 0.0, fail_threshold: int = 3,
                 cooldown: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.deadline = deadline
        self.fail_threshold = max(1, fail_threshold)
        self.cooldown = cooldown
        self.clock = clock
        self._mu = threading.Lock()
        self._state = CLOSED
        self._fails = 0
        self._opened_at = 0.0
        self._probe_out = False
        self.opens_total = 0
        self.refused_total = 0
        # invoked (outside the lock) on each CLOSED/PROBING -> OPEN
        # transition; BreakerBank.arm_notices wires the noticer push
        self.on_open: Optional[Callable[[], None]] = None

    @property
    def enabled(self) -> bool:
        return self.deadline > 0

    @property
    def state(self) -> str:
        with self._mu:
            return self._effective_state_locked()

    def _effective_state_locked(self) -> str:
        if self._state == OPEN and \
                self.clock() - self._opened_at >= self.cooldown:
            self._state = PROBING
            self._probe_out = False
        return self._state

    def allow(self) -> bool:
        """May a call be attempted now?  In PROBING exactly one caller
        gets True per cooldown window (the probe); everyone else is
        refused until it reports back."""
        if not self.enabled:
            return True
        with self._mu:
            st = self._effective_state_locked()
            if st == CLOSED:
                return True
            if st == PROBING and not self._probe_out:
                self._probe_out = True
                return True
            self.refused_total += 1
            return False

    def record(self, ok: bool, elapsed: float = 0.0):
        """Report a completed call.  ``ok`` means it succeeded AND beat
        the deadline; callers that measured a slow success pass
        ``ok=False`` via ``elapsed`` (slow == browned out)."""
        if not self.enabled:
            return
        if ok and elapsed > self.deadline:
            ok = False
        opened = False
        with self._mu:
            st = self._effective_state_locked()
            if ok:
                self._state = CLOSED
                self._fails = 0
                self._probe_out = False
                return
            self._fails += 1
            if st == OPEN:
                # straggler: a call that was already in flight when the
                # breaker opened fails late.  It must NOT restart the
                # cooldown (a scatter-gather's stragglers draining over
                # tens of seconds would push the probe — and recovery —
                # out indefinitely) nor inflate opens_total.
                return
            if st == PROBING or self._fails >= self.fail_threshold:
                self.opens_total += 1
                self._state = OPEN
                self._opened_at = self.clock()
                self._probe_out = False
                opened = True
        if opened and self.on_open is not None:
            # outside the lock: the hook must never stall (or deadlock)
            # the RPC path that reported the failure
            try:
                self.on_open()
            except Exception as e:  # noqa: BLE001 — paging is
                # best-effort; breaking is the load-bearing part
                log.warnf("breaker on_open hook failed: %s", e)

    def snapshot(self) -> dict:
        with self._mu:
            return {"state": self._effective_state_locked(),
                    "consecutive_fails": self._fails,
                    "opens_total": self.opens_total,
                    "refused_total": self.refused_total,
                    "deadline_s": self.deadline}


class ShardDegradedError(RuntimeError):
    """A shard's circuit breaker is OPEN: the op was refused fail-fast
    instead of stalling behind a browned-out shard.  Callers treat it
    like any transient store/sink error — the claim and flush ladders
    already retry, and leased keys (orders, fences, procs) age out
    safely."""


# lifecycle methods pass through unguarded: they are not RPCs (close on
# a dead shard must not count as a failure, clone must hand back the
# RAW client for re-wrapping)
_GUARD_PASSTHROUGH = frozenset(("clone", "close", "start_sweeper"))


class ShardGuard:
    """Per-shard health wrapper for the sharded fan-out clients: every
    RPC is breaker-gated (open -> :class:`ShardDegradedError`
    immediately, no wire wait) and timed (a success slower than the
    deadline counts as a brownout failure).  Pure delegation otherwise
    — the guarded client keeps the wrapped client's full surface.

    ``healthy_errors`` are exception types that are legitimate server
    ANSWERS, not shard-health failures (a missing lease, a compacted
    watch): they record success and re-raise."""

    __slots__ = ("_inner", "_breaker", "_idx", "_label", "_healthy",
                 "_cache")

    def __init__(self, inner, breaker: CircuitBreaker, idx: int,
                 healthy_errors=(KeyError,), label: str = "shard"):
        self._inner = inner
        self._breaker = breaker
        self._idx = idx
        self._label = label
        self._healthy = tuple(healthy_errors)
        self._cache: dict = {}

    def __getattr__(self, name):
        cached = self._cache.get(name)
        if cached is not None:
            return cached
        fn = getattr(self._inner, name)
        # generator functions (get_prefix_paged) pass through UNGUARDED:
        # timing generator CREATION would record an instant "success"
        # without touching the wire — a cooldown probe consumed by one
        # would close the breaker with no evidence — and mid-iteration
        # faults can't be attributed to one call anyway
        if not callable(fn) or name in _GUARD_PASSTHROUGH or \
                name.startswith("_") or inspect.isgeneratorfunction(fn):
            return fn
        breaker, idx, label = self._breaker, self._idx, self._label
        healthy = self._healthy

        def guarded(*a, **kw):
            if not breaker.allow():
                raise ShardDegradedError(
                    f"{label} {idx} degraded (breaker open); "
                    f"{name} refused fail-fast")
            t0 = time.monotonic()
            try:
                r = fn(*a, **kw)
            except healthy:
                breaker.record(True, time.monotonic() - t0)
                raise
            except Exception:
                breaker.record(False)
                raise
            breaker.record(True, time.monotonic() - t0)
            return r
        self._cache[name] = guarded
        return guarded


class BreakerBank:
    """Per-shard breakers + degraded-read accounting, shared by the
    sharded store and logsink clients (one definition — the two were
    drifting copies).  ``deadline <= 0`` disables everything: guards()
    hands back the raw clients and snapshot() is empty."""

    def __init__(self, nshards: int, deadline: float,
                 fail_threshold: int = 3, cooldown: float = 1.0,
                 label: str = "shard"):
        self.nshards = nshards
        self.deadline = deadline
        self.label = label
        self.breakers = [
            CircuitBreaker(deadline=deadline,
                           fail_threshold=fail_threshold,
                           cooldown=cooldown)
            for _ in range(nshards)]
        self._degraded = [0] * nshards
        self._mu = threading.Lock()
        self._log_at = 0.0

    @property
    def enabled(self) -> bool:
        return self.deadline > 0 and self.nshards > 1

    def guards(self, raw: List, healthy_errors=(KeyError,)) -> List:
        """Wrap the raw shard clients — or return them untouched when
        the bank is disabled (byte-identical behavior)."""
        if not self.enabled:
            return list(raw)
        return [ShardGuard(s, self.breakers[i], i,
                           healthy_errors=healthy_errors,
                           label=self.label)
                for i, s in enumerate(raw)]

    def note_degraded(self, i: int):
        """A tolerant read skipped shard ``i`` (breaker open): count it
        LOUDLY — a degraded partial result must be visible in metrics
        and logs, never silent."""
        with self._mu:
            self._degraded[i] += 1
        now = time.monotonic()
        if now - self._log_at >= 1.0:          # rate-limited, loud
            self._log_at = now
            log.warnf("%s %d degraded (breaker %s): serving partial "
                      "reads without it", self.label, i,
                      self.breakers[i].state)

    def tolerant(self, i: int, fn, default=None):
        """Wrap a fan thunk for a read that can TOLERATE a missing
        shard: an open breaker yields ``default`` (counted) instead of
        failing the whole scatter-gather."""
        def run():
            try:
                return fn()
            except ShardDegradedError:
                self.note_degraded(i)
                return default
        return run

    def arm_notices(self, store, prefix: str, source: str = "",
                    interval_s: float = 60.0):
        """Push a breaker OPEN transition into the noticer plane: a
        shard browning out should PAGE, not just count.

        Each transition writes a notice key under
        ``<prefix>/noticer/breaker-<label>-<shard>`` which the
        NoticerHost (hosted by the web process) delivers by SMTP/HTTP
        with its usual durable-retry ladder.  Rate-limited per shard
        (``interval_s``) — a flapping breaker pages once a minute, not
        once per open — and written BEST-EFFORT on a background thread
        with a short retry ladder: the write itself may route to the
        very shard that just opened, in which case it lands once the
        probe closes the breaker (the page is late, the metrics gauge
        is the real-time signal).

        ``store`` is any client with ``put`` (typically the sharded
        client that owns this bank); idempotent to call once per bank.
        """
        if not self.enabled:
            return
        slug = self.label.replace(" ", "-")
        last = [0.0] * self.nshards

        def mk(i: int):
            def fire():
                now = time.monotonic()
                if now - last[i] < interval_s:
                    return
                last[i] = now
                snap = self.breakers[i].snapshot()
                key = f"{prefix}/noticer/breaker-{slug}-{i}"
                body = json.dumps({
                    "subject": f"[cronsun] {self.label} {i} circuit "
                               f"OPEN" + (f" ({source})" if source
                                          else ""),
                    "body": f"{self.label} {i} breaker opened "
                            f"(open #{snap['opens_total']}, deadline "
                            f"{snap['deadline_s']}s): consecutive "
                            "failures or brownouts; writes fail fast "
                            "and tolerant reads serve without this "
                            "shard until a cooldown probe succeeds. "
                            "See cronsun_*_shard_breaker_* at "
                            "/v1/metrics."})

                def write():
                    for _ in range(10):
                        try:
                            store.put(key, body)
                            return
                        except Exception:  # noqa: BLE001 — the notice
                            # may route to the open shard; retry as it
                            # heals, give up quietly after the ladder
                            time.sleep(2.0)
                    log.warnf("breaker-open notice for %s %d could not "
                              "be written (store degraded)",
                              self.label, i)
                threading.Thread(target=write, daemon=True,
                                 name=f"breaker-notice-{slug}-{i}"
                                 ).start()
            return fire
        for i, b in enumerate(self.breakers):
            b.on_open = mk(i)

    def snapshot(self) -> List[dict]:
        """Per-shard breaker state + degraded-read counts (rendered at
        /v1/metrics).  Empty when disabled."""
        if not self.enabled:
            return []
        with self._mu:
            degraded = list(self._degraded)
        out = []
        for i, b in enumerate(self.breakers):
            snap = b.snapshot()
            snap["shard"] = i
            snap["degraded_reads_total"] = degraded[i]
            out.append(snap)
        return out
