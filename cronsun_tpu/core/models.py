"""Domain models: Job, JobRule, Group, Node, Account.

Field-compatible with the reference's JSON wire format (job.go:38-84,
group.go:17-22, node.go:25-35, account.go:14-25) so stored state is
interoperable; validation mirrors Check/Valid (job.go:502-537,633-656).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import List, Optional

from ..cron.parser import ParseError, parse
from .errors import SecurityInvalid, ValidationError
from .ids import next_id

KIND_COMMON = 0    # runs on every eligible node, no mutual exclusion
KIND_ALONE = 1     # exactly one execution fleet-wide at a time
KIND_INTERVAL = 2  # at most one start per schedule interval

ROLE_ADMIN = 1
ROLE_DEVELOPER = 2

# Workflow DAG plane: a dep-triggered job names up to MAX_DEPS upstream
# jobs; the on-device dependency matrix is padded to this width
# (ops/schedule_table.py stores one [capacity, MAX_DEPS] column block).
MAX_DEPS = 8

MISFIRE_SKIP = "skip"    # a failed upstream round is consumed, no fire
MISFIRE_FIRE = "fire"    # fire anyway on upstream failure
MISFIRE_HOLD = "hold"    # wait until every upstream's latest run succeeds
MISFIRE_POLICIES = (MISFIRE_SKIP, MISFIRE_FIRE, MISFIRE_HOLD)

# Rules of dep-triggered jobs carry this sentinel timer: placement
# (nids/gids/exclude) still comes from the rule, but the trigger is the
# upstream success-epoch test in the batched tick, not a cron mask.
DEP_TIMER = "@dep"


def _clean(s: Optional[str]) -> str:
    return (s or "").strip()


@dataclasses.dataclass
class DepSpec:
    """Workflow dependency spec: the job fires when the latest run of
    EVERY upstream job (same group) succeeds after this job's last fire.

    ``misfire`` picks the behaviour when an upstream's latest round
    FAILED (see MISFIRE_*); ``max_in_flight`` caps concurrently running
    executions of this job (0 = unlimited) — a saturated job holds its
    fire until a slot frees."""
    on: List[str] = dataclasses.field(default_factory=list)
    misfire: str = MISFIRE_SKIP
    max_in_flight: int = 0

    def validate(self):
        self.on = [_clean(u) for u in self.on]
        if not self.on:
            raise ValidationError("deps.on must name at least one "
                                  "upstream job id")
        if len(self.on) > MAX_DEPS:
            raise ValidationError(
                f"deps.on lists {len(self.on)} upstreams; the dependency "
                f"matrix is padded to {MAX_DEPS} columns per job")
        seen = set()
        for u in self.on:
            if not u:
                raise ValidationError("deps.on contains an empty job id")
            if "/" in u:
                raise ValidationError(
                    f"cross-group dep reference {u!r}: dependencies "
                    "resolve within the job's own group only")
            if u in seen:
                raise ValidationError(f"duplicate upstream {u!r} in deps.on")
            seen.add(u)
        self.misfire = _clean(self.misfire) or MISFIRE_SKIP
        if self.misfire not in MISFIRE_POLICIES:
            raise ValidationError(
                f"unknown misfire policy {self.misfire!r} "
                f"(one of {', '.join(MISFIRE_POLICIES)})")
        if self.max_in_flight < 0:
            raise ValidationError("deps.max_in_flight must be >= 0")

    def to_dict(self) -> dict:
        return {"on": self.on, "misfire": self.misfire,
                "max_in_flight": self.max_in_flight}

    @classmethod
    def from_dict(cls, d: dict) -> "DepSpec":
        return cls(on=list(d.get("on") or []),
                   misfire=d.get("misfire", MISFIRE_SKIP),
                   max_in_flight=int(d.get("max_in_flight") or 0))


def validate_dag(dep_map: dict, job_ids, root: str):
    """Group-level DAG validation for one (changed) job: every upstream
    reachable from ``root`` must exist in ``job_ids`` and the walk must
    not revisit ``root`` or any node on the current path (a cycle).

    ``dep_map`` is {job_id: [upstream ids]} for the whole group WITH the
    changed job's new deps substituted; pure host code so the web tier
    can run it at ``set_job`` without importing the device stack."""
    path: List[str] = []
    on_path = set()
    done = set()   # fully-validated subtrees: each node expands ONCE,
    #                or diamonds of shared substructure go exponential

    def walk(jid: str):
        if jid in done:
            return
        if jid in on_path:
            cyc = path[path.index(jid):] + [jid]
            raise ValidationError(
                "dependency cycle: " + " -> ".join(cyc))
        ups = dep_map.get(jid)
        if not ups:
            done.add(jid)
            return
        on_path.add(jid)
        path.append(jid)
        for u in ups:
            if u not in job_ids:
                raise ValidationError(
                    f"unknown upstream job {u!r} (dep of {jid!r}; "
                    "dependencies resolve within the job's group)")
            walk(u)
        path.pop()
        on_path.discard(jid)
        done.add(jid)

    walk(root)


@dataclasses.dataclass
class JobRule:
    """Placement rule: cron timer + include nodes/groups − exclude nodes
    (reference job.go:76-84)."""
    id: str = ""
    timer: str = ""
    gids: List[str] = dataclasses.field(default_factory=list)
    nids: List[str] = dataclasses.field(default_factory=list)
    exclude_nids: List[str] = dataclasses.field(default_factory=list)

    def validate(self, dep_triggered: bool = False):
        self.timer = _clean(self.timer)
        if dep_triggered:
            # dep-triggered jobs: the rule is placement-only; the timer
            # is pinned to the sentinel (an empty timer normalizes)
            if self.timer not in ("", DEP_TIMER):
                raise ValidationError(
                    f"rule timer {self.timer!r} conflicts with the "
                    "deps spec: dep-triggered jobs use timer "
                    f"{DEP_TIMER!r} (or omit it)")
            self.timer = DEP_TIMER
            return
        if self.timer == DEP_TIMER:
            raise ValidationError(
                f"timer {DEP_TIMER!r} requires a deps spec on the job")
        if not self.timer:
            raise ValidationError("rule timer required")
        try:
            parse(self.timer)
        except ParseError as e:
            raise ValidationError(f"invalid timer {self.timer!r}: {e}")

    def to_dict(self) -> dict:
        return {"id": self.id, "timer": self.timer, "gids": self.gids,
                "nids": self.nids, "exclude_nids": self.exclude_nids}

    @classmethod
    def from_dict(cls, d: dict) -> "JobRule":
        return cls(id=d.get("id", ""), timer=d.get("timer", ""),
                   gids=list(d.get("gids") or []),
                   nids=list(d.get("nids") or []),
                   exclude_nids=list(d.get("exclude_nids") or []))


@dataclasses.dataclass
class Job:
    """A schedulable command (reference job.go:38-74)."""
    id: str = ""
    name: str = ""
    group: str = ""
    command: str = ""
    user: str = ""
    # multi-tenant control plane: the isolation axis quotas/admission
    # key on; "" is the default tenant (never quota-limited)
    tenant: str = ""
    rules: List[JobRule] = dataclasses.field(default_factory=list)
    pause: bool = False
    timeout: int = 0            # seconds; 0 = unlimited
    parallels: int = 0          # max concurrent per node; 0 = unlimited
    retry: int = 0
    interval: int = 0           # seconds between retries
    kind: int = KIND_COMMON
    avg_time: float = 0.0       # EWMA execution seconds (job.go:581-589)
    fail_notify: bool = False
    to: List[str] = dataclasses.field(default_factory=list)
    # workflow DAG trigger: when set, the job fires on upstream success
    # instead of a cron mask (rules keep carrying placement)
    deps: Optional[DepSpec] = None
    # trace plane: force head-sampling of every fire of this job
    # regardless of the fleet's trace_sample_shift (failure runs are
    # always sampled either way)
    trace: bool = False
    # herd smearing: deterministic per-fire delay width in seconds
    # (0..300).  A fire matched at logical second s is dispatched at
    # s + fnv1a64("<group>/<id>|<s>") % (jitter+1) — no randomness,
    # the same job/second pair always lands on the same smeared epoch
    # across leaders and restores.  0 keeps today's exact-second
    # behaviour.
    jitter: int = 0

    # ---- validation (reference job.go:502-537) ---------------------------

    def check(self):
        self.id = _clean(self.id) or next_id()
        self.name = _clean(self.name)
        if not self.name:
            raise ValidationError("job name required")
        self.group = _clean(self.group) or "default"
        if "/" in self.group:
            raise ValidationError("group name must not contain '/'")
        self.tenant = _clean(self.tenant)
        if "/" in self.tenant:
            raise ValidationError("tenant name must not contain '/'")
        if self.timeout < 0:
            raise ValidationError("timeout must be >= 0")
        if self.parallels < 0:
            raise ValidationError("parallels must be >= 0")
        if self.retry < 0:
            raise ValidationError("retry must be >= 0")
        if self.interval < 0:
            raise ValidationError("interval must be >= 0")
        if self.kind not in (KIND_COMMON, KIND_ALONE, KIND_INTERVAL):
            raise ValidationError(f"unknown kind {self.kind}")
        if not _clean(self.command):
            raise ValidationError("command required")
        self.trace = bool(self.trace)
        j = self.jitter
        if isinstance(j, bool) or \
                (not isinstance(j, int) and
                 not (isinstance(j, float) and j.is_integer())):
            raise ValidationError(
                f"jitter must be an integer number of seconds, got {j!r}")
        j = int(j)
        if not 0 <= j <= 300:
            raise ValidationError(
                f"jitter must be in 0..300 seconds, got {j}")
        self.jitter = j
        if isinstance(self.deps, dict):
            self.deps = DepSpec.from_dict(self.deps)
        if self.deps is not None:
            self.deps.validate()
            if self.id in self.deps.on:
                raise ValidationError(
                    f"job {self.id!r} cannot depend on itself")
        dep_triggered = self.deps is not None
        if dep_triggered and self.jitter:
            raise ValidationError(
                "dep-triggered jobs cannot set jitter: their fires are "
                "event-driven (upstream success), not cron-matched, so "
                "there is no herd second to smear")
        if dep_triggered and not self.rules:
            raise ValidationError(
                "dep-triggered jobs need at least one rule for "
                "placement (nids/gids)")
        for rule in self.rules:
            rule.id = _clean(rule.id) or next_id()
            rule.validate(dep_triggered=dep_triggered)

    def security_valid(self, security) -> None:
        """Reject commands/users outside the policy (reference
        job.go:633-656).  ``security`` is conf.Security or None."""
        if security is None or security.open is False:
            return
        if security.users and self.user not in security.users:
            raise SecurityInvalid(
                f"user {self.user!r} not in allowed users")
        if security.exts:
            cmd = _clean(self.command).split()[0] if _clean(self.command) else ""
            if not any(cmd.endswith(ext) for ext in security.exts):
                raise SecurityInvalid(
                    f"command {cmd!r} does not match allowed suffixes")

    @property
    def exclusive(self) -> bool:
        return self.kind in (KIND_ALONE, KIND_INTERVAL)

    def update_avg_time(self, seconds: float):
        """avg of the last two (reference job.go:581-589)."""
        self.avg_time = seconds if self.avg_time == 0 \
            else (self.avg_time + seconds) / 2

    # ---- wire ------------------------------------------------------------

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["rules"] = [r.to_dict() if isinstance(r, JobRule) else r
                      for r in self.rules]
        if self.deps is None:
            # wire compat: dep-less jobs serialize exactly as before
            d.pop("deps", None)
        if not self.tenant:
            # wire compat: default-tenant jobs keep the pre-tenancy bytes
            d.pop("tenant", None)
        if not self.trace:
            # wire compat: untraced jobs keep the pre-trace bytes
            d.pop("trace", None)
        if not self.jitter:
            # wire compat: unsmeared jobs keep the pre-jitter bytes
            d.pop("jitter", None)
        return json.dumps(d, separators=(",", ":"))

    _FIELDS = None   # lazily cached field-name set (NOT annotated: an
                     # annotation would make it a dataclass field)

    @classmethod
    def from_json(cls, s: str) -> "Job":
        d = json.loads(s)
        rules = [JobRule.from_dict(r) for r in d.get("rules") or []]
        deps = d.get("deps")
        if isinstance(deps, dict) and deps.get("on"):
            deps = DepSpec.from_dict(deps)
        else:
            deps = None
        known = cls._FIELDS
        if known is None:
            # cached: dataclasses.fields() introspection per document
            # was a measured slice of the 1M-job cold load
            known = frozenset(f.name for f in dataclasses.fields(cls))
            cls._FIELDS = known
        kw = {k: v for k, v in d.items()
              if k in known and k not in ("rules", "deps")}
        return cls(rules=rules, deps=deps, **kw)


@dataclasses.dataclass
class Group:
    """Named node set (reference group.go:17-22)."""
    id: str = ""
    name: str = ""
    node_ids: List[str] = dataclasses.field(default_factory=list)

    def check(self):
        self.id = _clean(self.id) or next_id()
        self.name = _clean(self.name)
        if not self.name:
            raise ValidationError("group name required")
        if "/" in self.id:
            raise ValidationError("group id must not contain '/'")

    def included(self, node_id: str) -> bool:
        return node_id in self.node_ids

    def to_json(self) -> str:
        return json.dumps({"id": self.id, "name": self.name,
                           "nids": self.node_ids}, separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str) -> "Group":
        d = json.loads(s)
        return cls(id=d.get("id", ""), name=d.get("name", ""),
                   node_ids=list(d.get("nids") or []))


@dataclasses.dataclass
class Node:
    """Machine identity + liveness (reference node.go:25-35)."""
    id: str = ""                 # IP in the reference; any stable id here
    pid: int = 0
    ip: str = ""
    hostname: str = ""
    version: str = ""
    up_ts: float = 0.0
    alived: bool = False

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str) -> "Node":
        d = json.loads(s)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def hash_password(password: str, salt: str) -> str:
    """Double sha256(pwd+salt) — same shape as the reference's double-MD5
    (web/authentication.go:54-58) with a modern hash."""
    h1 = hashlib.sha256((password + salt).encode()).hexdigest()
    return hashlib.sha256((h1 + salt).encode()).hexdigest()


@dataclasses.dataclass
class Account:
    """Web user (reference account.go:14-25)."""
    email: str = ""
    password: str = ""           # hash_password output
    salt: str = ""
    role: int = ROLE_DEVELOPER
    status: int = 1              # 1 enabled, 0 banned
    session: str = ""
    unchangeable: bool = False
    # multi-tenant control plane: a non-empty tenant PINS this
    # account's jobs to that tenant (admins may set any tenant)
    tenant: str = ""

    def check_password(self, password: str) -> bool:
        return hash_password(password, self.salt) == self.password

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str) -> "Account":
        d = json.loads(s)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


# SLO scopes — which slice of the fleet's executions a spec covers.
# The scope string doubles as the counter key agents publish in their
# metrics snapshots ("" global, "t:<tenant>", "c:<group>/<job>").
SLO_SCOPE_GLOBAL = ""


@dataclasses.dataclass
class SloSpec:
    """Declarative service-level objective, stored under
    ``slo/<name>``.  ``target`` is the good-fire ratio (e.g. 0.999);
    ``latency_ms`` > 0 additionally counts an execution as bad when its
    run time exceeds the threshold (snapped DOWN to a histogram bucket
    bound — pick thresholds from trace.BUCKETS_MS for exactness).

    ``scope`` picks the slice: "" = every execution fleet-wide;
    ``tenant:<name>`` = one tenant's executions; ``chain:<group>/<job>``
    = one DAG chain, keyed by its terminal (dep-triggered) job.

    The web tier evaluates each spec as multi-window multi-burn-rate
    alerts (Google SRE workbook): fast page at burn >= 14.4 over BOTH
    5m and 1h, slow page at burn >= 6 over BOTH 30m and 6h, where
    burn = bad_fraction / (1 - target)."""
    name: str = ""
    scope: str = SLO_SCOPE_GLOBAL
    target: float = 0.999
    latency_ms: float = 0.0

    def validate(self):
        self.name = _clean(self.name)
        if not self.name:
            raise ValidationError("slo name required")
        if "/" in self.name:
            raise ValidationError("slo name must not contain '/'")
        self.scope = _clean(self.scope)
        if self.scope:
            kind, _, rest = self.scope.partition(":")
            if kind not in ("tenant", "chain") or not rest:
                raise ValidationError(
                    f"slo scope {self.scope!r}: expected '', "
                    "'tenant:<name>' or 'chain:<group>/<job>'")
            if kind == "chain" and "/" not in rest:
                raise ValidationError(
                    f"slo chain scope {rest!r}: expected <group>/<job>")
        if not (0.0 < self.target < 1.0):
            raise ValidationError("slo target must be in (0, 1)")
        if self.latency_ms < 0:
            raise ValidationError("slo latency_ms must be >= 0")

    @property
    def counter_scope(self) -> str:
        """The agent-snapshot counter key this spec reads."""
        if not self.scope:
            return ""
        kind, _, rest = self.scope.partition(":")
        return ("t:" + rest) if kind == "tenant" else ("c:" + rest)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self),
                          separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str) -> "SloSpec":
        d = json.loads(s)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class TenantQuota:
    """Per-tenant admission limits, stored under ``tenant/<id>/quota``.

    Zero means unlimited for every field.  ``rate``/``burst`` feed the
    scheduler's per-tenant token bucket (fires admitted per scheduled
    second, evaluated inside the batched tick); ``max_jobs`` is enforced
    at ``set_job`` (429 over quota); ``max_running`` caps concurrently
    outstanding EXCLUSIVE executions (orders + procs); ``weight`` is the
    fair-share weight when aggregate exclusive demand exceeds agent
    capacity (weighted max-min, default 1.0)."""
    tenant: str = ""
    max_jobs: int = 0
    rate: float = 0.0            # sustained fires/second
    burst: float = 0.0           # bucket depth; defaults to max(rate, 1)
    max_running: int = 0
    weight: float = 1.0

    def validate(self):
        self.tenant = _clean(self.tenant)
        if not self.tenant:
            raise ValidationError("tenant name required")
        if "/" in self.tenant:
            raise ValidationError("tenant name must not contain '/'")
        if self.max_jobs < 0 or self.max_running < 0:
            raise ValidationError("quota counts must be >= 0")
        if self.rate < 0 or self.burst < 0:
            raise ValidationError("rate/burst must be >= 0")
        if self.burst == 0 and self.rate > 0:
            # a zero-depth bucket never admits; default to one second's
            # worth (and at least 1 so sub-1/s rates can ever fire)
            self.burst = max(self.rate, 1.0)
        if self.weight <= 0:
            raise ValidationError("weight must be > 0")

    @property
    def limited(self) -> bool:
        """Whether the scheduler's token bucket applies at all."""
        return self.rate > 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str) -> "TenantQuota":
        d = json.loads(s)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})
