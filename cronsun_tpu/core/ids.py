"""Short unique ids for jobs/rules/groups (reference id.go:16-19 uses
4-byte fastuuid hex; uuid4-derived 8-hex here — same width, same shape)."""

import uuid


def next_id() -> str:
    return uuid.uuid4().hex[:8]
