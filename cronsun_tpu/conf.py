"""Configuration: JSON with @extend composition, token substitution,
defaults, and hot-reload.

Mirrors the reference's config system (conf/conf.go:45-213,
utils/confutil.go:43-93): a root JSON file may name a base file in an
``"@extend:"`` key (the base is loaded first, the child overrides);
``@pwd@`` and ``@root@`` tokens expand to the config file's directory and
its parent; defaults are applied after parsing; a polling watcher detects
mtime changes (3s debounce like the reference's fsnotify path) and emits a
reload event — connection-level settings (store endpoints, web bind) are
deliberately excluded from reload (conf/conf.go:200-213).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Callable, List, Optional

from .tlsutil import Tls

EXTEND_KEY = "@extend:"


@dataclasses.dataclass
class Security:
    open: bool = False
    users: List[str] = dataclasses.field(default_factory=list)
    exts: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Mail:
    enable: bool = False
    host: str = ""
    port: int = 25
    user: str = ""
    password: str = ""
    to: List[str] = dataclasses.field(default_factory=list)
    keepalive: int = 30
    http_api: str = ""


@dataclasses.dataclass
class Web:
    host: str = "0.0.0.0"
    port: int = 7079
    session_ttl: int = 8 * 3600
    auth_enabled: bool = True   # reference Web.Auth.Enabled (base.go:98);
                                # False = every request is an implicit admin


@dataclasses.dataclass
class Config:
    prefix: str = "/cronsun"
    node_ttl: int = 10          # node lease ttl (conf.Ttl)
    lock_ttl: int = 300
    proc_ttl: int = 600
    proc_req: int = 5           # short-run suppression threshold, seconds
    timezone: str = "UTC"
    window_s: int = 4           # planner window per dispatch
    pipelined_step: bool = True  # two-stage scheduler step (plan ∥
                                # build+publish); False = serial path
                                # (rollback switch; mesh planners are
                                # always serial)
    job_capacity: int = 65536
    node_capacity: int = 1024
    default_node_cap: int = 1 << 20
    log_db: str = "cronsun.db"
    log_addr: str = ""          # "host:port" of cronsun-logd; when set the
                                # networked result store replaces log_db
                                # (the reference's Mgo.Hosts, db/mgo.go:24-49)
    log_token: str = ""         # shared secret for log_addr (Mgo credentials)
    store_token: str = ""       # shared secret for the coordination store
                                # (the reference's etcd username/password,
                                # conf/conf.go:66-67)
    store_tls: Tls = dataclasses.field(default_factory=Tls)
    log_tls: Tls = dataclasses.field(default_factory=Tls)
                                # per-channel TLS material (the reference
                                # threads etcd TLS through clientv3.Config,
                                # conf/conf.go:66-67); empty = plaintext.
                                # Clients use ca(+cert/key for mutual TLS);
                                # servers use cert/key(+ca to demand client
                                # certs).  See cronsun_tpu/tlsutil.py.
    checkpoint_dir: str = ""    # scheduler checkpoint directory: the
                                # leader (and warm standbys) persist
                                # their built state there and a restart
                                # restores it + replays the watch delta
                                # instead of cold-loading the store.
                                # "" disables (cold loads only).
    checkpoint_interval: int = 0
                                # seconds between periodic scheduler
                                # checkpoint saves (0 = only on the
                                # `cronsun-ctl checkpoint` trigger)
    checkpoint_delta: bool = True
                                # incremental scheduler checkpoints: a
                                # periodic full (base) save plus small
                                # delta records of the applied watch
                                # events since the last save — save cost
                                # proportional to CHANGE, not state, so
                                # the cadence can tighten at 1M jobs.
                                # False = every save is a full image
                                # (the rollback switch).
    checkpoint_rebase_chain: int = 64
                                # auto-rebase: a full save replaces the
                                # delta chain once it reaches this many
                                # elements (restore folds the whole
                                # chain, so length bounds takeover time)
    checkpoint_rebase_bytes: int = 64 << 20
                                # ... or once the chain's on-disk bytes
                                # cross this bound
    trace_sample_shift: int = 8
                                # fire-lifecycle tracing: head-sample
                                # fires whose trace id's low SHIFT bits
                                # are zero (8 = 1/256).  0 samples every
                                # fire, -1 disables scheduler stamping;
                                # CRONSUN_TRACE=off kills the whole
                                # plane.  Per-job ``trace: true`` and
                                # failed executions sample regardless.
    slo_eval_s: int = 15        # web-tier SLO engine evaluation cadence
                                # (burn-rate windows are 5m/30m/1h/6h;
                                # the scrape ring keeps ~6h of samples)
    compile_cache: str = "~/.cache/cronsun-tpu/xla"
                                # persistent XLA compilation cache: a
                                # restarted scheduler (or a cold failover
                                # standby on the same host) reloads its
                                # compiled planner programs from disk
                                # instead of recompiling (~27 s of a cold
                                # boot measured on CPU; 20-40 s per
                                # program on TPU).  "" disables.
    security: Security = dataclasses.field(default_factory=Security)
    mail: Mail = dataclasses.field(default_factory=Mail)
    web: Web = dataclasses.field(default_factory=Web)

    # dynamic-reload exclusions, like the reference
    _RELOAD_EXCLUDE = ("prefix", "web", "log_db", "log_addr", "log_token",
                       "store_token", "store_tls", "log_tls")


def _substitute(text: str, path: str) -> str:
    pwd = os.path.dirname(os.path.abspath(path))
    return text.replace("@pwd@", pwd).replace("@root@", os.path.dirname(pwd))


def load_file(path: str) -> dict:
    """Load JSON with recursive @extend composition (child overrides base)."""
    with open(path) as f:
        data = json.loads(_substitute(f.read(), path))
    base_name = data.pop(EXTEND_KEY, None)
    if base_name:
        base_path = base_name if os.path.isabs(base_name) else \
            os.path.join(os.path.dirname(os.path.abspath(path)), base_name)
        base = load_file(base_path)
        base.update(data)
        data = base
    return data


def _merge(cfg: Config, data: dict, reload_only: bool = False) -> Config:
    for f in dataclasses.fields(Config):
        name = f.name
        if name.startswith("_") or name not in data:
            continue
        if reload_only and name in Config._RELOAD_EXCLUDE:
            continue
        v = data[name]
        if name == "security":
            v = Security(**v)
        elif name == "mail":
            v = Mail(**v)
        elif name == "web":
            v = Web(**v)
        elif name in ("store_tls", "log_tls"):
            v = Tls(**v)
        setattr(cfg, name, v)
    return cfg


def parse(path: Optional[str] = None) -> Config:
    cfg = Config()
    if path:
        _merge(cfg, load_file(path))
    if cfg.node_ttl <= 0:
        cfg.node_ttl = 10
    if cfg.lock_ttl < 2:
        cfg.lock_ttl = 300
    if cfg.mail.keepalive <= 0:
        cfg.mail.keepalive = 30
    return cfg


class ConfigWatcher:
    """Poll the file's mtime; on change (debounced 3s) re-parse and call
    ``on_reload(cfg)`` with reload-excluded fields preserved."""

    def __init__(self, path: str, cfg: Config,
                 on_reload: Callable[[Config], None],
                 poll_s: float = 1.0, debounce_s: float = 3.0):
        self.path = path
        self.cfg = cfg
        self.on_reload = on_reload
        self.poll_s = poll_s
        self.debounce_s = debounce_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        def run():
            try:
                last_mtime = os.stat(self.path).st_mtime
            except OSError:
                last_mtime = 0
            debounce_left = None
            while not self._stop.wait(self.poll_s):
                try:
                    m = os.stat(self.path).st_mtime
                except OSError:
                    continue
                if m != last_mtime:
                    last_mtime = m
                    debounce_left = self.debounce_s
                if debounce_left is not None:
                    debounce_left -= self.poll_s
                    if debounce_left <= 0:
                        debounce_left = None
                        try:
                            _merge(self.cfg, load_file(self.path),
                                   reload_only=True)
                            self.on_reload(self.cfg)
                        except (OSError, json.JSONDecodeError):
                            pass
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="conf-watcher")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)
