"""Shared entrypoint wiring: flags, conf, logging, store connection."""

from __future__ import annotations

import argparse
from typing import Optional, Tuple

from .. import events, log
from ..conf import Config, ConfigWatcher, parse as parse_conf
from ..core import Keyspace


def base_parser(doc: str, store_required: bool = True) -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=doc)
    ap.add_argument("--conf", default=None, help="JSON config file")
    ap.add_argument("--log-level", default="info",
                    choices=("debug", "info", "warn", "error"))
    if store_required:
        ap.add_argument("--store", default="127.0.0.1:7070",
                        metavar="HOST:PORT",
                        help="coordination store address")
        ap.add_argument("--logsink", default=None, metavar="HOST:PORT",
                        help="networked result store (cronsun-logd) "
                             "address, or a comma-joined SHARD SET "
                             "(h1:7078,h2:7078,...) routed by the "
                             "deterministic job hash; default: conf "
                             "log_addr, else the local log_db SQLite "
                             "file")
    return ap


def setup_common(args) -> Tuple[Config, Keyspace, Optional[ConfigWatcher]]:
    """Logging + conf + hot-reload watcher (reload emits events.WAIT, the
    reference's fsnotify->WAIT wiring, conf/conf.go:159-193)."""
    log.setup(args.log_level)
    cfg = parse_conf(args.conf)
    watcher = None
    if args.conf:
        watcher = ConfigWatcher(
            args.conf, cfg, lambda c: events.emit(events.WAIT, c))
        watcher.start()
    return cfg, Keyspace(cfg.prefix), watcher


def enable_compile_cache(path: str):
    """Persistent XLA compilation cache (conf.compile_cache): restarted
    processes — including a cold failover standby on the same host —
    reload compiled planner programs from disk instead of recompiling.
    Must run before the first jit dispatch; safe to call on any jax
    version (older ones without the knobs just skip it)."""
    import os as _os
    try:
        import jax
        d = _os.path.expanduser(path)
        _os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.3)
    except Exception as e:  # noqa: BLE001 — a cache is an optimization
        log.warnf("compile cache unavailable (%s): %s", path, e)


def server_tls(tls, native: bool, daemon: str):
    """Server-side TLS context from a conf section, or None (plaintext).
    The native servers cannot terminate TLS — exits 2 with the
    terminator hint rather than silently serving plaintext."""
    import sys
    from ..tlsutil import server_context
    ctx = server_context(tls)
    if ctx is not None and native:
        print(f"error: {daemon} TLS requires the Python server (drop "
              "--native or terminate TLS in front of the native daemon "
              "-- native/README.md)", file=sys.stderr)
        raise SystemExit(2)
    return ctx


def connect_store(addr: str, token: str = "", tls=None,
                  timeout: float = 120.0, prefix: str = "/cronsun"):
    """``tls`` is the conf ``store_tls`` section (tlsutil.Tls) or None.

    ``addr`` may be a comma-separated SHARD SET ("h1:7070,h2:7070,…"):
    more than one address returns a routing ShardedStore (same client
    surface, keyspace partitioned by the deterministic token hash —
    store/sharded.py); one address returns the plain RemoteStore after
    the read-only shard-map pin check (a stale single-store config
    pointed at one shard of a sharded layout refuses at startup).

    Each shard entry may itself be an ``a1|a2|a3`` REPLICA GROUP
    (replication plane, repl/): the shard routes to the group's
    leader and rotates on failover.  Empty members ("a|,b", "a||b")
    refuse at parse time with the malformed group named.

    The default RPC timeout is generous because bulk operations scale
    with fleet size: a scheduler cold-loading 1M jobs lists the whole
    cmd prefix in one call (hundreds of MB of JSON — measured over 10 s
    on a 1-core store host, which timed out the old 10 s default
    mid-boot)."""
    from ..tlsutil import client_context
    sslctx = client_context(tls) if tls is not None else None
    addrs = [a.strip() for a in addr.split(",") if a.strip()]
    if not addrs:
        raise ValueError(
            f"store address {addr!r} has no host:port entries")
    from ..store.sharded import connect_sharded
    return connect_sharded(addrs, prefix=prefix, timeout=timeout,
                           token=token, sslctx=sslctx,
                           tls_hostname=tls.hostname if tls else "")


def make_sink(cfg: Config, log_addr: Optional[str] = None):
    """Result-store handle: the networked store when an address is
    configured (processes may live on different machines — the
    reference's Mongo topology), else the local SQLite file.

    ``log_addr`` may be a comma-joined SHARD SET ("h1:7078,h2:7078,…"):
    more than one address returns a routing ShardedJobLogStore (same
    client surface, record space partitioned by the deterministic
    job-id hash — logsink/sharded.py); one address returns the plain
    RemoteJobLogStore after the read-only logmap pin check (a stale
    single-sink config pointed at one shard of a sharded layout
    refuses at startup)."""
    addr = log_addr if log_addr is not None else cfg.log_addr
    if addr:
        from ..logsink.sharded import connect_sharded_sink
        from ..tlsutil import client_context
        return connect_sharded_sink(
            [a.strip() for a in addr.split(",") if a.strip()],
            token=cfg.log_token, sslctx=client_context(cfg.log_tls),
            tls_hostname=cfg.log_tls.hostname)
    from ..logsink import JobLogStore
    return JobLogStore(cfg.log_db)
