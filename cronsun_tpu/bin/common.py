"""Shared entrypoint wiring: flags, conf, logging, store connection."""

from __future__ import annotations

import argparse
from typing import Optional, Tuple

from .. import events, log
from ..conf import Config, ConfigWatcher, parse as parse_conf
from ..core import Keyspace
from ..store.remote import RemoteStore


def base_parser(doc: str, store_required: bool = True) -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=doc)
    ap.add_argument("--conf", default=None, help="JSON config file")
    ap.add_argument("--log-level", default="info",
                    choices=("debug", "info", "warn", "error"))
    if store_required:
        ap.add_argument("--store", default="127.0.0.1:7070",
                        metavar="HOST:PORT",
                        help="coordination store address")
    return ap


def setup_common(args) -> Tuple[Config, Keyspace, Optional[ConfigWatcher]]:
    """Logging + conf + hot-reload watcher (reload emits events.WAIT, the
    reference's fsnotify->WAIT wiring, conf/conf.go:159-193)."""
    log.setup(args.log_level)
    cfg = parse_conf(args.conf)
    watcher = None
    if args.conf:
        watcher = ConfigWatcher(
            args.conf, cfg, lambda c: events.emit(events.WAIT, c))
        watcher.start()
    return cfg, Keyspace(cfg.prefix), watcher


def connect_store(addr: str) -> RemoteStore:
    host, _, port = addr.rpartition(":")
    return RemoteStore(host or "127.0.0.1", int(port))
