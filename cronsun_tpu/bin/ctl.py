"""cronsun-ctl — operator command line over the REST API.

The reference manages the fleet only through the Vue UI; day-2
operations (cron edits from a terminal, scripting a job rollout,
tailing failures) all need a browser.  This CLI drives the same
``/v1/*`` surface (web/server.py, mirroring reference
web/routers.go:17-114) with a persisted session, so everything the UI
can do is scriptable:

    cronsun-ctl --url http://web:7079 login admin@admin.com
    cronsun-ctl jobs
    cronsun-ctl job get default-8a81f3d2
    cronsun-ctl job save job.json
    cronsun-ctl job pause default-8a81f3d2
    cronsun-ctl run default-8a81f3d2 --node worker-3
    cronsun-ctl logs --failed --node worker-3
    cronsun-ctl nodes
    cronsun-ctl metrics

Sessions persist as a cookie jar in ``~/.config/cronsun/session``
(override with --session or CRONSUN_SESSION).  ``--json`` prints raw
API responses for scripting; default output is aligned tables.
"""

from __future__ import annotations

import argparse
import getpass
import http.cookiejar
import json
import os
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

from ..core.backoff import SSE_RECONNECT

DEFAULT_URL = os.environ.get("CRONSUN_URL", "http://127.0.0.1:7079")
DEFAULT_SESSION = os.environ.get(
    "CRONSUN_SESSION",
    os.path.join(os.path.expanduser("~"), ".config", "cronsun", "session"))


class ApiError(RuntimeError):
    def __init__(self, status: int, msg: str):
        super().__init__(f"HTTP {status}: {msg}")
        self.status = status


class Api:
    """Thin urllib client with a persisted cookie jar."""

    def __init__(self, url: str, session_file: str):
        self.url = url.rstrip("/")
        self.session_file = session_file
        self.jar = http.cookiejar.LWPCookieJar(session_file)
        if os.path.exists(session_file):
            try:
                self.jar.load(ignore_discard=True)
            except (OSError, http.cookiejar.LoadError):
                pass
        self.opener = urllib.request.build_opener(
            urllib.request.HTTPCookieProcessor(self.jar))

    def save(self):
        d = os.path.dirname(self.session_file)
        if d:
            os.makedirs(d, exist_ok=True)
        # pre-create 0600 so the session secret is never world-readable,
        # even for the instant between jar.save() and a chmod
        fd = os.open(self.session_file,
                     os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        os.close(fd)
        os.chmod(self.session_file, 0o600)   # pre-existing looser file
        self.jar.save(ignore_discard=True)

    def call(self, method: str, path: str, params: dict = None,
             body=None):
        url = self.url + path
        if params:
            qs = urllib.parse.urlencode(
                {k: v for k, v in params.items() if v not in (None, "")})
            if qs:
                url += "?" + qs
        data = None
        headers = {}
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        try:
            with self.opener.open(req, timeout=30) as resp:
                raw = resp.read().decode()
                ctype = resp.headers.get("Content-Type", "")
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace").strip()
            try:
                detail = json.loads(detail).get("error", detail)
            except (json.JSONDecodeError, AttributeError):
                pass
            raise ApiError(e.code, detail or e.reason)
        except urllib.error.URLError as e:
            raise ApiError(0, f"cannot reach {self.url}: {e.reason}")
        if "json" in ctype:
            return json.loads(raw) if raw else None
        return raw


# ---------------------------------------------------------------------------
# output helpers
# ---------------------------------------------------------------------------

def table(rows, headers):
    """Aligned plain-text table; rows of str-able cells."""
    rows = [[("" if c is None else str(c)) for c in r] for r in rows]
    widths = [len(h) for h in headers]
    for r in rows:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(c))
    fmt = "  ".join("{:<%d}" % w for w in widths)
    print(fmt.format(*headers))
    for r in rows:
        print(fmt.format(*r).rstrip())


def ts(epoch) -> str:
    if not epoch:
        return ""
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(epoch))


def parse_when(s: str) -> float:
    """Epoch seconds, or local 'YYYY-MM-DD[ HH:MM[:SS]]'."""
    try:
        return float(s)
    except ValueError:
        pass
    for fmt in ("%Y-%m-%d %H:%M:%S", "%Y-%m-%d %H:%M", "%Y-%m-%d"):
        try:
            return time.mktime(time.strptime(s, fmt))
        except ValueError:
            continue
    raise SystemExit(f"error: cannot parse time {s!r} "
                     "(epoch or YYYY-MM-DD[ HH:MM[:SS]])")


def _role(role) -> str:
    return "admin" if role == 1 else "developer"


def _read_json_arg(path: str):
    """JSON body from a file argument, with - meaning stdin."""
    if path == "-":
        return json.load(sys.stdin)
    with open(path) as f:
        return json.load(f)


def positive_int(s: str) -> int:
    v = int(s)
    if v < 1:
        raise argparse.ArgumentTypeError("must be >= 1")
    return v


def poll_interval(s: str) -> float:
    v = float(s)
    if v < 0.1:
        raise argparse.ArgumentTypeError(
            "must be >= 0.1 (don't busy-loop the result store)")
    return v


KINDS = {0: "Common", 1: "Alone", 2: "Interval"}


def _gid(d) -> str:
    return f"{d['group']}-{d['id']}"


# ---------------------------------------------------------------------------
# commands
# ---------------------------------------------------------------------------

def cmd_login(api, args):
    pw = args.password if args.password is not None else \
        getpass.getpass(f"password for {args.email}: ")
    # POST body keeps the password out of proxy/access logs (the server
    # keeps the GET-with-query route for UI compatibility)
    out = api.call("POST", "/v1/session",
                   body={"email": args.email, "password": pw})
    api.save()
    print(f"logged in as {out['email']} ({_role(out.get('role'))})")


def cmd_logout(api, args):
    api.call("DELETE", "/v1/session")
    api.save()
    print("logged out")


def cmd_whoami(api, args):
    out = api.call("GET", "/v1/session/me")
    print(json.dumps(out) if args.json else
          f"{out['email']} ({_role(out.get('role'))})")


def cmd_version(api, args):
    print(api.call("GET", "/v1/version"))


def cmd_overview(api, args):
    out = api.call("GET", "/v1/info/overview")
    if args.json:
        print(json.dumps(out, indent=2))
        return
    for k, v in out.items():
        print(f"{k:>16}  {v}")


def cmd_jobs(api, args):
    jobs = api.call("GET", "/v1/jobs", {"group": args.group})
    if args.json:
        print(json.dumps(jobs, indent=2))
        return
    rows = []
    for j in jobs:
        st = j.get("latest_status") or {}
        rows.append([_gid(j), j.get("name"), KINDS.get(j.get("kind"), "?"),
                     "paused" if j.get("pause") else "",
                     len(j.get("rules") or []),
                     j.get("jitter") or 0,
                     st.get("success", 0), st.get("failed", 0)])
    table(rows, ["ID", "NAME", "KIND", "STATE", "RULES", "JITTER",
                 "OK", "FAIL"])


def cmd_job_get(api, args):
    print(json.dumps(api.call("GET", f"/v1/job/{args.id}"), indent=2))


def cmd_job_save(api, args):
    out = api.call("PUT", "/v1/job", body=_read_json_arg(args.file))
    print(f"saved {out['group']}-{out['id']}")


def cmd_job_rm(api, args):
    api.call("DELETE", f"/v1/job/{args.id}")
    print(f"deleted {args.id}")


def _pause(api, job_id: str, pause: bool):
    api.call("POST", f"/v1/job/{job_id}", body={"pause": pause})
    print(f"{'paused' if pause else 'resumed'} {job_id}")


def cmd_job_pause(api, args):
    _pause(api, args.id, True)


def cmd_job_resume(api, args):
    _pause(api, args.id, False)


def cmd_job_nodes(api, args):
    nodes = api.call("GET", f"/v1/job/{args.id}/nodes")
    print(json.dumps(nodes) if args.json else "\n".join(nodes))


def cmd_run(api, args):
    api.call("PUT", f"/v1/job/{args.id}/execute",
             {"node": args.node or ""})
    print(f"run-now fired for {args.id}"
          + (f" on {args.node}" if args.node else " on all eligible nodes"))


def cmd_executing(api, args):
    out = api.call("GET", "/v1/job/executing",
                   {"node": args.node, "jobId": args.job})
    if args.json:
        print(json.dumps(out, indent=2))
        return
    table([[e["node"], f"{e['group']}-{e['jobId']}", e["pid"], e.get("time")]
           for e in out], ["NODE", "JOB", "PID", "STARTED"])


def _log_line(r) -> str:
    took = max(0.0, (r["endTime"] or 0) - (r["beginTime"] or 0))
    status = "ok  " if r["success"] else "FAIL"
    return (f"{ts(r['beginTime'])}  {status}  {r['name']:<20} "
            f"{r['node']:<12} {took:5.1f}s  #{r['id']}")


def _drain_cursor(api, params, cursor: str, as_json: bool) -> str:
    """Drain everything past ``cursor`` via the PR 7 cursor query (one
    page loop), printing each record; returns the advanced cursor."""
    while True:
        out = api.call("GET", "/v1/logs",
                       dict(params, afterId=cursor, page=1,
                            pageSize=500))
        for r in out["list"]:
            print(json.dumps(r) if as_json else _log_line(r),
                  flush=True)
        if out["list"]:
            cursor = out.get("cursor", str(out["list"][-1]["id"]))
        if len(out["list"]) < 500:
            return cursor


def _follow_sse(api, params, cursor: str, as_json: bool):
    """One /v1/stream connection: print pushed records as they land.
    Returns ``(cursor, why)`` — ``why`` is "lost" (server dropped this
    stream; the caller re-lists via the cursor) or "closed" (EOF, a
    drain ``bye``, or a read timeout; the caller reconnects).  Raises
    ApiError on HTTP errors (the fallback signal)."""
    qs = {k: v for k, v in params.items() if v not in (None, "")}
    if cursor:
        qs["cursor"] = cursor
    url = api.url + "/v1/stream"
    if qs:
        url += "?" + urllib.parse.urlencode(qs)
    try:
        resp = api.opener.open(urllib.request.Request(url), timeout=60)
    except urllib.error.HTTPError as e:
        detail = e.read().decode(errors="replace").strip()
        try:
            detail = json.loads(detail).get("error", detail)
        except (json.JSONDecodeError, AttributeError):
            pass
        raise ApiError(e.code, detail or e.reason)
    except urllib.error.URLError as e:
        raise ApiError(0, f"cannot reach {api.url}: {e.reason}")
    event, data = "message", []
    try:
        with resp:
            for raw in resp:
                line = raw.decode("utf-8", "replace").rstrip("\r\n")
                if not line:                   # frame boundary
                    if event == "log" and data:
                        r = json.loads("\n".join(data))
                        print(json.dumps(r) if as_json else _log_line(r),
                              flush=True)
                    elif event == "lost":
                        return cursor, "lost"
                    elif event == "bye":
                        return cursor, "closed"
                    event, data = "message", []
                    continue
                if line.startswith(":"):       # heartbeat comment
                    continue
                field, _, value = line.partition(":")
                value = value[1:] if value.startswith(" ") else value
                if field == "event":
                    event = value
                elif field == "data":
                    data.append(value)
                elif field == "id":
                    cursor = value
    except (OSError, TimeoutError):
        pass                                   # reconnect with cursor
    return cursor, "closed"


def _follow_logs(api, params, interval: float, as_json: bool):
    """tail -f over the result store, cursor-exact: the afterId query
    returns rows in per-shard insertion order, so records inserted with
    old begin_ts — long jobs finishing late — are never missed.  The
    cursor is OPAQUE to this loop (a scalar id for one sink, a
    comma-joined per-shard vector for a sharded one): bootstrap asks
    the server for the tail (``afterId=tail`` — the sink revision IS
    the tail cursor, one cheap read instead of draining history).

    Transport: live push (/v1/stream SSE) when the server offers it —
    records print at publish lag, zero polls — resuming through the
    cursor on reconnects and re-listing on ``lost``.  Falls back to
    the PR 7 cursor-poll protocol when the server predates /v1/stream
    or push is disabled (and for the begin/end/names filters, which
    only the query path evaluates)."""
    try:
        out = api.call("GET", "/v1/logs",
                       dict(params, afterId="tail", page=1, pageSize=1))
        cursor = out.get("cursor")
    except ApiError as e:
        # a pre-cursor server parses afterId with q_int and 400s on
        # "tail" — that's the compat signal, not a failure
        if e.status != 400:
            raise
        cursor = None
    if cursor is None:
        # pre-cursor server: the old probe path — one begin_ts-ordered
        # page finds the newest id, then cursored drains find the true
        # insertion high-water mark
        out = api.call("GET", "/v1/logs", dict(params, page=1, pageSize=1))
        cursor = str(max((r["id"] for r in out["list"]), default=0))
        while True:
            nxt = api.call("GET", "/v1/logs",
                           dict(params, afterId=cursor, page=1,
                                pageSize=500))
            if not nxt["list"]:
                break
            cursor = nxt.get("cursor", str(nxt["list"][-1]["id"]))
    print(f"following (cursor {cursor}; ^C to stop)", file=sys.stderr)
    # the stream evaluates node/ids/tenant/failedOnly server-side;
    # begin/end/names exist only on the query path — poll for those
    sse_ok = not any(params.get(k) for k in ("begin", "end", "names"))
    fails = 0
    while sse_ok:
        t0 = time.monotonic()
        err = None
        try:
            cursor, why = _follow_sse(api, params, cursor, as_json)
        except ApiError as e:
            if e.status in (400, 404, 501, 503):
                # the server doesn't speak /v1/stream (or push is
                # off): that's a capability signal, not an outage —
                # degrade to the poll protocol permanently
                print(f"live stream unavailable ({e}); polling every "
                      f"{interval:g}s", file=sys.stderr)
                break                          # poll fallback below
            # transient: unreachable (status 0), 5xx, mid-connect
            # resets — the cursor survives, so resume the stream on
            # the jittered ladder instead of crashing or falling back
            # to polls against a replica that is merely restarting
            why, err = "error", e
        if err is None and time.monotonic() - t0 >= 2.0:
            fails = 0              # the stream served; outage healed
        if why == "lost":
            # this viewer fell behind (or resumed past the replay
            # window): the cursor re-list is the documented recovery
            print("stream lost; re-listing from cursor",
                  file=sys.stderr)
            cursor = _drain_cursor(api, params, cursor, as_json)
            continue
        fails += 1
        delay = SSE_RECONNECT.delay(fails)
        if err is not None:
            print(f"stream error ({err}); retrying in {delay:.1f}s",
                  file=sys.stderr)
        time.sleep(delay)
    while True:
        time.sleep(interval)
        cursor = _drain_cursor(api, params, cursor, as_json)


def cmd_logs(api, args):
    params = {
        "node": args.node,
        "ids": args.job,
        "names": args.names,
        "tenant": args.tenant,
        "failedOnly": "true" if args.failed else None,
        "latest": "true" if args.latest else None,
        "page": args.page,
        "pageSize": args.size,
    }
    if args.begin:
        params["begin"] = parse_when(args.begin)
    if args.end:
        params["end"] = parse_when(args.end)
    if args.follow:
        if args.latest:
            raise SystemExit("error: --follow cannot combine with "
                             "--latest (the latest view has no cursor)")
        params.pop("page", None)
        params.pop("pageSize", None)
        try:
            _follow_logs(api, params, args.interval, args.json)
        except KeyboardInterrupt:
            pass
        return
    out = api.call("GET", "/v1/logs", params)
    if args.json:
        print(json.dumps(out, indent=2))
        return
    rows = [[r["id"], r["name"], r["node"],
             "ok" if r["success"] else "FAIL",
             ts(r["beginTime"]),
             f"{max(0.0, (r['endTime'] or 0) - (r['beginTime'] or 0)):.1f}s"]
            for r in out["list"]]
    table(rows, ["ID", "NAME", "NODE", "RESULT", "BEGIN", "TOOK"])
    pages = max(1, -(-out["total"] // args.size))
    print(f"({out['total']} records, page {args.page}/{pages})")


def cmd_log(api, args):
    r = api.call("GET", f"/v1/log/{args.id}")
    if args.json:
        print(json.dumps(r, indent=2))
        return
    for k in ("id", "name", "node", "user", "command", "success"):
        print(f"{k:>8}  {r.get(k)}")
    print(f"{'began':>8}  {ts(r['beginTime'])}")
    print(f"{'ended':>8}  {ts(r['endTime'])}")
    print("  output:")
    print(r.get("output") or "(empty)")


def cmd_job_export(api, args):
    """Full job definitions as a JSON array on stdout — the fleet's
    desired state, re-loadable with `job import` (backup, migration,
    code review of cron changes)."""
    jobs = api.call("GET", "/v1/jobs", {"group": args.group})
    for j in jobs:
        j.pop("latest_status", None)     # derived, not desired state
    json.dump(jobs, sys.stdout, indent=2)
    print()


def cmd_job_import(api, args):
    jobs = _read_json_arg(args.file)
    if not isinstance(jobs, list):
        jobs = [jobs]
    n = 0
    for i, j in enumerate(jobs):
        if not isinstance(j, dict):
            raise SystemExit(
                f"error: entry #{i + 1} is not a job object "
                f"({type(j).__name__})\n{n} of {len(jobs)} imported "
                "before the failure")
        try:
            out = api.call("PUT", "/v1/job", body=j)
        except ApiError as e:
            # job saves are idempotent upserts, so re-running the import
            # after fixing the bad entry is safe
            raise SystemExit(
                f"error: entry #{i + 1} ({j.get('name', '?')!r}) refused: "
                f"{e}\n{n} of {len(jobs)} imported before the failure")
        n += 1
        print(f"imported {out['group']}-{out['id']}  {j.get('name', '')}")
    print(f"{n} job(s) imported")


def cmd_nodes(api, args):
    out = api.call("GET", "/v1/nodes")
    if args.json:
        print(json.dumps(out, indent=2))
        return
    table([[n.get("id"), "up" if n.get("connected") else "DOWN",
            "alive" if n.get("alived") else "dead",
            n.get("pid"), ts(n.get("up_ts"))] for n in out],
          ["NODE", "CONN", "MIRROR", "PID", "UP SINCE"])


def cmd_groups(api, args):
    out = api.call("GET", "/v1/node/groups")
    if args.json:
        print(json.dumps(out, indent=2))
        return
    table([[g.get("id"), g.get("name"),
            ",".join(g.get("nids") or [])] for g in out],
          ["ID", "NAME", "NODES"])


def cmd_group_get(api, args):
    print(json.dumps(api.call("GET", f"/v1/node/group/{args.id}"), indent=2))


def cmd_group_save(api, args):
    out = api.call("PUT", "/v1/node/group",
                   body=_read_json_arg(args.file))
    print(f"saved group {out.get('id')}")


def cmd_group_rm(api, args):
    api.call("DELETE", f"/v1/node/group/{args.id}")
    print(f"deleted group {args.id}")


def cmd_accounts(api, args):
    out = api.call("GET", "/v1/admin/accounts")
    if args.json:
        print(json.dumps(out, indent=2))
        return
    table([[a.get("email"), _role(a.get("role")),
            "enabled" if a.get("status") else "disabled"] for a in out],
          ["EMAIL", "ROLE", "STATUS"])


def cmd_account_add(api, args):
    pw = args.password if args.password is not None else \
        getpass.getpass(f"password for new account {args.email}: ")
    role = 1 if args.admin else 2
    api.call("PUT", "/v1/admin/account",
             body={"email": args.email, "password": pw, "role": role,
                   "status": 0 if args.disabled else 1})
    print(f"created {args.email} ({_role(role)})")


def cmd_account_update(api, args):
    body = {"email": args.email}
    if args.role is not None:
        body["role"] = {"admin": 1, "developer": 2}[args.role]
    if args.enable:
        body["status"] = 1
    if args.disable:
        body["status"] = 0
    if args.password is not None:
        if not args.password:
            # the server ignores falsy passwords but still force-logs
            # the account out — refuse the silent no-op
            raise SystemExit("error: --password must not be empty")
        body["password"] = args.password
    if len(body) == 1:
        raise SystemExit("error: nothing to update "
                         "(--role/--enable/--disable/--password)")
    api.call("POST", "/v1/admin/account", body=body)
    print(f"updated {args.email} (any open sessions were logged out)")


def cmd_passwd(api, args):
    old = args.old if args.old is not None else \
        getpass.getpass("current password: ")
    new = args.new if args.new is not None else \
        getpass.getpass("new password: ")
    api.call("POST", "/v1/user/setpwd",
             body={"password": old, "newPassword": new})
    print("password changed")


def cmd_sched_status(api, args):
    """Per-partition scheduler fleet view: who leads each job-space
    slice, its step health, and whether any partition is leaderless —
    a stalled partition must be one command away, not averaged into a
    fleet mean."""
    out = api.call("GET", "/v1/sched")
    if args.json:
        print(json.dumps(out, indent=2))
        return
    p = out.get("partitions")
    print(f"partitions: {p if p else 'unpartitioned'}")
    rows = []
    for d in out.get("instances", []):
        part = d.get("partition")
        rows.append([
            "-" if part is None else part,
            d["instance"],
            "leader" if d.get("is_leader") else "standby",
            d.get("jobs", 0),
            d.get("dispatches_total", 0),
            _fmt_ms(d.get("sched_step_p99_ms")),
            d.get("lease_resigns_total", 0),
            d.get("watch_losses_total", 0),
            d.get("skipped_seconds_total", 0),
        ])
    table(rows, ["PART", "INSTANCE", "ROLE", "JOBS", "DISPATCHES",
                 "STEP_P99", "RESIGNS", "WATCHLOSS", "SKIPPED"])
    missing = out.get("leaderless") or []
    if missing:
        print(f"WARNING: leaderless partition(s): {missing}")


def cmd_repl_status(api, args):
    """Per-shard store replication view (repl/): each replica's role,
    applied revision, lag behind its leader, and fencing epoch —
    follower lag and a deposed or unreachable replica must be one
    command away."""
    out = api.call("GET", "/v1/repl")
    if args.json:
        print(json.dumps(out, indent=2))
        return
    rows = []
    for ent in out.get("shards", []):
        for addr in ent.get("group", []) or \
                sorted(ent.get("replicas", {})):
            st = (ent.get("replicas") or {}).get(addr)
            if not isinstance(st, dict):
                rows.append([ent.get("shard"), addr, "unreachable",
                             "-", "-", "-", "-", "-"])
                continue
            if not st.get("enabled"):
                rows.append([ent.get("shard"), addr, "unreplicated",
                             "-", "-", "-", "-", "-"])
                continue
            lag = st.get("lag_records")
            rows.append([
                ent.get("shard"), addr, st.get("role", "?"),
                st.get("epoch", 0), st.get("applied_rev", 0),
                "-" if lag is None else lag,
                "-" if st.get("role") == "leader"
                else st.get("lag_seconds", 0),
                st.get("ack_mode", "-"),
            ])
    table(rows, ["SHARD", "REPLICA", "ROLE", "EPOCH", "REV",
                 "LAG_RECS", "LAG_S", "ACK"])
    stale = [r for r in rows if r[2] == "unreachable"]
    if stale:
        print(f"WARNING: {len(stale)} unreachable replica(s)")


def cmd_metrics(api, args):
    sys.stdout.write(api.call("GET", "/v1/metrics"))


def cmd_checkpoint(api, args):
    """Trigger the checkpoint plane: store WAL snapshot + scheduler
    state checkpoints (admin)."""
    out = api.call("POST", "/v1/checkpoint")
    if args.json:
        print(json.dumps(out, indent=2))
        return
    if "store_snapshot_rev" in out:
        print(f"store snapshot written at revision "
              f"{out['store_snapshot_rev']} (WAL truncated)")
    else:
        print(f"store snapshot: {out.get('store_snapshot')}")
    print(out.get("scheduler", ""))


def cmd_configurations(api, args):
    print(json.dumps(api.call("GET", "/v1/configurations"), indent=2))


def cmd_checkpoint_compact(api, args):
    """Offline delta-chain compaction: fold every sched.ckpt.d<seq>
    beside the base into ONE element (direct filesystem access, not the
    web API).  Run against a QUIESCED checkpoint dir — compacting under
    a live scheduler makes its next delta a seq gap (which a restore
    then refuses, loudly)."""
    del api
    import os as _os
    from ..checkpoint.sched_ckpt import (CheckpointError, FILE_NAME,
                                         compact_delta_chain)
    path = args.path
    if _os.path.isdir(path):
        path = _os.path.join(path, FILE_NAME)
    try:
        out = compact_delta_chain(path)
    except (CheckpointError, OSError) as e:
        # refusals (torn/gapped/foreign chains, missing base) exit
        # cleanly with the files untouched — protection is not a crash
        raise SystemExit(f"error: {e}")
    if args.json:
        print(json.dumps(out, indent=2))
        return
    if not out["compacted"]:
        print(f"nothing to compact ({out['folded']} chain element(s) "
              f"at {path})")
        return
    print(f"compacted {out['folded']} delta elements -> 1 "
          f"({out['events']} events, chain tip rev {out['rev']})")


# ---------------------------------------------------------------------------
# workflow DAG views
# ---------------------------------------------------------------------------

def cmd_tenants(api, args):
    res = api.call("GET", "/v1/tenants")
    if args.json:
        print(json.dumps(res, indent=2))
        return
    rows = []
    for t in res:
        q = t.get("quota") or {}
        rows.append([t["tenant"], t["jobs"],
                     q.get("max_jobs") or "-", q.get("rate") or "-",
                     q.get("burst") or "-", q.get("max_running") or "-",
                     q.get("weight", 1.0)])
    table(rows, ["TENANT", "JOBS", "MAX_JOBS", "RATE/S", "BURST",
                 "MAX_RUN", "WEIGHT"])


def cmd_tenant_show(api, args):
    res = api.call("GET", f"/v1/tenant/{args.id}")
    if args.json:
        print(json.dumps(res, indent=2))
        return
    q = res.get("quota") or {}
    print(f"tenant:      {res['tenant']}")
    print(f"jobs:        {res['jobs']}"
          + (f" / {q['max_jobs']}" if q.get("max_jobs") else ""))
    if q:
        print(f"fire rate:   {q.get('rate') or 'unlimited'}"
              + (f"/s (burst {q.get('burst')})" if q.get("rate") else ""))
        print(f"max running: {q.get('max_running') or 'unlimited'}")
        print(f"weight:      {q.get('weight', 1.0)}")
    else:
        print("quota:       none (unlimited)")
    live = res.get("live") or {}
    if live:
        print("live (scheduler snapshots):")
        for k in sorted(live):
            print(f"  {k}: {live[k]}")


def cmd_tenant_set(api, args):
    body = {"tenant": args.id}
    for k in ("max_jobs", "rate", "burst", "max_running", "weight"):
        v = getattr(args, k)
        if v is not None:
            body[k] = v
    res = api.call("PUT", "/v1/tenant", body=body)
    if args.json:
        print(json.dumps(res, indent=2))
    else:
        print(f"quota set for tenant {res['tenant']!r}: "
              f"max_jobs={res['max_jobs']} rate={res['rate']}/s "
              f"burst={res['burst']} max_running={res['max_running']} "
              f"weight={res['weight']}")


def cmd_tenant_rm(api, args):
    api.call("DELETE", f"/v1/tenant/{args.id}")
    print(f"quota removed for tenant {args.id!r} (now unlimited)")


def _fmt_ms(v):
    return f"{v:.1f}ms" if isinstance(v, (int, float)) else "-"


def cmd_trace_show(api, args):
    """Render one fire's waterfall: per executing node, the six stage
    durations between the scheduled tick and the flushed record."""
    res = api.call("GET",
                   f"/v1/trace/{urllib.parse.quote(args.job)}/"
                   f"{int(args.second)}")
    if args.json:
        print(json.dumps(res, indent=2))
        return
    print(f"trace {res['trace_id']}  job {res.get('group', '')}/"
          f"{res['job']}  second {res['second']}  "
          f"total {_fmt_ms(res['total_ms'])}")
    from ..trace import STAGES
    rows = []
    for nd in res["nodes"]:
        st = nd.get("stages", {})
        rows.append([nd["node"], "ok" if nd.get("ok") else "FAIL"]
                    + [_fmt_ms(st[s]) if s in st else "-"
                       for s in STAGES]
                    + [_fmt_ms(nd.get("total_ms"))])
    table(rows, ["NODE", "RESULT"] + [s.upper() for s in STAGES]
          + ["TOTAL"])


def cmd_trace_top(api, args):
    """Slowest recent traces (by total or one stage) from the logd
    trace rings."""
    q = f"?n={args.n}"
    if args.stage:
        q += f"&stage={urllib.parse.quote(args.stage)}"
    res = api.call("GET", f"/v1/trace/top{q}")
    if args.json:
        print(json.dumps(res, indent=2))
        return
    rows = []
    for t in res["traces"]:
        worst = max(t.get("nodes", []),
                    key=lambda nd: nd.get("total_ms", 0), default={})
        st = worst.get("stages", {})
        slowest = max(st.items(), key=lambda kv: kv[1])[0] if st else "-"
        rows.append([t.get("grp", ""), t["job"], t["sec"],
                     len(t.get("nodes", [])), _fmt_ms(t["total_ms"]),
                     slowest])
    table(rows, ["GROUP", "JOB", "SECOND", "NODES", "TOTAL",
                 "SLOWEST STAGE"])
    if not rows:
        print("(no traces in the ring — sampling off, or no recent "
              "fires)")


def cmd_slos(api, args):
    res = api.call("GET", "/v1/slos")
    if args.json:
        print(json.dumps(res, indent=2))
        return
    rows = [[s["name"], s.get("scope") or "global", s["target"],
             s.get("latency_ms") or "-"] for s in res]
    table(rows, ["SLO", "SCOPE", "TARGET", "LATENCY_MS"])


def cmd_slo_show(api, args):
    """Current burn rates + alert states from the web tier's engine."""
    res = api.call("GET", "/v1/slo/status")
    if args.json:
        print(json.dumps(res, indent=2))
        return
    if res.get("engine") != "on":
        print("slo engine: off (web server started without one)")
        return
    rows = []
    for name in sorted(res["slos"]):
        st = res["slos"][name]
        b = st.get("burn", {})
        rows.append([name, st.get("scope") or "global",
                     st.get("target"),
                     b.get("5m", 0), b.get("1h", 0),
                     b.get("30m", 0), b.get("6h", 0),
                     st.get("alert") or "-"])
    table(rows, ["SLO", "SCOPE", "TARGET", "BURN 5M", "1H", "30M",
                 "6H", "ALERT"])
    stats = res.get("stats") or {}
    if stats:
        print(f"evals={stats.get('slo_evals_total', 0)} "
              f"alerts={stats.get('slo_alerts_total', 0)} "
              f"notices={stats.get('slo_notices_total', 0)} "
              f"recoveries={stats.get('slo_recoveries_total', 0)}")


def cmd_slo_set(api, args):
    body = {"name": args.name, "scope": args.scope or "",
            "target": args.target}
    if args.latency_ms is not None:
        body["latency_ms"] = args.latency_ms
    res = api.call("PUT", "/v1/slo", body=body)
    print(f"slo {res['name']!r} set: scope="
          f"{res.get('scope') or 'global'} target={res['target']}"
          + (f" latency<={res['latency_ms']}ms"
             if res.get("latency_ms") else ""))


def cmd_slo_rm(api, args):
    api.call("DELETE", f"/v1/slo/{urllib.parse.quote(args.name)}")
    print(f"slo {args.name!r} removed")


def cmd_dag_show(api, args):
    """Render the group's dependency graph: topological order, each
    job's upstreams, misfire policy and in-flight cap, plus broken
    references (missing upstreams)."""
    out = api.call("GET", f"/v1/dag/{urllib.parse.quote(args.group)}")
    if args.json:
        print(json.dumps(out, indent=2))
        return
    if not out["jobs"]:
        print(f"group {args.group!r} has no dep-triggered jobs")
        return
    rows = []
    for j in out["jobs"]:
        d = j.get("deps") or {}
        rows.append([
            j["id"], j.get("name", ""),
            "paused" if j.get("pause") else "",
            " ".join(d.get("on") or []) or "(time-triggered)",
            d.get("misfire", ""),
            d.get("max_in_flight") or "",
        ])
    table(rows, ["JOB", "NAME", "STATE", "UPSTREAMS", "MISFIRE",
                 "MAX-IN-FLIGHT"])
    if out.get("missing"):
        print("\nBROKEN upstream references (dependents hold, never "
              "fire):")
        for dep_id, ups in sorted(out["missing"].items()):
            print(f"  {dep_id} -> missing {', '.join(ups)}")


def cmd_dag_runs(api, args):
    """Latest completed round + in-flight executions per job of the
    group's DAG — the chain's live state (reads the dep/ completion
    keys and the proc registry)."""
    out = api.call("GET",
                   f"/v1/dag/{urllib.parse.quote(args.group)}/runs")
    if args.json:
        print(json.dumps(out, indent=2))
        return
    if not out["jobs"]:
        print(f"group {args.group!r} has no dep-triggered jobs")
        return
    rows = []
    for j in out["jobs"]:
        rows.append([
            j["id"],
            "dep" if j.get("deps") else "time",
            ts(j.get("last_epoch")) or "(never)",
            j.get("last_status", ""),
            j.get("in_flight", 0),
        ])
    table(rows, ["JOB", "TRIGGER", "LAST ROUND", "RESULT", "IN-FLIGHT"])


def cmd_logd_reshard(api, args):
    """Result-plane resharding escape hatch: record ids encode the
    shard count (raw * N + shard), so changing N is a dump/rehash/load
    into a FRESH shard set — this command performs it over the wire
    (logsink/sharded.reshard_sinks), re-encoding every id under the new
    layout and re-pinning the destination logmap.  Talks to the logd
    shards directly, not the web API."""
    del api
    from ..logsink.serve import RemoteJobLogStore

    def connect(addrs):
        conns = []
        try:
            for addr in addrs.split(","):
                host, _, port = addr.strip().rpartition(":")
                conns.append(RemoteJobLogStore(host or "127.0.0.1",
                                               int(port),
                                               token=args.token or ""))
        except BaseException:
            for c in conns:
                c.close()
            raise
        return conns
    from ..logsink.sharded import reshard_sinks
    src = dst = []
    try:
        src = connect(getattr(args, "from"))
        dst = connect(args.to)
        summary = reshard_sinks(
            src, dst, batch=args.batch,
            on_log=lambda m: print(m, file=sys.stderr, flush=True))
    except (RuntimeError, ValueError) as e:
        # refusals (non-empty destination, mismatched logmaps) and
        # malformed addresses exit cleanly — the tool protecting the
        # data is not a crash
        raise SystemExit(f"error: {e}")
    finally:
        for c in src + dst:
            try:
                c.close()
            except OSError:
                pass
    if args.json:
        print(json.dumps(summary, indent=2))
        return
    print(f"resharded {len(src)} -> {len(dst)} shards: "
          f"{summary['records']} records, {summary['nodes']} nodes, "
          f"{summary['accounts']} accounts")
    if summary["stat_shortfall"]:
        print(f"WARNING: {summary['stat_shortfall']} executions counted "
              "in source stats had no surviving record (retention-"
              "evicted); destination counters reflect migrated records "
              "only", file=sys.stderr)
    if summary.get("latest_shortfall"):
        print(f"WARNING: {summary['latest_shortfall']} (job, node) "
              "latest-status rows had no surviving record to rebuild "
              "from and are absent from the destination's latest view",
              file=sys.stderr)


def cmd_fsck(api, args):
    """Offline global-invariant audit (chaos/invariants.fsck): leaked
    dispatch reservations, orphan proc entries, fences without
    execution records, dangling dep completions — plus, when a shard
    is served by an ``a1|a2|a3`` replica group, the replication audit
    (replica state below the min applied revision must match the
    leader's byte-for-byte; divergence is named with its first key).
    Talks to the store (and optionally logd) shards DIRECTLY,
    read-only — the same checks the chaos drills gate on, runnable
    against a live fleet.  Exits nonzero when findings exist."""
    del api
    from ..chaos.invariants import (fsck, render, replication_audit,
                                    to_json)
    from ..core import Keyspace
    from ..store.sharded import connect_sharded
    store = sink = None
    try:
        try:
            store = connect_sharded(
                [a.strip() for a in args.store.split(",") if a.strip()],
                prefix=args.prefix, token=args.token or "")
            if args.logsink:
                from ..logsink.sharded import connect_sharded_sink
                sink = connect_sharded_sink(
                    [a.strip() for a in args.logsink.split(",")
                     if a.strip()],
                    token=args.token or "")
            findings = fsck(store, sink=sink,
                            ks=Keyspace(prefix=args.prefix),
                            stale_order_s=args.stale_order_s,
                            fence_settle_s=args.fence_settle_s)
            findings += replication_audit(store)
        except (RuntimeError, ValueError, OSError) as e:
            raise SystemExit(f"error: {e}")
    finally:
        for c in (store, sink):
            if c is not None:
                try:
                    c.close()
                except Exception:  # noqa: BLE001 — teardown
                    pass
    if args.json:
        print(to_json(findings))
    else:
        print(render(findings))
    raise SystemExit(1 if findings else 0)


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="cronsun-ctl",
        description=__doc__.split("\n\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--url", default=DEFAULT_URL,
                    help=f"web server base URL (default {DEFAULT_URL}, "
                         "env CRONSUN_URL)")
    ap.add_argument("--session", default=DEFAULT_SESSION,
                    help="cookie-jar file (env CRONSUN_SESSION)")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON output (scripting)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def add(name, fn, help_, **kw):
        p = sub.add_parser(name, help=help_, **kw)
        p.set_defaults(fn=fn)
        return p

    p = add("login", cmd_login, "create a session")
    p.add_argument("email")
    p.add_argument("--password", default=None,
                   help="password (prompted when omitted)")
    add("logout", cmd_logout, "destroy the session")
    add("whoami", cmd_whoami, "show the logged-in account")
    add("version", cmd_version, "server version")
    add("overview", cmd_overview, "dashboard numbers")

    p = add("jobs", cmd_jobs, "list jobs")
    p.add_argument("--group", default=None)

    job = sub.add_parser("job", help="job operations")
    jsub = job.add_subparsers(dest="jobcmd", required=True)

    def jadd(name, fn, help_):
        p = jsub.add_parser(name, help=help_)
        p.set_defaults(fn=fn)
        return p
    jadd("get", cmd_job_get, "show one job as JSON").add_argument("id")
    jadd("save", cmd_job_save,
         "create/update a job from a JSON file (or - for stdin)"
         ).add_argument("file")
    jadd("rm", cmd_job_rm, "delete a job").add_argument("id")
    jadd("pause", cmd_job_pause, "pause a job").add_argument("id")
    jadd("resume", cmd_job_resume, "resume a paused job").add_argument("id")
    jadd("nodes", cmd_job_nodes,
         "nodes a job resolves to (include ∪ groups − exclude)"
         ).add_argument("id")
    p = jadd("export", cmd_job_export,
             "dump all job definitions as JSON (re-loadable)")
    p.add_argument("--group", default=None)
    jadd("import", cmd_job_import,
         "load jobs from a JSON array file (or -)").add_argument("file")

    p = add("run", cmd_run, "run a job immediately (bypasses schedule)")
    p.add_argument("id")
    p.add_argument("--node", default=None,
                   help="single node (default: all eligible)")

    p = add("executing", cmd_executing, "what is running right now")
    p.add_argument("--node", default=None)
    p.add_argument("--job", default=None)

    p = add("logs", cmd_logs, "execution history (filters match the UI)")
    p.add_argument("--node", default=None)
    p.add_argument("--job", default=None, help="job id (comma-list ok)")
    p.add_argument("--names", default=None, help="name substring")
    p.add_argument("--tenant", default=None,
                   help="only this tenant's jobs (enforced server-side "
                        "for tenant-pinned accounts)")
    p.add_argument("--failed", action="store_true")
    p.add_argument("--latest", action="store_true",
                   help="latest record per (job, node)")
    p.add_argument("--begin", default=None,
                   help="epoch or YYYY-MM-DD[ HH:MM[:SS]] (local)")
    p.add_argument("--end", default=None)
    p.add_argument("--page", type=positive_int, default=1)
    p.add_argument("--size", type=positive_int, default=50)
    p.add_argument("--follow", "-f", action="store_true",
                   help="poll for new records and stream them (tail -f)")
    p.add_argument("--interval", type=poll_interval, default=2.0,
                   help="--follow poll interval seconds (>= 0.1)")

    add("log", cmd_log, "one execution record with output"
        ).add_argument("id", type=int)
    add("nodes", cmd_nodes, "node liveness (mirror ⋈ live keys)")
    add("groups", cmd_groups, "node groups")

    grp = sub.add_parser("group", help="node-group operations")
    gsub = grp.add_subparsers(dest="groupcmd", required=True)

    def gadd(name, fn, help_):
        p = gsub.add_parser(name, help=help_)
        p.set_defaults(fn=fn)
        return p
    gadd("get", cmd_group_get, "show one group").add_argument("id")
    gadd("save", cmd_group_save,
         "create/update a group from a JSON file (or -)"
         ).add_argument("file")
    gadd("rm", cmd_group_rm,
         "delete a group (scrubs it from job rules)").add_argument("id")

    add("accounts", cmd_accounts, "list accounts (admin)")

    acct = sub.add_parser("account", help="account administration (admin)")
    asub = acct.add_subparsers(dest="acctcmd", required=True)
    p = asub.add_parser("add", help="create an account")
    p.set_defaults(fn=cmd_account_add)
    p.add_argument("email")
    p.add_argument("--password", default=None,
                   help="initial password (prompted when omitted)")
    p.add_argument("--admin", action="store_true",
                   help="Administrator role (default: Developer)")
    p.add_argument("--disabled", action="store_true")
    p = asub.add_parser("update",
                        help="change role/status/password "
                             "(force-logs-out the account)")
    p.set_defaults(fn=cmd_account_update)
    p.add_argument("email")
    p.add_argument("--role", choices=("admin", "developer"), default=None)
    st = p.add_mutually_exclusive_group()
    st.add_argument("--enable", action="store_true")
    st.add_argument("--disable", action="store_true")
    p.add_argument("--password", default=None)

    p = add("passwd", cmd_passwd, "change your own password")
    p.add_argument("--old", default=None, help="prompted when omitted")
    p.add_argument("--new", default=None, help="prompted when omitted")
    sch = sub.add_parser("sched",
                         help="scheduler plane (partition leaders)")
    schsub = sch.add_subparsers(dest="schedcmd", required=True)
    p = schsub.add_parser("status",
                          help="per-partition leaders, step health, "
                               "leaderless partitions")
    p.set_defaults(fn=cmd_sched_status)
    rp = sub.add_parser("repl",
                        help="store replication plane (replica groups)")
    rpsub = rp.add_subparsers(dest="replcmd", required=True)
    p = rpsub.add_parser("status",
                         help="per-shard replica roles, applied "
                              "revisions, lag, fencing epochs")
    p.set_defaults(fn=cmd_repl_status)

    add("metrics", cmd_metrics, "Prometheus metrics text")
    add("checkpoint", cmd_checkpoint,
        "trigger store WAL snapshot + scheduler checkpoints (admin)")
    p = add("checkpoint-compact", cmd_checkpoint_compact,
            "fold a scheduler checkpoint's delta chain into one element "
            "(offline; direct file access)")
    p.add_argument("path", help="checkpoint dir or sched.ckpt path")
    add("configurations", cmd_configurations,
        "security/alarm config exposed to the UI")

    p = add("fsck", cmd_fsck,
            "offline invariant audit (direct store access, read-only; "
            "nonzero exit on findings)")
    p.add_argument("--store", required=True,
                   help="store address(es), host:port[,host:port...]")
    p.add_argument("--logsink", default="",
                   help="logd address(es) for the fence-vs-record "
                        "cross-check (optional)")
    p.add_argument("--prefix", default="/cronsun")
    p.add_argument("--token", default=os.environ.get("CRONSUN_TOKEN", ""),
                   help="store/logsink shared secret (env CRONSUN_TOKEN)")
    p.add_argument("--stale-order-s", type=float, default=900.0,
                   help="dispatch keys older than this count as leaked "
                        "reservations (default 900)")
    p.add_argument("--fence-settle-s", type=float, default=60.0,
                   help="fences older than this must have an execution "
                        "record (default 60 — must stay BELOW the "
                        "fence lease lifetime, lock_ttl+60, or the "
                        "cross-check can never fire)")

    add("tenants", cmd_tenants, "list tenants (jobs + quotas)")
    ten = sub.add_parser("tenant",
                         help="tenant quotas and admission state")
    tsub = ten.add_subparsers(dest="tenantcmd", required=True)
    p = tsub.add_parser("show", help="one tenant's quota, job count "
                                     "and live throttle counters")
    p.set_defaults(fn=cmd_tenant_show)
    p.add_argument("id")
    p = tsub.add_parser("set", help="create/update a tenant quota "
                                    "(admin; omitted fields keep 0 = "
                                    "unlimited)")
    p.set_defaults(fn=cmd_tenant_set)
    p.add_argument("id")
    p.add_argument("--max-jobs", dest="max_jobs", type=int, default=None)
    p.add_argument("--rate", type=float, default=None,
                   help="sustained fires/second (token-bucket refill)")
    p.add_argument("--burst", type=float, default=None,
                   help="bucket depth (default max(rate, 1))")
    p.add_argument("--max-running", dest="max_running", type=int,
                   default=None,
                   help="max outstanding exclusive executions")
    p.add_argument("--weight", type=float, default=None,
                   help="fair-share weight under capacity scarcity")
    p = tsub.add_parser("rm", help="remove a tenant's quota (admin)")
    p.set_defaults(fn=cmd_tenant_rm)
    p.add_argument("id")

    tr = sub.add_parser("trace", help="fire-lifecycle trace plane")
    trsub = tr.add_subparsers(dest="tracecmd", required=True)
    p = trsub.add_parser("show",
                         help="one fire's waterfall: per-stage "
                              "durations tick -> record")
    p.set_defaults(fn=cmd_trace_show)
    p.add_argument("job", help="job id")
    p.add_argument("second", type=int, help="scheduled epoch second")
    p = trsub.add_parser("top",
                         help="slowest recent traces (by total or one "
                              "stage)")
    p.set_defaults(fn=cmd_trace_top)
    p.add_argument("--n", type=int, default=10)
    p.add_argument("--stage", default="",
                   help="sort by one stage: sched publish claim queue "
                        "run record")

    add("slos", cmd_slos, "list SLO specs")
    slo = sub.add_parser("slo", help="SLO burn-rate engine")
    ssub = slo.add_subparsers(dest="slocmd", required=True)
    p = ssub.add_parser("show", help="current burn rates + alert "
                                     "states")
    p.set_defaults(fn=cmd_slo_show)
    p = ssub.add_parser("set", help="create/update an SLO (admin)")
    p.set_defaults(fn=cmd_slo_set)
    p.add_argument("name")
    p.add_argument("--scope", default="",
                   help="'' (global), tenant:<name>, or "
                        "chain:<group>/<job>")
    p.add_argument("--target", type=float, default=0.999,
                   help="good-fire ratio objective (default 0.999)")
    p.add_argument("--latency-ms", dest="latency_ms", type=float,
                   default=None,
                   help="runs longer than this count as bad (pick a "
                        "histogram bucket bound; 0/omitted = "
                        "success-only SLO)")
    p = ssub.add_parser("rm", help="remove an SLO (admin)")
    p.set_defaults(fn=cmd_slo_rm)
    p.add_argument("name")

    dag = sub.add_parser("dag", help="workflow DAG views")
    dsub = dag.add_subparsers(dest="dagcmd", required=True)
    p = dsub.add_parser("show",
                        help="dependency graph of a group (topo order, "
                             "policies, broken refs)")
    p.set_defaults(fn=cmd_dag_show)
    p.add_argument("group")
    p = dsub.add_parser("runs",
                        help="latest round + in-flight state per DAG job")
    p.set_defaults(fn=cmd_dag_runs)
    p.add_argument("group")

    p = add("logd-reshard", cmd_logd_reshard,
            "dump/rehash/load the result store into a new shard count "
            "(destination must be a fresh, empty logd set)")
    p.add_argument("--from", required=True, metavar="H:P,H:P,...",
                   help="current logd shard address list (ALL shards)")
    p.add_argument("--to", required=True, metavar="H:P,...",
                   help="destination logd shard address list (empty set)")
    p.add_argument("--token", default=None,
                   help="logd auth token (default: none)")
    p.add_argument("--batch", type=positive_int, default=500,
                   help="records per cursor page / bulk load (default 500)")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    api = Api(args.url, args.session)
    try:
        args.fn(api, args)
    except ApiError as e:
        if e.status == 401 and args.cmd != "login":
            print("error: not logged in (or session expired) — "
                  "run: cronsun-ctl login EMAIL", file=sys.stderr)
        else:
            # login itself keeps the server detail ("invalid email or
            # password"), not circular advice to run login
            print(f"error: {e}", file=sys.stderr)
        return 1
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
