"""Leader scheduler — run one or more; they elect a leader.

    python -m cronsun_tpu.bin.sched --store H:P [--conf F]
"""

from __future__ import annotations

import sys

from .. import events, log
from ..sched import SchedulerService
from .common import base_parser, connect_store, setup_common


def main(argv=None) -> int:
    ap = base_parser(__doc__)
    ap.add_argument("--node-id", default="scheduler-1")
    ap.add_argument("--profile-port", type=int, default=0, metavar="PORT",
                    help="start a jax.profiler server (TensorBoard-"
                         "connectable) so tick/assign spans can be captured "
                         "live; 0 disables")
    ap.add_argument("--mesh", type=int, default=0, metavar="D",
                    help="shard the planner over a D-device jobs mesh "
                         "(0 = single chip)")
    args = ap.parse_args(argv)
    cfg, ks, watcher = setup_common(args)
    if args.profile_port:
        import jax
        jax.profiler.start_server(args.profile_port)
        log.infof("jax profiler server on :%d", args.profile_port)

    tz = None
    if cfg.timezone and cfg.timezone.upper() != "UTC":
        from zoneinfo import ZoneInfo
        tz = ZoneInfo(cfg.timezone)
    store = connect_store(args.store, token=cfg.store_token, tls=cfg.store_tls)
    planner = None
    if args.mesh > 1:
        from ..parallel.mesh import ShardedTickPlanner, make_mesh
        planner = ShardedTickPlanner(
            make_mesh(args.mesh), job_capacity=cfg.job_capacity,
            node_capacity=cfg.node_capacity, tz=tz)
        log.infof("planner sharded over %d devices", args.mesh)
    sched = SchedulerService(
        store, ks=ks, job_capacity=cfg.job_capacity,
        node_capacity=cfg.node_capacity, window_s=cfg.window_s,
        default_node_cap=cfg.default_node_cap, node_id=args.node_id,
        dispatch_ttl=cfg.lock_ttl, tz=tz, planner=planner)
    sched.start()
    log.infof("cronsun-sched %s up (store %s, tz %s)",
              args.node_id, args.store, cfg.timezone)
    print(f"READY {args.node_id}", flush=True)
    events.on(events.EXIT, sched.stop, store.close)
    if watcher:
        events.on(events.EXIT, watcher.stop)
    events.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
