"""Leader scheduler — run one or more; they elect a leader.

    python -m cronsun_tpu.bin.sched --store H:P [--conf F]
"""

from __future__ import annotations

import json
import os
import sys

from .. import events, log
from ..sched import SchedulerService
from .common import base_parser, connect_store, setup_common


def install_worker_signal_watchdog():
    """Mesh-worker signal policy: first SIGTERM/SIGINT is logged and
    ignored (the worker's normal stop is the leader's release broadcast;
    a rank dying mid-plan wedges the fleet's collectives), a second
    signal — or a single SIGUSR1 — force-exits.

    Escalation must work even while the main thread is parked inside a
    gloo/grpc collective that never returns to the interpreter — a pure
    Python signal handler only runs at bytecode boundaries, so it would
    never fire there.  Instead the C-level wakeup-fd path (written by
    CPython's signal trampoline in whichever thread receives the signal,
    regardless of what the main thread is doing) feeds a watchdog
    thread.  SA_RESTART is restored so the first signal can't surface
    as EINTR mid-collective either.

    SIGTERM caveat (measured, not theory): jax.distributed spawns a
    preemption-notifier thread that sigwait()s SIGTERM and wins the
    shared-pending dequeue race against the main thread's handler —
    SIGTERMs can be swallowed before the wakeup fd sees them, even with
    the signal explicitly unblocked on the main thread.  So the
    RELIABLE force paths for a wedged worker are SIGINT twice (Ctrl-C
    Ctrl-C) or SIGUSR1 once; both appear in the first-signal message
    operators actually see.  Must be called from the main thread."""
    import signal as _signal
    import threading as _threading
    rfd, wfd = os.pipe()
    os.set_blocking(wfd, False)
    _signal.set_wakeup_fd(wfd, warn_on_full_buffer=False)
    for _sig in (_signal.SIGTERM, _signal.SIGINT, _signal.SIGUSR1):
        _signal.signal(_sig, lambda s, f: None)
        _signal.siginterrupt(_sig, False)
    _signal.pthread_sigmask(_signal.SIG_UNBLOCK,
                            {_signal.SIGTERM, _signal.SIGINT,
                             _signal.SIGUSR1})

    def _sig_watchdog():
        seen = 0
        while True:
            try:
                data = os.read(rfd, 64)
            except OSError:
                return
            for b in data:
                if b == _signal.SIGUSR1 or seen:
                    os.write(2, b"mesh worker: force exit\n")
                    os._exit(1)
                seen += 1
                os.write(2, b"mesh worker: first signal ignored "
                            b"(normal stop is the leader's release "
                            b"broadcast; signal again or SIGUSR1 to "
                            b"force exit)\n")
    _threading.Thread(target=_sig_watchdog, daemon=True,
                      name="sig-watchdog").start()


def main(argv=None) -> int:
    ap = base_parser(__doc__)
    ap.add_argument("--node-id", default="scheduler-1")
    ap.add_argument("--profile-port", type=int, default=0, metavar="PORT",
                    help="start a jax.profiler server (TensorBoard-"
                         "connectable) so tick/assign spans can be captured "
                         "live; 0 disables")
    ap.add_argument("--mesh", type=int, default=0, metavar="D",
                    help="shard the planner over a D-device jobs mesh "
                         "(0 = single chip)")
    ap.add_argument("--mesh2d", default=None, metavar="DJxDN",
                    help="2-D (jobs x nodes) mesh instead of --mesh, "
                         "e.g. 4x2 — for fleets whose bitpacked "
                         "eligibility exceeds jobs-sharded HBM")
    ap.add_argument("--mesh-hosts", type=int, default=1, metavar="N",
                    help="multi-host mesh: total participating processes "
                         "(jax.distributed; see --mesh-proc-id)")
    ap.add_argument("--mesh-proc-id", type=int, default=0, metavar="I",
                    help="this process's rank; 0 leads (store + dispatch), "
                         ">0 runs as a mesh worker joining the leader's "
                         "collective plans (no store connection)")
    ap.add_argument("--mesh-coordinator", default="127.0.0.1:8476",
                    metavar="H:P", help="jax.distributed coordinator "
                                        "(rank 0's address)")
    ap.add_argument("--mesh-replicated-bids", action="store_true",
                    help="rollback switch: use the replicated-waterfill "
                         "reconcile (O(fired-bucket) exchange per round) "
                         "instead of bucket-sharded bidding (O(nodes)); "
                         "every rank of a multi-host mesh must agree")
    ap.add_argument("--mesh-demand-format", default="auto",
                    choices=("auto", "dense", "compacted"),
                    metavar="FMT",
                    help="demand wire format for the sharded reconcile: "
                         "auto picks dense vs compacted per plan from "
                         "the collective-bytes crossover; dense/"
                         "compacted pin it (the compacted-gather "
                         "rollback knob); every rank of a multi-host "
                         "mesh must agree")
    ap.add_argument("--health-port", type=int, default=0, metavar="P",
                    help="serve /healthz + /readyz on this port "
                         "(readiness: leader lease / watches / step "
                         "loop; 0 disables)")
    ap.add_argument("--partitions", type=int, default=1, metavar="P",
                    help="partitioned scheduler plane: total number of "
                         "job-space partitions (the fleet runs one "
                         "leader, plus standbys, per partition; the "
                         "first leader pins sched/partmap and "
                         "mismatched counts refuse to start; default "
                         "1 = the unpartitioned scheduler)")
    ap.add_argument("--partition", type=int, default=0, metavar="I",
                    help="this scheduler's partition index in "
                         "[0, --partitions)")
    args = ap.parse_args(argv)
    if args.partitions < 1 or not 0 <= args.partition < args.partitions:
        print(f"error: --partition {args.partition} out of range for "
              f"--partitions {args.partitions}", file=sys.stderr)
        return 2
    if args.partitions > 1 and args.node_id == "scheduler-1":
        # the default node id must not collide across partition
        # processes OR between a partition's leader and its warm
        # standbys launched with the same flags (it keys the leased
        # metrics snapshot — a collision makes the fleet view flap);
        # the pid disambiguates, operators wanting stable instance
        # labels set explicit --node-id
        args.node_id = f"scheduler-p{args.partition}-{os.getpid()}"
    if args.mesh2d is not None:
        try:
            dj, dn = (int(x) for x in args.mesh2d.lower().split("x"))
        except ValueError:
            dj = dn = 0
        if dj < 1 or dn < 1:
            print("error: --mesh2d wants DJxDN with both >= 1 (e.g. 4x2)",
                  file=sys.stderr)
            return 2
        if args.mesh:
            print("error: --mesh and --mesh2d are mutually exclusive",
                  file=sys.stderr)
            return 2
        args.mesh = dj * dn
    if args.mesh_hosts > 1:
        # flag errors must surface BEFORE initialize: it blocks waiting
        # for every rank, and a rank that errors out after connecting
        # would leave the others wedged in the first collective
        if args.mesh < 2:
            print("error: --mesh-hosts requires --mesh D or --mesh2d "
                  "DJxDN (global device count)", file=sys.stderr)
            return 2
        # must run before any device use; the global mesh assembles every
        # host's local devices (ICI within a host, DCN between hosts)
        import jax
        jax.distributed.initialize(
            coordinator_address=args.mesh_coordinator,
            num_processes=args.mesh_hosts, process_id=args.mesh_proc_id)
    cfg, ks, watcher = setup_common(args)
    # only the scheduler compiles planner programs — agents/web/stores
    # must never pay a jax import for a cache they'd never use
    if cfg.compile_cache:
        from .common import enable_compile_cache
        enable_compile_cache(cfg.compile_cache)
    if args.profile_port:
        import jax
        jax.profiler.start_server(args.profile_port)
        log.infof("jax profiler server on :%d", args.profile_port)

    tz = None
    if cfg.timezone and cfg.timezone.upper() != "UTC":
        from zoneinfo import ZoneInfo
        tz = ZoneInfo(cfg.timezone)
    planner = None
    shard_bids = not args.mesh_replicated_bids
    if args.mesh2d is not None:
        from ..parallel.mesh import Sharded2DTickPlanner, make_mesh2d
        planner = Sharded2DTickPlanner(
            make_mesh2d(dj, dn), job_capacity=cfg.job_capacity,
            node_capacity=cfg.node_capacity, tz=tz, shard_bids=shard_bids,
            demand_format=args.mesh_demand_format)
        log.infof("planner sharded over a %dx%d (jobs x nodes) mesh "
                  "(%s bidding, %s demand)", dj, dn,
                  "bucket-sharded" if shard_bids else "replicated",
                  args.mesh_demand_format)
    elif args.mesh > 1:
        from ..parallel.mesh import ShardedTickPlanner, make_mesh
        planner = ShardedTickPlanner(
            make_mesh(args.mesh), job_capacity=cfg.job_capacity,
            node_capacity=cfg.node_capacity, tz=tz, shard_bids=shard_bids,
            demand_format=args.mesh_demand_format)
        log.infof("planner sharded over %d devices (%s bidding, "
                  "%s demand)", args.mesh,
                  "bucket-sharded" if shard_bids else "replicated",
                  args.mesh_demand_format)
    if args.mesh_hosts > 1 and args.mesh_proc_id > 0:
        # mesh worker: no store, no leadership — replay the leader's
        # broadcast deltas and join its collective plans until told to
        # stop (parallel/hostsync.py documents the protocol).  Signal
        # policy: see install_worker_signal_watchdog.
        install_worker_signal_watchdog()
        from ..parallel.hostsync import run_worker
        log.infof("mesh worker %d/%d up (coordinator %s)",
                  args.mesh_proc_id, args.mesh_hosts,
                  args.mesh_coordinator)
        print(f"READY mesh-worker-{args.mesh_proc_id}", flush=True)
        steps = run_worker(planner)
        log.infof("mesh worker released after %d plan steps", steps)
        return 0
    store = connect_store(args.store, token=cfg.store_token, tls=cfg.store_tls,
                          prefix=cfg.prefix)
    if args.partitions > 1:
        # a duplicate --node-id across partition processes silently
        # corrupts the fleet view (the leased metrics snapshot is
        # keyed by instance — one partition's numbers overwrite the
        # other's, readyz pages a healthy partition as leaderless):
        # scheduling itself stays correct, so warn LOUDLY rather than
        # refuse (the colliding snapshot may be our own previous
        # incarnation's unexpired lease)
        try:
            kv = store.get(ks.metrics_key("sched", args.node_id))
            other = (json.loads(kv.value).get("partition")
                     if kv is not None else None)
        except Exception:  # noqa: BLE001 — advisory check only
            other = None
        if other is not None and int(other) != args.partition:
            log.errorf(
                "node-id %r already publishes sched metrics as "
                "partition %s — duplicate --node-id across partitions "
                "corrupts /v1/sched and readyz; give each partition "
                "process a distinct --node-id", args.node_id, other)
    sync_proxy = None
    if args.mesh_hosts > 1:
        from ..parallel.hostsync import PlannerSyncProxy
        planner = sync_proxy = PlannerSyncProxy(planner)
        log.infof("mesh leader: broadcasting plan deltas to %d workers",
                  args.mesh_hosts - 1)
    # single-host mesh planners checkpoint like the plain one (shards
    # host-gather through _fetch, topology-tagged); proxied multi-host
    # planners are still refused by SchedulerService itself (it logs why)
    ckpt_dir = os.path.expanduser(cfg.checkpoint_dir) \
        if cfg.checkpoint_dir else None
    if ckpt_dir and args.partitions > 1:
        # per-partition checkpoint chains: each partition's built state
        # is its own restore point (a foreign partition's checkpoint is
        # refused by the restore's slice validation anyway)
        ckpt_dir = os.path.join(ckpt_dir, f"p{args.partition}")
        os.makedirs(ckpt_dir, exist_ok=True)
    sched = SchedulerService(
        store, ks=ks, job_capacity=cfg.job_capacity,
        node_capacity=cfg.node_capacity, window_s=cfg.window_s,
        default_node_cap=cfg.default_node_cap, node_id=args.node_id,
        dispatch_ttl=cfg.lock_ttl, tz=tz, planner=planner,
        pipelined=None if cfg.pipelined_step else False,
        checkpoint_dir=ckpt_dir,
        checkpoint_interval_s=float(cfg.checkpoint_interval),
        checkpoint_delta=cfg.checkpoint_delta,
        delta_max_chain=cfg.checkpoint_rebase_chain,
        delta_max_bytes=cfg.checkpoint_rebase_bytes,
        trace_shift=cfg.trace_sample_shift,
        partitions=args.partitions, partition=args.partition)
    sched.start()
    health = None
    if args.health_port:
        from ..health import HealthServer

        def leader_check():
            h = sched.health()
            return h["leader"], json.dumps(h)

        def watches_check():
            h = sched.health()
            return h["watches_open"] > 0 and h["loop_alive"], \
                json.dumps(h)
        health = HealthServer(
            {"leader": leader_check, "watches": watches_check},
            port=args.health_port).start()
    if args.partitions > 1:
        log.infof("cronsun-sched %s up (store %s, tz %s, partition "
                  "%d/%d)", args.node_id, args.store, cfg.timezone,
                  args.partition, args.partitions)
    else:
        log.infof("cronsun-sched %s up (store %s, tz %s)",
                  args.node_id, args.store, cfg.timezone)
    print(f"READY {args.node_id}", flush=True)
    if sync_proxy is not None:
        # stop order matters: join the service loop FIRST so no plan
        # broadcast can interleave with the workers' release
        events.on(events.EXIT, sched.stop, sync_proxy.shutdown_workers,
                  store.close)
    else:
        events.on(events.EXIT, sched.stop, store.close)
    if health is not None:
        events.on(events.EXIT, health.stop)
    if watcher:
        events.on(events.EXIT, watcher.stop)
    events.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
