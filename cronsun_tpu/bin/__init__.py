"""Production entrypoints (reference bin/node/server.go, bin/web/server.go).

Each is a real OS process wired through conf + logging + the event bus,
talking to the coordination store over TCP:

    python -m cronsun_tpu.bin.store --port 7070          # the store
    python -m cronsun_tpu.bin.sched --store H:P          # leader scheduler
    python -m cronsun_tpu.bin.node  --store H:P          # execution agent
    python -m cronsun_tpu.bin.web   --store H:P          # API/UI + noticer
"""
