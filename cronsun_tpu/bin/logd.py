"""Result-store server — the rebuild's MongoDB.

    python -m cronsun_tpu.bin.logd [--db FILE] [--host H] [--port P]
                                   [--token T] [--conf F] [--native]

Serves execution logs, latest-status, stats, the node-liveness mirror
and accounts (reference collections in db/mgo.go, job_log.go) over TCP
so agents, web servers and noticers on DIFFERENT machines share one
result store.  With --native the C++ server (native/logd.cc) serves
instead of the Python/SQLite one: same wire protocol and semantics
(tests/test_logsink_remote.py runs the conformance suite against both),
in-memory tables + WAL, bounded retention.  Single-machine deployments
can skip this process and point every entrypoint at the same ``log_db``
file instead.
"""

from __future__ import annotations

import sys

from .. import events, log
from ..logsink import LogSinkServer
from .common import base_parser, server_tls, setup_common


def main(argv=None) -> int:
    ap = base_parser(__doc__, store_required=False)
    ap.add_argument("--db", default=None, metavar="FILE",
                    help="SQLite file (Python) / WAL file (--native); "
                         "default: conf log_db")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7078)
    ap.add_argument("--token", default=None,
                    help="shared secret clients must present "
                         "(default: conf log_token)")
    ap.add_argument("--native", action="store_true",
                    help="serve with the native C++ result store")
    ap.add_argument("--retain", type=int, default=None,
                    help="execution-history retention cap in records, "
                         ">= 1 (stats/latest-status stay exact); "
                         "default: native 1M, Python unbounded")
    args = ap.parse_args(argv)
    if args.retain is not None and args.retain < 1:
        # 0 would mean "unbounded" to the SQLite store but "keep
        # nothing" to the native one — refuse the ambiguity
        print("error: --retain must be >= 1 (omit it for the default)",
              file=sys.stderr)
        return 2
    cfg, ks, watcher = setup_common(args)
    token = cfg.log_token if args.token is None else args.token

    sslctx = server_tls(cfg.log_tls, args.native, "cronsun-logd")
    rc = [0]
    if args.native:
        from ..logsink.native import NativeLogSinkServer
        srv = NativeLogSinkServer(host=args.host, port=args.port,
                                  db=args.db or cfg.log_db,
                                  retain=args.retain, token=token).start()

        def child_died(code: int):
            # don't sit healthy-looking in front of a dead result store
            log.errorf("native logd exited rc=%d; shutting down", code)
            rc[0] = code if code > 0 else 1
            events.shutdown()
        srv.monitor(child_died)
    else:
        srv = LogSinkServer(db_path=args.db or cfg.log_db,
                            host=args.host, port=args.port,
                            token=token, sslctx=sslctx,
                            retain=args.retain or 0).start()
    log.infof("cronsun-logd serving on %s:%d (db %s)%s", srv.host, srv.port,
              args.db or cfg.log_db,
              " (tls)" if sslctx is not None else "")
    print(f"READY {srv.host}:{srv.port}", flush=True)
    events.on(events.EXIT, srv.stop)
    if watcher:
        events.on(events.EXIT, watcher.stop)
    events.wait()
    return rc[0]


if __name__ == "__main__":
    sys.exit(main())
