"""Result-store server — the rebuild's MongoDB.

    python -m cronsun_tpu.bin.logd [--db FILE] [--host H] [--port P]
                                   [--token T] [--conf F] [--native]

Serves execution logs, latest-status, stats, the node-liveness mirror
and accounts (reference collections in db/mgo.go, job_log.go) over TCP
so agents, web servers and noticers on DIFFERENT machines share one
result store.  With --native the C++ server (native/logd.cc) serves
instead of the Python/SQLite one: same wire protocol and semantics
(tests/test_logsink_remote.py runs the conformance suite against both),
in-memory tables + WAL, bounded retention.  Single-machine deployments
can skip this process and point every entrypoint at the same ``log_db``
file instead.
"""

from __future__ import annotations

import sys

from .. import events, log
from ..logsink import LogSinkServer
from .common import base_parser, server_tls, setup_common


def main(argv=None) -> int:
    ap = base_parser(__doc__, store_required=False)
    ap.add_argument("--db", default=None, metavar="FILE",
                    help="SQLite file (Python) / WAL file (--native); "
                         "default: conf log_db")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7078)
    ap.add_argument("--token", default=None,
                    help="shared secret clients must present "
                         "(default: conf log_token)")
    ap.add_argument("--native", action="store_true",
                    help="serve with the native C++ result store")
    ap.add_argument("--retain", type=int, default=None,
                    help="execution-history retention cap in records, "
                         ">= 1 (stats/latest-status stay exact); "
                         "default: native 1M, Python unbounded")
    ap.add_argument("--hot-days", type=int, default=0, metavar="D",
                    help="tiered retention: keep D whole UTC days of "
                         "records HOT (in memory / SQL); older days age "
                         "into immutable per-day segment files "
                         "(FILE.segs/<day>.seg) the history queries "
                         "merge back in.  0 (default) = no day aging; "
                         "CRONSUN_TIERING=off also disables the hot "
                         "read mirrors entirely")
    ap.add_argument("--health-port", type=int, default=0, metavar="P",
                    help="serve /healthz + /readyz on this port "
                         "(readiness: every shard accepting TCP + the "
                         "WAL/DB directory writable; 0 disables)")
    ap.add_argument("--shards", type=int, default=1, metavar="N",
                    help="serve a RESULT-PLANE SHARD SET: N logd "
                         "servers on ports port..port+N-1, each with "
                         "its own DB/WAL sidecar (FILE.s<i>) — clients "
                         "connect with the comma-joined address list "
                         "and route by the deterministic job hash "
                         "(logsink/sharded.py)")
    args = ap.parse_args(argv)
    if args.retain is not None and args.retain < 1:
        # 0 would mean "unbounded" to the SQLite store but "keep
        # nothing" to the native one — refuse the ambiguity
        print("error: --retain must be >= 1 (omit it for the default)",
              file=sys.stderr)
        return 2
    if args.shards < 1:
        ap.error(f"--shards must be >= 1 (got {args.shards})")
    if args.hot_days < 0:
        ap.error(f"--hot-days must be >= 0 (got {args.hot_days})")
    cfg, ks, watcher = setup_common(args)
    token = cfg.log_token if args.token is None else args.token

    sslctx = server_tls(cfg.log_tls, args.native, "cronsun-logd")
    rc = [0]
    servers = []
    db_base = args.db or cfg.log_db

    def shard_db(i):
        # N=1 keeps the plain FILE name (and an existing pre-shard DB);
        # :memory: stays :memory: — each server owns its own anyway
        if args.shards == 1 or db_base == ":memory:":
            return db_base
        return f"{db_base}.s{i}"

    def shard_port(i):
        # --port 0 = ephemeral: every shard picks its own free port
        # (0+i would try to bind fixed low ports); the READY line
        # carries the actual bound addresses either way
        return args.port + i if args.port else 0

    if args.native:
        from ..logsink.native import NativeLogSinkServer

        def child_died(code: int):
            # don't sit healthy-looking in front of a dead result store
            log.errorf("native logd exited rc=%d; shutting down", code)
            rc[0] = code if code > 0 else 1
            events.shutdown()
        for i in range(args.shards):
            srv = NativeLogSinkServer(host=args.host, port=shard_port(i),
                                      db=shard_db(i), retain=args.retain,
                                      hot_days=args.hot_days or None,
                                      token=token).start()
            srv.monitor(child_died)
            servers.append(srv)
    else:
        for i in range(args.shards):
            servers.append(LogSinkServer(db_path=shard_db(i),
                                         host=args.host,
                                         port=shard_port(i),
                                         token=token, sslctx=sslctx,
                                         retain=args.retain or 0,
                                         hot_days=args.hot_days).start())
    addrs = ",".join(f"{s.host}:{s.port}" for s in servers)
    if args.shards == 1:
        log.infof("cronsun-logd serving on %s (db %s)%s", addrs, db_base,
                  " (tls)" if sslctx is not None else "")
    else:
        log.infof("cronsun-logd serving %d shards on %s (db %s.s<i>)%s",
                  args.shards, addrs, db_base,
                  " (tls)" if sslctx is not None else "")
    print(f"READY {addrs}", flush=True)
    if args.health_port:
        from ..health import HealthServer, tcp_accept_check, \
            wal_writable_check
        checks = {"wal": wal_writable_check(
            None if db_base == ":memory:" else db_base)}
        for i, s in enumerate(servers):
            checks[f"shard{i}"] = tcp_accept_check(s.host, s.port)
        health = HealthServer(checks, port=args.health_port).start()
        events.on(events.EXIT, health.stop)
    for s in servers:
        events.on(events.EXIT, s.stop)
    if watcher:
        events.on(events.EXIT, watcher.stop)
    events.wait()
    return rc[0]


if __name__ == "__main__":
    sys.exit(main())
