"""Result-store server — the rebuild's MongoDB.

    python -m cronsun_tpu.bin.logd [--db FILE] [--host H] [--port P]
                                   [--token T] [--conf F]

Serves execution logs, latest-status, stats, the node-liveness mirror
and accounts (reference collections in db/mgo.go, job_log.go) over TCP
so agents, web servers and noticers on DIFFERENT machines share one
result store.  Single-machine deployments can skip this process and
point every entrypoint at the same ``log_db`` file instead.
"""

from __future__ import annotations

import sys

from .. import events, log
from ..logsink import LogSinkServer
from .common import base_parser, setup_common


def main(argv=None) -> int:
    ap = base_parser(__doc__, store_required=False)
    ap.add_argument("--db", default=None, metavar="FILE",
                    help="SQLite file (default: conf log_db)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7078)
    ap.add_argument("--token", default=None,
                    help="shared secret clients must present "
                         "(default: conf log_token)")
    args = ap.parse_args(argv)
    cfg, ks, watcher = setup_common(args)

    srv = LogSinkServer(db_path=args.db or cfg.log_db,
                        host=args.host, port=args.port,
                        token=cfg.log_token if args.token is None
                        else args.token).start()
    log.infof("cronsun-logd serving on %s:%d (db %s)", srv.host, srv.port,
              args.db or cfg.log_db)
    print(f"READY {srv.host}:{srv.port}", flush=True)
    events.on(events.EXIT, srv.stop)
    if watcher:
        events.on(events.EXIT, watcher.stop)
    events.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
