"""Coordination store server — the rebuild's etcd.

    python -m cronsun_tpu.bin.store [--host H] [--port P] [--conf F]
"""

from __future__ import annotations

import sys

from .. import events, log
from ..store.remote import StoreServer
from .common import base_parser, setup_common


def main(argv=None) -> int:
    ap = base_parser(__doc__, store_required=False)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7070)
    args = ap.parse_args(argv)
    cfg, ks, watcher = setup_common(args)

    srv = StoreServer(host=args.host, port=args.port).start()
    log.infof("cronsun-store serving on %s:%d", srv.host, srv.port)
    print(f"READY {srv.host}:{srv.port}", flush=True)
    events.on(events.EXIT, srv.stop)
    if watcher:
        events.on(events.EXIT, watcher.stop)
    events.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
