"""Coordination store server — the rebuild's etcd.

    python -m cronsun_tpu.bin.store [--host H] [--port P] [--conf F]
                                    [--native]

With --native the C++ server (native/stored.cc) serves instead of the
Python one: same wire protocol and semantics (the conformance suite in
tests/test_remote_store.py runs against both), no GIL, O(log n) prefix
scans — the production choice.
"""

from __future__ import annotations

import sys

from .. import events, log
from ..store.remote import StoreServer
from .common import base_parser, server_tls, setup_common


def main(argv=None) -> int:
    ap = base_parser(__doc__, store_required=False)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7070)
    ap.add_argument("--native", action="store_true",
                    help="serve with the native C++ store")
    ap.add_argument("--wal", default=None, metavar="FILE",
                    help="write-ahead log + snapshot sidecar (FILE and "
                         "FILE.snap): state survives restarts; boot is "
                         "load-snapshot + replay-tail (both backends)")
    ap.add_argument("--compact-wal-bytes", type=int, default=-1,
                    metavar="N",
                    help="snapshot + truncate the WAL once it exceeds N "
                         "bytes — bounds restart replay by snapshot "
                         "cadence (default: backend default, 256 MiB; "
                         "0 disables size-triggered compaction)")
    ap.add_argument("--token", default=None,
                    help="shared secret clients must present "
                         "(default: conf store_token)")
    ap.add_argument("--stripes", type=int, default=0,
                    help="keyspace lock stripes (0 = backend default, "
                         "16); more stripes = more concurrent writers "
                         "before lock contention")
    ap.add_argument("--snapshot-staggered", choices=("on", "off"),
                    default="on",
                    help="snapshot imaging: 'on' (default) images "
                         "stripes one at a time under their own locks "
                         "against a pinned revision (copy-on-write side "
                         "buffers; writers stall at most one stripe's "
                         "copy); 'off' = the full-lock hold (rollback)")
    ap.add_argument("--shards", type=int, default=1, metavar="N",
                    help="serve a SHARD SET: N store servers on ports "
                         "port..port+N-1, each with its own WAL "
                         "(FILE.s<i>) — clients connect with the "
                         "comma-joined address list and route by the "
                         "deterministic key hash (store/sharded.py)")
    ap.add_argument("--health-port", type=int, default=0, metavar="P",
                    help="serve /healthz + /readyz on this port "
                         "(readiness: every shard accepting TCP + the "
                         "WAL directory writable; on a replica the "
                         "'leader' check 503s followers; 0 disables)")
    ap.add_argument("--repl-group", default="", metavar="A1|A2|A3",
                    help="replication plane (repl/): serve as ONE "
                         "member of this '|'-joined replica group "
                         "(every member lists the same group).  Member "
                         "0 boots as leader, the rest as followers "
                         "shipping the WAL record stream; requires "
                         "--shards 1 (replicate each shard as its own "
                         "process/group)")
    ap.add_argument("--repl-self", default="", metavar="HOST:PORT",
                    help="this server's own address within "
                         "--repl-group (default: the bound host:port)")
    ap.add_argument("--repl-ack", choices=("async", "quorum"),
                    default="async",
                    help="'async' (default): client writes ack after "
                         "the leader's local apply — today's latency, "
                         "single-copy durability until shipped; "
                         "'quorum': acks wait for >= 1 follower to "
                         "hold the write, so an acked write survives "
                         "losing the leader")
    ap.add_argument("--repl-promote-after", type=float, default=3.0,
                    metavar="S",
                    help="follower takeover grace: promote after the "
                         "leader has been unreachable this long "
                         "(default 3s)")
    args = ap.parse_args(argv)
    if args.shards < 1:
        ap.error(f"--shards must be >= 1 (got {args.shards})")
    if args.repl_group:
        members = [m.strip() for m in args.repl_group.split("|")]
        if any(not m for m in members) or not members:
            ap.error(f"--repl-group {args.repl_group!r} has an empty "
                     "member (want addr1|addr2|...)")
        if args.shards != 1:
            ap.error("--repl-group requires --shards 1: replicate a "
                     "shard set by launching each shard as its own "
                     "replica-group process set")
    cfg, ks, watcher = setup_common(args)

    token = cfg.store_token if args.token is None else args.token
    sslctx = server_tls(cfg.store_tls, args.native, "cronsun-store")
    if args.repl_group and args.native:
        # the native server does not speak the repl_* wire ops yet —
        # refuse loudly (ROADMAP: "native stored.cc replication
        # follow-on") instead of silently serving an unreplicated shard
        print("error: --repl-group requires the Python server (drop "
              "--native; native stored.cc replication is a named "
              "ROADMAP follow-on)", file=sys.stderr)
        return 2
    return _serve_shard_set(args, token, sslctx, watcher)


def _serve_shard_set(args, token, sslctx, watcher) -> int:
    """One supervising process, N shard servers on consecutive ports
    (N=1 is the ordinary single store on args.port with the plain FILE
    WAL name).  Each shard is an ordinary store server with its own WAL
    + snapshot sidecar (FILE.s<i>); the partitioning lives entirely in
    the clients' routing hash, so a shard set can equally be launched
    as N independent ``cronsun-store`` processes across machines (the
    production layout — docs/OPERATIONS.md)."""
    rc = [0]
    servers = []

    def shard_wal(i):
        if not args.wal:
            return None
        # N=1 keeps the plain FILE name (and its existing snapshot
        # sidecar from a pre-shard deployment)
        return args.wal if args.shards == 1 else f"{args.wal}.s{i}"

    def shard_port(i):
        # --port 0 = ephemeral: every shard picks its own free port
        # (0+i would try to bind fixed low ports); the READY line
        # carries the actual bound addresses either way
        return args.port + i if args.port else 0

    if args.native:
        from ..store.native import NativeStoreServer

        def child_died(code: int):
            # the wrapper must not sit healthy-looking in front of a dead
            # store — exit so process supervision restarts the set
            log.errorf("native store exited rc=%d; shutting down", code)
            rc[0] = code if code > 0 else 1   # signal deaths -> plain 1
            events.shutdown()
        for i in range(args.shards):
            srv = NativeStoreServer(host=args.host, port=shard_port(i),
                                    wal=shard_wal(i), token=token,
                                    stripes=args.stripes,
                                    compact_wal_bytes=args.compact_wal_bytes,
                                    snapshot_staggered=(
                                        args.snapshot_staggered == "on")
                                    ).start()
            srv.monitor(child_died)
            servers.append(srv)
    else:
        from ..store.memstore import MemStore
        for i in range(args.shards):
            kw0 = {"snapshot_staggered": args.snapshot_staggered == "on"}
            store = MemStore(stripes=args.stripes, **kw0) \
                if args.stripes > 0 else MemStore(**kw0)
            if args.wal:
                # replay (snapshot + tail) BEFORE serving: no concurrent
                # clients may observe a half-replayed keyspace
                kw = {}
                if args.compact_wal_bytes >= 0:   # 0 = disable, -1 = default
                    kw["compact_bytes"] = args.compact_wal_bytes
                store.open_wal(shard_wal(i), **kw)
            srv = StoreServer(store=store, host=args.host,
                              port=shard_port(i), token=token,
                              sslctx=sslctx)
            if args.repl_group:
                # attach the repl manager BEFORE serving so no client
                # op can race the follower-refusal / quorum wiring
                from ..repl import ReplManager
                members = [m.strip()
                           for m in args.repl_group.split("|")]
                self_addr = args.repl_self or f"{srv.host}:{srv.port}"
                srv.attach_repl(ReplManager(
                    store, self_addr, members, ack_mode=args.repl_ack,
                    token=token,
                    promote_after=args.repl_promote_after))
            srv.start()
            if srv.repl is not None:
                srv.repl.start()
            servers.append(srv)
    addrs = ",".join(f"{s.host}:{s.port}" for s in servers)
    if args.shards == 1:
        log.infof("cronsun-store serving on %s%s", addrs,
                  " (tls)" if sslctx is not None else "")
    else:
        log.infof("cronsun-store serving %d shards on %s%s", args.shards,
                  addrs, " (tls)" if sslctx is not None else "")
    print(f"READY {addrs}", flush=True)
    if args.health_port:
        from ..health import HealthServer, tcp_accept_check, \
            wal_writable_check
        checks = {"wal": wal_writable_check(args.wal)}
        for i, s in enumerate(servers):
            checks[f"shard{i}"] = tcp_accept_check(s.host, s.port)
        mgr = getattr(servers[0], "repl", None)
        if mgr is not None:
            # the PR 14 standby pattern: a FOLLOWER fails exactly the
            # named 'leader' check (503 from /readyz keeps it out of
            # writer rotation) while shard/wal checks stay green
            checks["leader"] = lambda: (
                mgr.role() == "leader",
                f"role={mgr.role()} epoch={mgr.store.repl_epoch()}")
        health = HealthServer(checks, port=args.health_port).start()
        events.on(events.EXIT, health.stop)
    for s in servers:
        events.on(events.EXIT, s.stop)
    if watcher:
        events.on(events.EXIT, watcher.stop)
    events.wait()
    return rc[0]


if __name__ == "__main__":
    sys.exit(main())
