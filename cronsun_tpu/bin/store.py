"""Coordination store server — the rebuild's etcd.

    python -m cronsun_tpu.bin.store [--host H] [--port P] [--conf F]
                                    [--native]

With --native the C++ server (native/stored.cc) serves instead of the
Python one: same wire protocol and semantics (the conformance suite in
tests/test_remote_store.py runs against both), no GIL, O(log n) prefix
scans — the production choice.
"""

from __future__ import annotations

import sys

from .. import events, log
from ..store.remote import StoreServer
from .common import base_parser, server_tls, setup_common


def main(argv=None) -> int:
    ap = base_parser(__doc__, store_required=False)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7070)
    ap.add_argument("--native", action="store_true",
                    help="serve with the native C++ store")
    ap.add_argument("--wal", default=None, metavar="FILE",
                    help="write-ahead log + snapshot sidecar (FILE and "
                         "FILE.snap): state survives restarts; boot is "
                         "load-snapshot + replay-tail (both backends)")
    ap.add_argument("--compact-wal-bytes", type=int, default=-1,
                    metavar="N",
                    help="snapshot + truncate the WAL once it exceeds N "
                         "bytes — bounds restart replay by snapshot "
                         "cadence (default: backend default, 256 MiB; "
                         "0 disables size-triggered compaction)")
    ap.add_argument("--token", default=None,
                    help="shared secret clients must present "
                         "(default: conf store_token)")
    ap.add_argument("--stripes", type=int, default=0,
                    help="keyspace lock stripes (0 = backend default, "
                         "16); more stripes = more concurrent writers "
                         "before lock contention")
    args = ap.parse_args(argv)
    cfg, ks, watcher = setup_common(args)

    token = cfg.store_token if args.token is None else args.token
    sslctx = server_tls(cfg.store_tls, args.native, "cronsun-store")
    rc = [0]
    if args.native:
        from ..store.native import NativeStoreServer
        srv = NativeStoreServer(host=args.host, port=args.port,
                                wal=args.wal, token=token,
                                stripes=args.stripes,
                                compact_wal_bytes=args.compact_wal_bytes
                                ).start()

        def child_died(code: int):
            # the wrapper must not sit healthy-looking in front of a dead
            # store — exit so process supervision restarts the pair
            log.errorf("native store exited rc=%d; shutting down", code)
            rc[0] = code if code > 0 else 1   # signal deaths -> plain 1
            events.shutdown()
        srv.monitor(child_died)
    else:
        from ..store.memstore import MemStore
        store = MemStore(stripes=args.stripes) if args.stripes > 0 \
            else MemStore()
        if args.wal:
            # replay (snapshot + tail) BEFORE serving: no concurrent
            # clients may observe a half-replayed keyspace
            kw = {}
            if args.compact_wal_bytes >= 0:   # 0 = disable, -1 = default
                kw["compact_bytes"] = args.compact_wal_bytes
            store.open_wal(args.wal, **kw)
        srv = StoreServer(store=store, host=args.host, port=args.port,
                          token=token, sslctx=sslctx).start()
    log.infof("cronsun-store serving on %s:%d%s", srv.host, srv.port,
              " (tls)" if sslctx is not None else "")
    print(f"READY {srv.host}:{srv.port}", flush=True)
    events.on(events.EXIT, srv.stop)
    if watcher:
        events.on(events.EXIT, watcher.stop)
    events.wait()
    return rc[0]


if __name__ == "__main__":
    sys.exit(main())
