"""Execution agent — one per machine (reference bin/node/server.go:23-70).

    python -m cronsun_tpu.bin.node --store H:P [--node-id ID] [--conf F]
"""

from __future__ import annotations

import sys

from .. import events, log
from ..core.errors import DuplicateNode
from ..node.agent import NodeAgent
from .common import base_parser, connect_store, make_sink, setup_common


def main(argv=None) -> int:
    ap = base_parser(__doc__)
    ap.add_argument("--node-id", default=None,
                    help="stable node identity (default: local IP)")
    args = ap.parse_args(argv)
    cfg, ks, watcher = setup_common(args)

    store = connect_store(args.store, token=cfg.store_token, tls=cfg.store_tls,
                          prefix=cfg.prefix)
    sink = make_sink(cfg, args.logsink)
    fatal: list = []

    def on_fatal(e):
        fatal.append(e)
        events.shutdown()

    agent = NodeAgent(store, sink, node_id=args.node_id, ks=ks,
                      ttl=cfg.node_ttl, proc_ttl=cfg.proc_ttl,
                      lock_ttl=cfg.lock_ttl, proc_req=cfg.proc_req,
                      on_fatal=on_fatal,
                      trace_shift=cfg.trace_sample_shift)
    try:
        agent.start()
    except DuplicateNode as e:
        log.errorf("%s", e)
        return 1
    log.infof("cronsun-node %s up (store %s)", agent.id, args.store)
    print(f"READY {agent.id}", flush=True)

    def reload_conf(c):
        # dynamic knobs only — the reference reloads the proc lease the
        # same way (proc.go:37-52)
        agent.ttl = c.node_ttl
        agent.proc_ttl = c.proc_ttl
        agent.lock_ttl = c.lock_ttl
        agent.proc_req = c.proc_req
        log.infof("config reloaded")
    events.on(events.WAIT, reload_conf)
    events.on(events.EXIT, agent.stop, store.close)
    if watcher:
        events.on(events.EXIT, watcher.stop)
    events.wait()
    return 1 if fatal else 0


if __name__ == "__main__":
    sys.exit(main())
