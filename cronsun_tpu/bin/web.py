"""Web/API server + noticer host (reference bin/web/server.go:24-88).

    python -m cronsun_tpu.bin.web --store H:P [--port P] [--conf F]
"""

from __future__ import annotations

import sys

from .. import events, log
from ..noticer import HttpNoticer, MailNoticer, Notice, NoticerHost
from ..web import ApiServer
from .common import base_parser, connect_store, make_sink, setup_common


class LogSender:
    """Fallback noticer: failures land in the log instead of the void."""

    def send(self, notice: Notice):
        log.warnf("notice: %s — %s", notice.subject, notice.body)


def main(argv=None) -> int:
    ap = base_parser(__doc__)
    ap.add_argument("--host", default=None)
    ap.add_argument("--port", type=int, default=None)
    args = ap.parse_args(argv)
    cfg, ks, watcher = setup_common(args)

    store = connect_store(args.store, token=cfg.store_token, tls=cfg.store_tls,
                          prefix=cfg.prefix)
    sink = make_sink(cfg, args.logsink)
    # SLO engine: multi-window burn-rate evaluation over the agents'
    # scraped execution counters, paging through the noticer this
    # process hosts (web/slo.py)
    from ..web.slo import SloEngine
    slo = SloEngine(store, ks=ks, interval_s=cfg.slo_eval_s).start()
    api = ApiServer(store, sink, ks=ks, security=cfg.security,
                    alarm=cfg.mail.enable,
                    auth_enabled=cfg.web.auth_enabled,
                    host=args.host or cfg.web.host,
                    port=cfg.web.port if args.port is None else args.port,
                    slo_engine=slo)
    api.start()

    if cfg.mail.enable and cfg.mail.host:
        sender = MailNoticer(cfg.mail.host, cfg.mail.port, cfg.mail.user,
                             cfg.mail.password, default_to=cfg.mail.to,
                             keepalive=cfg.mail.keepalive)
    elif cfg.mail.enable and cfg.mail.http_api:
        sender = HttpNoticer(cfg.mail.http_api)
    else:
        sender = LogSender()
    noticer = NoticerHost(store, sink, sender, ks=ks)
    noticer.start()

    log.infof("cronsun-web on %s:%d (store %s)", api.host, api.port,
              args.store)
    print(f"READY {api.host}:{api.port}", flush=True)
    events.on(events.EXIT, noticer.stop, api.stop, slo.stop, store.close)
    if watcher:
        events.on(events.EXIT, watcher.stop)
    events.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
