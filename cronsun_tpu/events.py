"""Process-wide event bus + signal wait (reference event/event.go:20-94).

On/Emit/Off with handler dedupe by identity; Wait() blocks until
SIGINT/SIGTERM, then emits EXIT — the shutdown fan-out the entrypoints use.
"""

from __future__ import annotations

import signal
import threading
from typing import Callable, Dict, List

EXIT = "exit"
WAIT = "wait"   # config reloaded (reference: fsnotify -> WAIT)

_lock = threading.Lock()
_handlers: Dict[str, List[Callable]] = {}


def on(name: str, *fns: Callable):
    with _lock:
        hs = _handlers.setdefault(name, [])
        for fn in fns:
            if all(fn is not h for h in hs):   # dedupe by identity
                hs.append(fn)


def off(name: str, *fns: Callable):
    with _lock:
        hs = _handlers.get(name, [])
        for fn in fns:
            _handlers[name] = hs = [h for h in hs if h is not fn]


def emit(name: str, arg=None):
    with _lock:
        hs = list(_handlers.get(name, []))
    for fn in hs:
        fn(arg) if fn.__code__.co_argcount else fn()


def clear():
    with _lock:
        _handlers.clear()


_stop = threading.Event()


def shutdown():
    """Release a blocked :func:`wait` programmatically — the path a
    component takes when it hits a fatal condition (e.g. the node agent
    losing its identity to a live replacement) and the process must wind
    down without an operator signal."""
    _stop.set()


def wait():
    """Block until SIGINT/SIGTERM (or :func:`shutdown`), then emit EXIT."""
    _stop.clear()
    signal.signal(signal.SIGINT, lambda *a: _stop.set())
    signal.signal(signal.SIGTERM, lambda *a: _stop.set())
    _stop.wait()
    emit(EXIT)
