"""Process-wide event bus + signal wait (reference event/event.go:20-94).

On/Emit/Off with handler dedupe by identity; Wait() blocks until
SIGINT/SIGTERM, then emits EXIT — the shutdown fan-out the entrypoints use.
"""

from __future__ import annotations

import inspect
import signal
import threading
from typing import Callable, Dict, List

EXIT = "exit"
WAIT = "wait"   # config reloaded (reference: fsnotify -> WAIT)

_lock = threading.Lock()
_handlers: Dict[str, List[Callable]] = {}


def on(name: str, *fns: Callable):
    with _lock:
        hs = _handlers.setdefault(name, [])
        for fn in fns:
            if all(fn is not h for h in hs):   # dedupe by identity
                hs.append(fn)


def off(name: str, *fns: Callable):
    with _lock:
        hs = _handlers.get(name, [])
        for fn in fns:
            _handlers[name] = hs = [h for h in hs if h is not fn]


def _wants_arg(fn: Callable) -> bool:
    """Does the handler take a positional argument?  (Bound methods must
    not count ``self`` — ``__code__.co_argcount`` does, which made emit
    call zero-arg methods like ``server.stop`` with a spurious arg.)"""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return any(
        p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        for p in sig.parameters.values())


def emit(name: str, arg=None):
    with _lock:
        hs = list(_handlers.get(name, []))
    for fn in hs:
        fn(arg) if _wants_arg(fn) else fn()


def clear():
    with _lock:
        _handlers.clear()
    _stop.clear()


_stop = threading.Event()


def shutdown():
    """Release a blocked :func:`wait` programmatically — the path a
    component takes when it hits a fatal condition (e.g. the node agent
    losing its identity to a live replacement) and the process must wind
    down without an operator signal."""
    _stop.set()


def wait():
    """Block until SIGINT/SIGTERM (or :func:`shutdown`), then emit EXIT.
    Signal handlers install only from the main thread (Python forbids it
    elsewhere); an embedded wait() still releases via shutdown().

    shutdown() is sticky: one fired *before* main reaches wait() (e.g. a
    supervised child dying between READY and wait, bin/store.py) still
    releases immediately instead of being swallowed.  Tests reset the
    latch via :func:`clear`."""
    if threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGINT, lambda *a: _stop.set())
        signal.signal(signal.SIGTERM, lambda *a: _stop.set())
    _stop.wait()
    emit(EXIT)
