"""In-memory coordination store with etcd v3 semantics.

Implements exactly the subset the framework (and the reference) relies on:

- revisioned KV: every key carries (create_rev, mod_rev); a global revision
  counter advances on every mutation (etcd's store revision).
- prefix gets and prefix watches; watch events carry the previous KV for
  delete/modify deltas (the reference watches groups WithPrevKV,
  group.go:64-66).
- leases: grant(ttl)/keepalive/revoke; keys attached to an expired lease are
  deleted *with events*, which is how node death detection works
  (noticer.go:172-200).
- txns: put-if-absent on create_rev==0 (the distributed lock,
  client.go:95-109) and put-if-mod-rev CAS (pause toggle / group scrub,
  client.go:44-65).

Thread-safe; watchers receive events through BOUNDED queues on the
mutating thread — a consumer that falls max_backlog behind loses the
stream (WatchLost on the next drain/get) and must re-list + re-watch,
etcd's slow-watcher cancellation.  Lease expiry is checked lazily on
every operation and by an optional sweeper thread.
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

PUT = "PUT"
DELETE = "DELETE"


class CompactedError(RuntimeError):
    """watch(start_rev) asked for revisions older than the bounded event
    history retains (etcd's ErrCompacted): the caller must re-list the
    prefix and watch from the current revision instead."""


class WatchLost(RuntimeError):
    """The watch stream was cancelled because the consumer fell too far
    behind (etcd's slow-watcher cancellation).  Raised by get()/drain()
    once the buffered events are exhausted: the consumer must re-watch
    and re-list the prefix to resynchronize."""


@dataclasses.dataclass(frozen=True)
class KV:
    key: str
    value: str
    create_rev: int
    mod_rev: int
    lease: int = 0


@dataclasses.dataclass(frozen=True)
class Event:
    type: str                 # PUT | DELETE
    kv: KV
    prev_kv: Optional[KV]

    @property
    def is_create(self) -> bool:
        return self.type == PUT and self.prev_kv is None

    @property
    def is_modify(self) -> bool:
        return self.type == PUT and self.prev_kv is not None


@dataclasses.dataclass
class Lease:
    id: int
    ttl: float
    deadline: float
    keys: set = dataclasses.field(default_factory=set)


class LossyEventStream:
    """Event-queue base with the WatchLost contract, shared by the
    in-process :class:`Watcher` and the remote client's watcher: a lost
    stream first yields its buffered tail, then raises
    :class:`WatchLost` — never a silent starve."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        self.lost = False
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue()
        self._closed = False

    def get(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event, or None on timeout/close.  Raises WatchLost once a
        cancelled stream has drained its buffered events."""
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            if self.lost:
                raise WatchLost(f"watch {self.prefix!r} overflowed")
            return None
        if ev is None and self.lost:
            raise WatchLost(f"watch {self.prefix!r} overflowed")
        return ev

    def drain(self) -> List[Event]:
        """Buffered events.  A cancelled stream first yields its
        remaining buffer, then raises WatchLost on the next call."""
        out = []
        while True:
            try:
                ev = self._q.get_nowait()
            except queue.Empty:
                if self.lost and not out:
                    raise WatchLost(f"watch {self.prefix!r} overflowed")
                return out
            if ev is None:
                if self.lost and not out:
                    raise WatchLost(f"watch {self.prefix!r} overflowed")
                return out
            out.append(ev)

    def __iter__(self):
        while not self._closed:
            ev = self.get()
            if ev is None:
                return
            yield ev


class Watcher(LossyEventStream):
    """A watch stream over a key prefix.

    The queue is bounded: a consumer that falls ``max_backlog`` events
    behind has lost the stream anyway, so the watcher cancels itself
    (etcd cancels slow watchers the same way; the native server bounds
    its per-connection outbox identically)."""

    MAX_BACKLOG = 1 << 17

    def __init__(self, store: "MemStore", prefix: str, start_rev: int,
                 max_backlog: int = MAX_BACKLOG, events: str = ""):
        super().__init__(prefix)
        self._store = store
        self.start_rev = start_rev
        self._max_backlog = max_backlog
        # "" = all event types; "delete" = DELETE only.  A writer
        # watching its own output prefix (the scheduler mirrors
        # outstanding orders it publishes by the tens of thousands per
        # window) would otherwise get every one of its own puts pushed
        # back, serialized and re-parsed, for nothing.
        self.events = events

    def _emit(self, ev: Event):
        if self._closed:
            return
        if self.events == "delete" and ev.type != DELETE:
            return
        if self._q.qsize() >= self._max_backlog:
            self.lost = True
            self.close()
            return
        self._q.put(ev)

    def close(self):
        self._closed = True
        self._store._remove_watcher(self)
        self._q.put(None)


class MemStore:
    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 history: int = 65536):
        self._lock = threading.RLock()
        self._clock = clock
        self._kv: Dict[str, KV] = {}
        self._rev = 0
        self._leases: Dict[int, Lease] = {}
        self._next_lease = 1
        self._watchers: List[Watcher] = []
        self._history: "collections.deque[Event]" = \
            collections.deque(maxlen=history)
        self._sweeper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # per-op server-side timing for the dispatch plane's hot ops
        # (claim paths, bulk writes, watch fan-out): op -> [count,
        # total_ns, max_ns].  Lets a bench attribute the plane's ceiling
        # to a NAMED component instead of "the store" (VERDICT #2).
        self._op_ns: Dict[str, list] = {}

    def _op_record(self, op: str, t0_ns: int):
        dt = time.perf_counter_ns() - t0_ns
        ent = self._op_ns.get(op)
        if ent is None:
            self._op_ns[op] = [1, dt, dt]
        else:
            ent[0] += 1
            ent[1] += dt
            if dt > ent[2]:
                ent[2] = dt

    def op_stats(self) -> dict:
        """Per-op timing snapshot: {op: {count, total_ms, max_ms}}."""
        with self._lock:
            return {op: {"count": c, "total_ms": round(t / 1e6, 3),
                         "max_ms": round(m / 1e6, 3)}
                    for op, (c, t, m) in self._op_ns.items()}

    # ---- lifecycle -------------------------------------------------------

    def start_sweeper(self, interval: float = 0.2):
        if self._sweeper:
            return
        def run():
            while not self._stop.wait(interval):
                self._expire_leases()
        self._sweeper = threading.Thread(target=run, daemon=True,
                                         name="memstore-sweeper")
        self._sweeper.start()

    def close(self):
        self._stop.set()
        with self._lock:
            for w in list(self._watchers):
                w.close()

    # ---- KV --------------------------------------------------------------

    def put(self, key: str, value: str, lease: int = 0) -> int:
        with self._lock:
            self._expire_leases()
            return self._put_locked(key, value, lease)

    def put_many(self, items: Sequence[Sequence[str]], lease: int = 0) -> int:
        """Bulk put under ONE lock acquisition — the dispatch plane writes
        whole planned windows at once.  ``items`` is [(key, value), ...];
        the lease (if any) applies to every key."""
        with self._lock:
            t0 = time.perf_counter_ns()
            self._expire_leases()
            rev = self._rev
            for key, value in items:
                rev = self._put_locked(key, value, lease)
            self._op_record("put_many", t0)
            return rev

    def _put_locked(self, key: str, value: str, lease: int) -> int:
        prev = self._kv.get(key)
        new_lease = None
        if lease:
            new_lease = self._leases.get(lease)
            if new_lease is None:   # validate BEFORE any mutation
                raise KeyError(f"lease {lease} not found")
        if prev and prev.lease and prev.lease != lease:
            # etcd semantics: a put re-binds the key's lease attachment —
            # the old lease must no longer own (and delete) this key.
            old = self._leases.get(prev.lease)
            if old is not None:
                old.keys.discard(key)
        if new_lease is not None:
            new_lease.keys.add(key)
        self._rev += 1
        kv = KV(key, value, prev.create_rev if prev else self._rev,
                self._rev, lease)
        self._kv[key] = kv
        self._notify(Event(PUT, kv, prev))
        return self._rev

    def get(self, key: str) -> Optional[KV]:
        with self._lock:
            self._expire_leases()
            return self._kv.get(key)

    def get_many(self, keys: Sequence[str]) -> List[Optional[KV]]:
        """Bulk point-get under one lock acquisition (one round trip over
        the wire) — agents batch their job-cache fills with this."""
        with self._lock:
            self._expire_leases()
            return [self._kv.get(k) for k in keys]

    def get_prefix(self, prefix: str) -> List[KV]:
        with self._lock:
            self._expire_leases()
            return sorted((kv for k, kv in self._kv.items()
                           if k.startswith(prefix)), key=lambda kv: kv.key)

    def get_prefix_page(self, prefix: str, start_after: str = "",
                        limit: int = 50_000) -> List[KV]:
        """One PAGE of a prefix listing: up to ``limit`` keys strictly
        after ``start_after``, in key order.  A million-key prefix as
        one reply is hundreds of MB serialized and a seconds-long GIL
        hold to parse client-side; pagination turns both into bounded
        slices (etcd's WithRange+WithLimit).  The page is a consistent
        snapshot; the WHOLE iteration is not — callers that page
        through a live keyspace get the same read-skew any etcd range
        pagination has, which every consumer here already tolerates
        (anti-entropy re-lists, leases expire)."""
        import heapq
        with self._lock:
            self._expire_leases()
            # nsmallest keeps each page O(n log limit), not a full sort
            # of every matching key per page (O(pages x n log n) across
            # an iteration)
            hits = heapq.nsmallest(
                max(1, limit),
                (k for k in self._kv
                 if k.startswith(prefix) and k > start_after))
            return [self._kv[k] for k in hits]

    def count_prefix(self, prefix: str) -> int:
        with self._lock:
            self._expire_leases()
            return sum(1 for k in self._kv if k.startswith(prefix))

    def delete(self, key: str) -> bool:
        with self._lock:
            self._expire_leases()
            return self._delete_locked(key)

    def _delete_locked(self, key: str) -> bool:
        prev = self._kv.pop(key, None)
        if prev is None:
            return False
        if prev.lease and prev.lease in self._leases:
            self._leases[prev.lease].keys.discard(key)
        self._rev += 1
        tomb = KV(key, "", prev.create_rev, self._rev, 0)
        self._notify(Event(DELETE, tomb, prev))
        return True

    def delete_prefix(self, prefix: str) -> int:
        with self._lock:
            self._expire_leases()
            keys = [k for k in self._kv if k.startswith(prefix)]
            for k in keys:
                self._delete_locked(k)
            return len(keys)

    def delete_many(self, keys: Sequence[str]) -> int:
        """Bulk delete under ONE lock acquisition — completion flushers
        retire whole batches of proc keys in one round trip."""
        with self._lock:
            self._expire_leases()
            return sum(1 for k in keys if self._delete_locked(k))

    # ---- txns ------------------------------------------------------------

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        """Txn If(create_rev(key)==0) Then(put) — the distributed lock
        acquire (reference client.go:95-109)."""
        with self._lock:
            self._expire_leases()
            if key in self._kv:
                return False
            self._put_locked(key, value, lease)
            return True

    def put_if_mod_rev(self, key: str, value: str, mod_rev: int,
                       lease: int = 0) -> bool:
        """CAS on mod revision (reference client.go:44-65).  mod_rev 0 means
        'must not exist'."""
        with self._lock:
            self._expire_leases()
            cur = self._kv.get(key)
            if mod_rev == 0:
                if cur is not None:
                    return False
            elif cur is None or cur.mod_rev != mod_rev:
                return False
            self._put_locked(key, value, lease)
            return True

    def claim(self, fence_key: str, fence_val: str, fence_lease: int = 0,
              order_key: str = "", proc_key: str = "", proc_val: str = "",
              proc_lease: int = 0) -> bool:
        """Atomic execution claim — the dispatch plane's per-order hot op.

        One round trip replaces the agent's fence ``put_if_absent`` +
        proc-registry put + order-key delete chain (the reference pays up
        to 3 etcd RPCs per fire: lock txn job.go:243-271, proc put
        proc.go:209-237, and its own cleanup).  Semantics:

        - fence_key already exists -> the claim LOSES: the order key is
          still consumed (another node ran this (job, second)), nothing
          else changes, returns False;
        - otherwise the fence is written (under fence_lease), the proc
          key (if given) is written under proc_lease, the order key (if
          given) is deleted, and the claim WINS: returns True.

        Both leases are validated before any mutation, so an expired
        lease raises KeyError without a half-applied claim.
        """
        with self._lock:
            t0 = time.perf_counter_ns()
            self._expire_leases()
            for lz in (fence_lease, proc_lease if proc_key else 0):
                if lz and lz not in self._leases:
                    raise KeyError(f"lease {lz} not found")
            if fence_key in self._kv:
                if order_key:
                    self._delete_locked(order_key)
                self._op_record("claim", t0)
                return False
            self._put_locked(fence_key, fence_val, fence_lease)
            if proc_key:
                self._put_locked(proc_key, proc_val, proc_lease)
            if order_key:
                self._delete_locked(order_key)
            self._op_record("claim", t0)
            return True

    # ---- leases ----------------------------------------------------------

    def claim_many(self, items: Sequence[Sequence[str]],
                   fence_lease: int = 0,
                   proc_lease: int = 0) -> List[bool]:
        """Batched :meth:`claim` under ONE lock acquisition: ``items`` is
        [(fence_key, fence_val, order_key, proc_key, proc_val), ...]; the
        two leases are shared by the whole batch (agents pool their fence
        and proc keys on shared leases anyway).  Returns one win/lose
        bool per item — an agent's claim batcher turns a burst of due
        executions into a single store round trip."""
        with self._lock:
            t0 = time.perf_counter_ns()
            self._expire_leases()
            # malformed items yield per-item False WITHOUT aborting the
            # batch (never a half-applied batch + whole-batch error) —
            # bit-for-bit the native stored's behavior
            any_proc = any(len(it) >= 5 and it[3] for it in items)
            for lz in (fence_lease, proc_lease if any_proc else 0):
                if lz and lz not in self._leases:
                    raise KeyError(f"lease {lz} not found")
            out = []
            for it in items:
                if len(it) < 5:
                    out.append(False)
                    continue
                fence_key, fence_val, order_key, proc_key, proc_val = it[:5]
                if fence_key in self._kv:
                    if order_key:
                        self._delete_locked(order_key)
                    out.append(False)
                    continue
                self._put_locked(fence_key, fence_val, fence_lease)
                if proc_key:
                    self._put_locked(proc_key, proc_val, proc_lease)
                if order_key:
                    self._delete_locked(order_key)
                out.append(True)
            self._op_record("claim_many", t0)
            return out

    def claim_bundle(self, order_key: str,
                     items: Sequence[Sequence[str]],
                     fence_lease: int = 0,
                     proc_lease: int = 0) -> List[bool]:
        """Consume one coalesced (node, second) dispatch bundle in a
        single atomic op: per-job fence claims + proc registrations for
        the winners, then ONE delete of the bundle order key.  ``items``
        is [(fence_key, fence_val, proc_key, proc_val), ...] — proc_key
        may be "" (short-run suppression registers later via the delay
        monitor).  The bundle key is the scheduler's outstanding-capacity
        reservation for the whole bundle; deleting it here — in the same
        locked op that writes the winners' proc keys — means the
        reservation converts to proc-key accounting with no window in
        which capacity is either double-counted or leaked.  Losing items
        (fence already held: another node ran that (job, second)) change
        nothing but still count toward the bundle's consumption; the key
        is deleted regardless of the win/lose mix, exactly once.
        Malformed items yield per-item False without aborting the
        bundle.  Leases are validated before any mutation."""
        with self._lock:
            t0 = time.perf_counter_ns()
            self._expire_leases()
            any_proc = any(len(it) >= 4 and it[2] for it in items)
            for lz in (fence_lease, proc_lease if any_proc else 0):
                if lz and lz not in self._leases:
                    raise KeyError(f"lease {lz} not found")
            out = []
            for it in items:
                if len(it) < 4:
                    out.append(False)
                    continue
                fence_key, fence_val, proc_key, proc_val = it[:4]
                if fence_key in self._kv:
                    out.append(False)
                    continue
                self._put_locked(fence_key, fence_val, fence_lease)
                if proc_key:
                    self._put_locked(proc_key, proc_val, proc_lease)
                out.append(True)
            if order_key:
                self._delete_locked(order_key)
            self._op_record("claim_bundle", t0)
            return out

    def grant(self, ttl: float) -> int:
        with self._lock:
            lid = self._next_lease
            self._next_lease += 1
            self._leases[lid] = Lease(lid, ttl, self._clock() + ttl)
            return lid

    def keepalive(self, lease_id: int) -> bool:
        with self._lock:
            self._expire_leases()
            l = self._leases.get(lease_id)
            if l is None:
                return False
            l.deadline = self._clock() + l.ttl
            return True

    def revoke(self, lease_id: int) -> bool:
        with self._lock:
            l = self._leases.pop(lease_id, None)
            if l is None:
                return False
            for k in sorted(l.keys):
                self._delete_locked(k)
            return True

    def lease_ttl_remaining(self, lease_id: int) -> Optional[float]:
        with self._lock:
            l = self._leases.get(lease_id)
            return None if l is None else l.deadline - self._clock()

    def _expire_leases(self):
        now = self._clock()
        expired = [l for l in self._leases.values() if l.deadline <= now]
        for l in expired:
            del self._leases[l.id]
            for k in sorted(l.keys):
                self._delete_locked(k)

    # ---- watch -----------------------------------------------------------

    def watch(self, prefix: str, start_rev: int = 0,
              max_backlog: Optional[int] = None,
              events: str = "") -> Watcher:
        """Watch a prefix.  With ``start_rev`` > 0, replay retained events
        with mod_rev >= start_rev first (etcd WithRev) — a reconnecting
        watcher resumes without losing deltas.  Raises
        :class:`CompactedError` if the bounded history no longer reaches
        back that far, and :class:`WatchLost` if the replay itself
        overflows ``max_backlog`` (re-list instead).  ``events="delete"``
        suppresses PUT pushes server-side (etcd's WithFilterPut): the
        filter applies to the replay too."""
        with self._lock:
            w = Watcher(self, prefix, start_rev or self._rev,
                        max_backlog=max_backlog or Watcher.MAX_BACKLOG,
                        events=events)
            if start_rev and start_rev <= self._rev:
                # every revision 1..rev emitted exactly one event, so the
                # replay is complete iff the ring still holds start_rev
                oldest = (self._history[0].kv.mod_rev if self._history
                          else self._rev + 1)
                if start_rev < oldest and oldest > 1:
                    raise CompactedError(
                        f"start_rev {start_rev} compacted "
                        f"(oldest retained {oldest})")
                for ev in self._history:
                    if (ev.kv.mod_rev >= start_rev
                            and ev.kv.key.startswith(prefix)):
                        w._emit(ev)
                if w.lost:   # replay alone overflowed: don't register a
                    raise WatchLost(   # dead watcher, tell the caller
                        f"watch {prefix!r} replay overflowed; re-list")
            self._watchers.append(w)
            return w

    def _remove_watcher(self, w: Watcher):
        with self._lock:
            if w in self._watchers:
                self._watchers.remove(w)

    def _notify(self, ev: Event):
        t0 = time.perf_counter_ns()
        self._history.append(ev)
        # copy: an overflowing watcher cancels itself (removes from the
        # list) from inside _emit
        for w in list(self._watchers):
            if ev.kv.key.startswith(w.prefix):
                w._emit(ev)
        self._op_record("watch_fanout", t0)
