"""In-memory coordination store with etcd v3 semantics.

Implements exactly the subset the framework (and the reference) relies on:

- revisioned KV: every key carries (create_rev, mod_rev); a global revision
  counter advances on every mutation (etcd's store revision).
- prefix gets and prefix watches; watch events carry the previous KV for
  delete/modify deltas (the reference watches groups WithPrevKV,
  group.go:64-66).
- leases: grant(ttl)/keepalive/revoke; keys attached to an expired lease are
  deleted *with events*, which is how node death detection works
  (noticer.go:172-200).
- txns: put-if-absent on create_rev==0 (the distributed lock,
  client.go:95-109) and put-if-mod-rev CAS (pause toggle / group scrub,
  client.go:44-65).

Thread-safe, and STRIPED: the keyspace is hash-sharded across N lock
domains (default 16) so concurrent writers on disjoint keys — several
agents' claim batches, a publisher's put_many, lease keepalives — no
longer serialize behind one global lock.  Three small shared domains
remain, each held only for bookkeeping (never for per-key map work or
serialization):

- the EVENT PLANE (``_ev_lock``): revision counter + bounded history
  ring + watcher registry/fan-out.  Holding it per mutation keeps watch
  streams revision-ordered (etcd's contract) and history replayable.
- the LEASE TABLE (``_lease_lock``, reentrant): grants/keepalives and
  key<->lease attachment.  Claim ops hold it across their item loop so
  a validated lease cannot expire mid-batch (no half-applied claims).
- op stats (``_op_lock``).

Lock order (never acquired in reverse): stripe locks in ascending index
order -> lease lock -> event lock.  Multi-key ops (txn/claim_bundle/
put_many/delete_many/prefix scans) acquire every stripe they touch in
ascending order; lease expiry collects doomed keys under the lease lock
alone and deletes them through the normal striped path afterwards.

Watchers receive events through BOUNDED queues on the mutating thread —
a consumer that falls max_backlog behind loses the stream (WatchLost on
the next drain/get) and must re-list + re-watch, etcd's slow-watcher
cancellation.  Lease expiry is checked lazily on every operation while
no sweeper runs; once a sweeper owns expiry, the hot ops skip the
per-op whole-table scan (it was a measured per-put cost at dispatch
rates, and under the shared lease lock it re-serialized the striped
ops).  Writes still reject expired-but-unswept leases via an O(1)
deadline check at validation.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

PUT = "PUT"
DELETE = "DELETE"


class CompactedError(RuntimeError):
    """watch(start_rev) asked for revisions older than the bounded event
    history retains (etcd's ErrCompacted): the caller must re-list the
    prefix and watch from the current revision instead."""


class WatchLost(RuntimeError):
    """The watch stream was cancelled because the consumer fell too far
    behind (etcd's slow-watcher cancellation).  Raised by get()/drain()
    once the buffered events are exhausted: the consumer must re-watch
    and re-list the prefix to resynchronize."""


@dataclasses.dataclass(frozen=True)
class KV:
    key: str
    value: str
    create_rev: int
    mod_rev: int
    lease: int = 0


@dataclasses.dataclass(frozen=True)
class Event:
    type: str                 # PUT | DELETE
    kv: KV
    prev_kv: Optional[KV]

    @property
    def is_create(self) -> bool:
        return self.type == PUT and self.prev_kv is None

    @property
    def is_modify(self) -> bool:
        return self.type == PUT and self.prev_kv is not None


@dataclasses.dataclass
class Lease:
    id: int
    ttl: float
    deadline: float
    keys: set = dataclasses.field(default_factory=set)


class LossyEventStream:
    """Event-queue base with the WatchLost contract, shared by the
    in-process :class:`Watcher` and the remote client's watcher: a lost
    stream first yields its buffered tail, then raises
    :class:`WatchLost` — never a silent starve."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        self.lost = False
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue()
        self._closed = False

    def get(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event, or None on timeout/close.  Raises WatchLost once a
        cancelled stream has drained its buffered events."""
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            if self.lost:
                raise WatchLost(f"watch {self.prefix!r} overflowed")
            return None
        if ev is None and self.lost:
            raise WatchLost(f"watch {self.prefix!r} overflowed")
        return ev

    def drain(self) -> List[Event]:
        """Buffered events.  A cancelled stream first yields its
        remaining buffer, then raises WatchLost on the next call."""
        out = []
        while True:
            try:
                ev = self._q.get_nowait()
            except queue.Empty:
                if self.lost and not out:
                    raise WatchLost(f"watch {self.prefix!r} overflowed")
                return out
            if ev is None:
                if self.lost and not out:
                    raise WatchLost(f"watch {self.prefix!r} overflowed")
                return out
            out.append(ev)

    def __iter__(self):
        while not self._closed:
            ev = self.get()
            if ev is None:
                return
            yield ev


class Watcher(LossyEventStream):
    """A watch stream over a key prefix.

    The queue is bounded: a consumer that falls ``max_backlog`` events
    behind has lost the stream anyway, so the watcher cancels itself
    (etcd cancels slow watchers the same way; the native server bounds
    its per-connection outbox identically)."""

    MAX_BACKLOG = 1 << 17

    def __init__(self, store: "MemStore", prefix: str, start_rev: int,
                 max_backlog: int = MAX_BACKLOG, events: str = ""):
        super().__init__(prefix)
        self._store = store
        self.start_rev = start_rev
        self._max_backlog = max_backlog
        # "" = all event types; "delete" = DELETE only.  A writer
        # watching its own output prefix (the scheduler mirrors
        # outstanding orders it publishes by the tens of thousands per
        # window) would otherwise get every one of its own puts pushed
        # back, serialized and re-parsed, for nothing.
        self.events = events
        # optional readiness hook: called (with this watcher) after an
        # event or the close sentinel lands in the queue.  The remote
        # server's per-connection pump uses it to wake ONE batching
        # writer instead of parking a thread per watcher.
        self.on_ready: Optional[Callable[["Watcher"], None]] = None

    def _emit(self, ev: Event):
        if self._closed:
            return
        if self.events == "delete" and ev.type != DELETE:
            return
        if self._q.qsize() >= self._max_backlog:
            self.lost = True
            self.close()
            return
        self._q.put(ev)
        if self.on_ready is not None:
            self.on_ready(self)

    def close(self):
        self._closed = True
        self._store._remove_watcher(self)
        self._q.put(None)
        if self.on_ready is not None:
            self.on_ready(self)


class _Stripe:
    __slots__ = ("lock", "kv", "imaged", "cow")

    def __init__(self):
        self.lock = threading.Lock()
        self.kv: Dict[str, KV] = {}
        # staggered-snapshot state, guarded by this stripe's lock:
        # imaged=False while a snapshot is active and this stripe's
        # image hasn't been taken yet; cow holds the PRE-image (KV, or
        # None for not-present) of every key mutated in that window
        self.imaged = True
        self.cow: Dict[str, Optional[KV]] = {}


class MemStore:
    STRIPES = 16

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 history: int = 65536, stripes: int = STRIPES,
                 snapshot_staggered: Optional[bool] = None):
        self._nstripes = max(1, int(stripes))
        self._stripes = [_Stripe() for _ in range(self._nstripes)]
        # event plane: revision counter, history ring, watcher registry +
        # fan-out.  Reentrant because an overflowing watcher cancels
        # itself (-> _remove_watcher) from inside the fan-out.
        self._ev_lock = threading.RLock()
        # lease table.  Reentrant because claim ops hold it across their
        # whole item loop (a validated lease must not expire mid-batch)
        # while each inner put/delete re-takes it for attachment.
        self._lease_lock = threading.RLock()
        self._clock = clock
        self._rev = 0
        self._leases: Dict[int, Lease] = {}
        self._next_lease = 1
        self._watchers: List[Watcher] = []
        self._history: "collections.deque[Event]" = \
            collections.deque(maxlen=history)
        self._sweeper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # per-op server-side timing for the dispatch plane's hot ops
        # (claim paths, bulk writes, watch fan-out).  Lets a bench
        # attribute the plane's ceiling to a NAMED component instead of
        # "the store" (VERDICT #2); shared shape with the result
        # store's op_stats (metrics.OpStats).
        from ..metrics import OpStats
        self._ops = OpStats()
        # optional persistence (checkpoint plane): WAL + snapshot
        # sidecar, same record format as the native stored.cc — see
        # open_wal / snapshot
        self._wal = None
        self._replaying = False
        self._wal_compact_bytes = 0
        # replication plane (repl/): when a ReplLog is attached, every
        # WAL-worthy record is mirrored into it for follower shipping
        # (same record format — walsnap.py's table).  ``_epoch`` is the
        # fencing epoch ("E" records / snapshot "v" 4th field): bumped
        # on promotion so a deposed leader's late appends are
        # refusable.  ``_repl_follower`` disables LOCAL lease expiry —
        # the leader is the sole expiry authority, a follower expiring
        # locally would emit "d"s the leader never shipped.
        self._repl_log = None
        self._epoch = 0
        self._repl_follower = False
        # staggered snapshots (default): image stripes one at a time
        # under their OWN locks against a pinned revision boundary with
        # per-stripe copy-on-write pre-images, so a multi-GB image never
        # stalls writers longer than one stripe's copy.  Off = the PR 5
        # full-lock hold (the rollback switch).
        if snapshot_staggered is None:
            import os as _os
            snapshot_staggered = _os.environ.get(
                "CRONSUN_SNAPSHOT_STAGGERED", "on").lower() \
                not in ("off", "0")
        self._snap_staggered = bool(snapshot_staggered)
        self._snap_active = False
        self._snap_mu = threading.Lock()   # one snapshot at a time

    # ---- striped locking -------------------------------------------------

    def _sidx(self, key: str) -> int:
        return hash(key) % self._nstripes

    def _acquire_stripe(self, idx: int):
        lk = self._stripes[idx].lock
        if not lk.acquire(False):
            # blocked acquisition = real cross-writer contention; counted
            # so the bench (and /v1/metrics via op_stats) can see whether
            # the stripe count is the ceiling
            self.op_count("stripe_contention")
            lk.acquire()

    @contextlib.contextmanager
    def _locked(self, keys: Optional[Sequence[str]] = None,
                all_stripes: bool = False):
        """Hold the stripe locks covering ``keys`` (or every stripe),
        acquired in ascending index order — the deadlock-free order every
        multi-stripe op (txn, claim_bundle, put_many, prefix scan) uses."""
        if all_stripes:
            idxs: Sequence[int] = range(self._nstripes)
        else:
            idxs = sorted({self._sidx(k) for k in keys})
        for i in idxs:
            self._acquire_stripe(i)
        try:
            yield
        finally:
            for i in reversed(list(idxs)):
                self._stripes[i].lock.release()

    def _op_record(self, op: str, t0_ns: int):
        self._ops.record(op, t0_ns)

    def op_count(self, op: str, n: int = 1):
        """Count-only stat (no timing): contention ticks, watch-batch
        frame/event tallies.  Rendered through the same op_stats surface."""
        self._ops.count(op, n)

    def op_stats(self) -> dict:
        """Per-op timing snapshot: {op: {count, total_ms, max_ms}}."""
        return self._ops.snapshot()

    # ---- lifecycle -------------------------------------------------------

    def start_sweeper(self, interval: float = 0.2):
        if self._sweeper:
            return
        def run():
            while not self._stop.wait(interval):
                self._expire_leases()
                wal = self._wal
                if wal is not None:
                    # fdatasync rides the sweep cadence (the native
                    # server's contract); size-triggered compaction
                    # keeps the WAL — and therefore the next boot's
                    # replay — bounded by snapshot cadence, not history
                    wal.sync()
                    if self._wal_compact_bytes and \
                            wal.size() > self._wal_compact_bytes:
                        try:
                            self.snapshot()
                        except Exception as e:  # noqa: BLE001 — retry
                            import sys      # at the next sweep; a full
                            print(f"wal compaction failed: {e}",  # disk
                                  file=sys.stderr)  # must not kill the
                                                    # sweeper
        self._sweeper = threading.Thread(target=run, daemon=True,
                                         name="memstore-sweeper")
        self._sweeper.start()

    def close(self):
        self._stop.set()
        with self._ev_lock:
            for w in list(self._watchers):
                w.close()
        if self._wal is not None:
            self._wal.sync()
            self._wal.close()

    # ---- persistence (checkpoint plane) ----------------------------------

    def open_wal(self, path: str, sync_per_commit: bool = False,
                 compact_bytes: int = 256 << 20) -> "MemStore":
        """Attach a WAL + snapshot pair at ``path`` / ``path + ".snap"``
        (native stored.cc record format): replay the snapshot, replay
        the WAL tail through the normal mutation paths, then write a
        fresh snapshot and truncate the WAL — boot cost is bounded by
        snapshot cadence, not total history.  Must run before the store
        serves clients (no concurrent mutations during replay)."""
        from ..checkpoint.walsnap import (WalFile, read_records,
                                          rotated_path, snap_path)
        if self._wal is not None:
            raise RuntimeError("wal already open")
        self._replaying = True
        try:
            t0 = time.perf_counter_ns()
            for rec in read_records(snap_path(path)):
                self._replay_record(rec)
            self._op_record("snapshot_load", t0)
            t0 = time.perf_counter_ns()
            # FILE.1 = pre-pin records parked by a staggered snapshot
            # that died mid-image: strictly older than the live WAL,
            # replayed between snapshot and tail so last-write-wins
            # convergence holds
            for rec in read_records(rotated_path(path)):
                self._replay_record(rec)
            for rec in read_records(path):
                self._replay_record(rec)
            self._op_record("wal_replay", t0)
        finally:
            self._replaying = False
        self._wal = WalFile(path, sync_per_commit)
        self._wal_compact_bytes = compact_bytes
        self.snapshot()
        return self

    def snapshot(self) -> int:
        """Write a point-in-time image of the striped keyspace + lease
        table (tagged with its revision) to the snapshot sidecar — temp
        file + atomic rename.  Two paths:

        - STAGGERED (default): a brief all-locks PIN (revision + lease
          copy + WAL rotation to ``FILE.1`` — O(1), no state copied but
          the lease table), then stripes image ONE AT A TIME under
          their own locks with copy-on-write pre-images for writes
          racing the image — writers never wait longer than one
          stripe's copy, and the ``.snap`` is consistent at the pinned
          revision (every post-pin mutation is in the fresh WAL, so
          boot replay converges regardless).  On success ``FILE.1`` is
          deleted (its records are covered).
        - FULL-LOCK (``snapshot_staggered=False`` /
          CRONSUN_SNAPSHOT_STAGGERED=off): the PR 5 behavior — every
          lock held for the whole serialization; kept as the rollback
          and the bench's stall baseline.

        Returns the snapshot's revision.  The per-path cost shows as
        the ``snapshot`` (and staggered ``snapshot_pin``) op in
        op_stats."""
        if self._wal is None:
            raise RuntimeError("snapshot: no WAL configured "
                               "(open_wal first)")
        from ..checkpoint.walsnap import rotated_path, write_snapshot
        if not self._snap_staggered:
            with self._locked(all_stripes=True), self._lease_lock, \
                    self._ev_lock:
                t0 = time.perf_counter_ns()
                write_snapshot(self._wal.path, self._snapshot_lines())
                # any parked FILE.1 goes BEFORE the truncation: a crash
                # between the two with the order reversed leaves
                # snapshot + stale FILE.1 + empty WAL, and the next
                # boot replays the stale records over the snapshot with
                # no newer tail to converge them
                self._remove_rotated(rotated_path(self._wal.path))
                self._wal.truncate()
                rev = self._rev
                self._op_record("snapshot", t0)
            return rev
        with self._snap_mu:
            t0 = time.perf_counter_ns()
            rotated = rotated_path(self._wal.path)
            # PIN — the brief exclusive window: all locks held only
            # long enough to fix the revision boundary, copy the (small)
            # lease table, rotate the WAL, and arm the per-stripe COW
            with self._locked(all_stripes=True), self._lease_lock, \
                    self._ev_lock:
                tp = time.perf_counter_ns()
                rev = self._rev
                next_lease = self._next_lease
                epoch = self._epoch
                now_c, now_w = self._clock(), time.time()
                leases = [(l.id, l.ttl, now_w + (l.deadline - now_c))
                          for l in self._leases.values()]
                self._wal.rotate(rotated)
                for s in self._stripes:
                    s.imaged = False
                    s.cow = {}
                self._snap_active = True
                self._op_record("snapshot_pin", tp)
            try:
                def lines():
                    yield ["v", rev, next_lease, epoch]
                    for lid, ttl, wall in leases:
                        yield ["g", lid, ttl, wall]
                    for s in self._stripes:
                        with s.lock:
                            img = dict(s.kv)
                            cow, s.cow = s.cow, {}
                            s.imaged = True
                        # pre-images overlay OUTSIDE the lock: a key
                        # mutated post-pin reverts to its pinned value
                        # (None = did not exist at the pin)
                        for k, pre in cow.items():
                            if pre is None:
                                img.pop(k, None)
                            else:
                                img[k] = pre
                        for k, kv in img.items():
                            yield ["s", k, kv.value, kv.create_rev,
                                   kv.mod_rev, kv.lease]
                write_snapshot(self._wal.path, lines())
            finally:
                self._snap_active = False
                for s in self._stripes:
                    with s.lock:
                        s.imaged = True
                        s.cow = {}
            # the rename published an image covering everything in the
            # rotated pre-pin records — they are dead weight now (left
            # in place on failure: boot and the next pin both handle a
            # lingering FILE.1)
            self._remove_rotated(rotated)
            self._op_record("snapshot", t0)
            return rev

    @staticmethod
    def _remove_rotated(rotated: str):
        import os as _os
        try:
            _os.remove(rotated)
        except OSError:
            pass

    def rev(self) -> int:
        """Current store revision — the checkpoint plane tags scheduler
        checkpoints with it so a restore can replay exactly the watch
        delta since the checkpointed state."""
        with self._ev_lock:
            return self._rev

    def _snapshot_lines(self):
        """Caller holds every stripe lock + lease + event locks."""
        yield ["v", self._rev, self._next_lease, self._epoch]
        now_c, now_w = self._clock(), time.time()
        for lid, l in self._leases.items():
            # deadlines persist as WALL-clock instants (the store clock
            # is monotonic and does not survive the process)
            yield ["g", lid, l.ttl, now_w + (l.deadline - now_c)]
        for s in self._stripes:
            for k, kv in s.kv.items():
                yield ["s", k, kv.value, kv.create_rev, kv.mod_rev,
                       kv.lease]

    def _replay_record(self, rec: list):
        """Apply one snapshot/WAL record (boot only: no clients yet)."""
        op = rec[0]
        if op == "p" and len(rec) >= 4:
            key, value, lease = rec[1], rec[2], int(rec[3] or 0)
            with self._lease_lock:
                if lease and lease not in self._leases:
                    # the lease expired+vanished during downtime; a
                    # recreate-then-expire is indistinguishable — drop
                    return
            with self._locked([key]):
                self._put_locked(key, value, lease)
        elif op == "d" and len(rec) >= 2:
            with self._locked([rec[1]]):
                self._delete_locked(rec[1])
        elif op == "g" and len(rec) >= 4:
            lid, ttl, wall_deadline = int(rec[1]), float(rec[2]), \
                float(rec[3])
            with self._lease_lock:
                self._leases[lid] = Lease(
                    lid, ttl, self._clock() + (wall_deadline - time.time()))
                if lid >= self._next_lease:
                    self._next_lease = lid + 1
        elif op == "k" and len(rec) >= 3:
            with self._lease_lock:
                l = self._leases.get(int(rec[1]))
                if l is not None:
                    l.deadline = self._clock() + (float(rec[2])
                                                  - time.time())
        elif op == "x" and len(rec) >= 2:
            # full revoke semantics: delete attached keys too — closes
            # the crash window between a flushed "x" and its "d"s
            lid = int(rec[1])
            with self._lease_lock:
                l = self._leases.pop(lid, None)
            if l is not None:
                self._delete_keys(sorted(l.keys), only_lease=lid)
        elif op == "v" and len(rec) >= 3:
            self._rev = int(rec[1])
            self._next_lease = int(rec[2])
            if len(rec) >= 4:       # pre-replication snapshots: epoch 0
                self._epoch = int(rec[3])
        elif op == "E" and len(rec) >= 2:
            # promotion fencing epoch (replication plane): adopt it so
            # a restarted replica rejoins at the epoch it last saw
            self._epoch = int(rec[1])
        elif op == "s" and len(rec) >= 6:
            key, value = rec[1], rec[2]
            kv = KV(key, value, int(rec[3]), int(rec[4]), int(rec[5]))
            if kv.lease:
                with self._lease_lock:
                    l = self._leases.get(kv.lease)
                    if l is None:
                        # the key's lease is gone (snapshot raced a
                        # revoke/expiry between the lease pop and the
                        # key deletes): the key was doomed — keeping it
                        # would resurrect it PERMANENTLY, attached to a
                        # lease that can never expire it
                        return
                    l.keys.add(key)
            self._stripes[self._sidx(key)].kv[key] = kv

    def _log(self, rec: list):
        """Record one mutation in every attached durability/shipping
        sink: the WAL (if open) and the replication log (if the repl
        plane is attached).  Replay never re-logs.  The caller holds
        the lock that ordered the mutation (``_ev_lock`` for KV
        records, ``_lease_lock`` for lease records), so both sinks see
        records in the order the store applied them."""
        if self._replaying:
            return
        if self._wal is not None:
            self._wal.append(rec)
        if self._repl_log is not None:
            self._repl_log.append(rec)

    # ---- replication (repl/ plane) ---------------------------------------

    def repl_attach(self, repl_log, follower: bool = False):
        """Attach the replication plane: every WAL-worthy record is
        mirrored into ``repl_log`` (repl.log.ReplLog) for follower
        shipping.  ``follower=True`` puts the store in follower mode:
        local lease expiry is disabled (the LEADER is the sole expiry
        authority — a follower expiring locally would generate "d"
        records the leader never shipped, diverging the replicas), and
        mutations are expected only via :meth:`repl_apply`."""
        self._repl_log = repl_log
        self._repl_follower = bool(follower)

    def repl_epoch(self) -> int:
        with self._ev_lock:
            return self._epoch

    def repl_is_follower(self) -> bool:
        return self._repl_follower

    def repl_apply(self, rec: list):
        """Apply one shipped WAL record on a FOLLOWER, through the
        normal mutation paths — watch events fire, the follower's own
        WAL and repl log record it (chained replication composes), and
        the revision counter advances exactly as the leader's did.

        Differences from boot replay (:meth:`_replay_record`):

        - a "p" whose lease is missing applies with lease=0 instead of
          dropping: the leader logs a revoke's "x" under the lease
          lock while a racing put logs its "p" later under the event
          lock, so the shipped order can be x-then-p even though the
          leader's state briefly held the key — the revoke's key-sweep
          "d" ships next, finds the key, and bumps the revision on
          both sides, so state AND revision converge.  Boot replay's
          drop would leave the follower's revision permanently behind.
        - "x" pops the lease-table entry ONLY: the leader ships one
          "d" per swept key itself; sweeping here too would
          double-delete (and double-bump the revision).
        - "E" adopts the fencing epoch a promotion stamped.
        """
        op = rec[0]
        if op == "p" and len(rec) >= 4:
            key, value, lease = rec[1], rec[2], int(rec[3] or 0)
            with self._locked([key]), self._lease_lock:
                if lease and lease not in self._leases:
                    lease = 0
                self._put_locked(key, value, lease)
        elif op == "d" and len(rec) >= 2:
            with self._locked([rec[1]]):
                self._delete_locked(rec[1])
        elif op == "g" and len(rec) >= 4:
            lid, ttl, wall = int(rec[1]), float(rec[2]), float(rec[3])
            with self._lease_lock:
                self._leases[lid] = Lease(
                    lid, ttl, self._clock() + (wall - time.time()))
                if lid >= self._next_lease:
                    self._next_lease = lid + 1
                self._log(["g", lid, ttl, wall])
        elif op == "k" and len(rec) >= 3:
            with self._lease_lock:
                l = self._leases.get(int(rec[1]))
                if l is not None:
                    l.deadline = self._clock() + (float(rec[2])
                                                  - time.time())
                    self._log(["k", l.id, float(rec[2])])
        elif op == "x" and len(rec) >= 2:
            lid = int(rec[1])
            with self._lease_lock:
                if self._leases.pop(lid, None) is not None:
                    self._log(["x", lid])
        elif op == "E" and len(rec) >= 2:
            with self._ev_lock:
                self._epoch = int(rec[1])
                self._log(["E", self._epoch])

    def repl_dump(self) -> Tuple[list, int, int]:
        """Consistent bootstrap image for a joining follower: the full
        snapshot line stream plus the repl-log sequence and fencing
        epoch it corresponds to.

        Staggered by default, reusing the snapshot plane's machinery
        (same ``_snap_mu`` / per-stripe COW state, so it serializes
        with :meth:`snapshot`): a brief all-locks PIN fixes the cursor,
        revision and lease copy and arms the copy-on-write pre-images,
        then stripes image ONE AT A TIME under their own locks — a
        follower bootstrap never stalls the leader's write plane longer
        than one stripe's copy.  Post-pin mutations revert to their
        pinned pre-image in the lines, so the image is exactly the
        state at the captured cursor (their records ship via the tail
        stream).  ``snapshot_staggered=False`` keeps the full-lock hold
        (the same rollback switch as :meth:`snapshot`)."""
        if not self._snap_staggered:
            with self._locked(all_stripes=True), self._lease_lock, \
                    self._ev_lock:
                lines = [list(r) for r in self._snapshot_lines()]
                seq = self._repl_log.seq \
                    if self._repl_log is not None else 0
                return lines, seq, self._epoch
        with self._snap_mu:
            t0 = time.perf_counter_ns()
            # PIN: all locks held only long enough to fix the cursor /
            # revision boundary, copy the (small) lease table and arm
            # the per-stripe COW — _log appends happen under _ev_lock
            # (KV) or _lease_lock (lease records), both held here, so
            # no record can land between the state capture and the seq
            with self._locked(all_stripes=True), self._lease_lock, \
                    self._ev_lock:
                rev = self._rev
                next_lease = self._next_lease
                epoch = self._epoch
                seq = self._repl_log.seq \
                    if self._repl_log is not None else 0
                now_c, now_w = self._clock(), time.time()
                leases = [(l.id, l.ttl, now_w + (l.deadline - now_c))
                          for l in self._leases.values()]
                for s in self._stripes:
                    s.imaged = False
                    s.cow = {}
                self._snap_active = True
            lines: list = [["v", rev, next_lease, epoch]]
            try:
                for lid, ttl, wall in leases:
                    lines.append(["g", lid, ttl, wall])
                for s in self._stripes:
                    with s.lock:
                        img = dict(s.kv)
                        cow, s.cow = s.cow, {}
                        s.imaged = True
                    # pre-images overlay OUTSIDE the lock: a key
                    # mutated post-pin reverts to its pinned value
                    # (None = did not exist at the pin)
                    for k, pre in cow.items():
                        if pre is None:
                            img.pop(k, None)
                        else:
                            img[k] = pre
                    for k, kv in img.items():
                        lines.append(["s", k, kv.value, kv.create_rev,
                                      kv.mod_rev, kv.lease])
            finally:
                self._snap_active = False
                for s in self._stripes:
                    with s.lock:
                        s.imaged = True
                        s.cow = {}
            self._op_record("repl_dump", t0)
            return lines, seq, epoch

    def repl_load(self, lines: Sequence[list], seq: int, epoch: int):
        """Follower bootstrap: replace local state with a leader's
        :meth:`repl_dump` image, then (if a WAL is attached) write one
        fresh local snapshot so the on-disk state is exactly a
        replica's snap+WAL; the attached repl log resets its cursor to
        the leader's ``seq`` so the tail stream continues the same
        numbering.  Only the repl apply thread may mutate during the
        load (concurrent READS can observe the partial image — the
        manager reports the follower unready until the load returns)."""
        with self._locked(all_stripes=True), self._lease_lock, \
                self._ev_lock:
            for s in self._stripes:
                s.kv.clear()
                s.cow = {}
            self._leases.clear()
            self._rev = 0
            self._next_lease = 1
        self._replaying = True
        try:
            for rec in lines:
                self._replay_record(rec)
        finally:
            self._replaying = False
        with self._ev_lock:
            self._epoch = int(epoch)
        if self._repl_log is not None:
            self._repl_log.reset(int(seq), int(epoch))
        if self._wal is not None:
            self.snapshot()

    def repl_promote(self) -> int:
        """Follower -> leader takeover: bump the fencing epoch and
        stamp it into the WAL/repl stream ("E" record), re-arm local
        lease expiry, give every replicated lease one fresh ttl (its
        deadline was converted from the OLD leader's wall clock; a
        takeover must not insta-expire the fleet's live leases — the
        owners re-keepalive within one ttl), and sweep orphan keys
        whose lease died in the old leader's crash window between a
        flushed "x" and its "d"s.  Returns the new epoch."""
        with self._locked(all_stripes=True), self._lease_lock, \
                self._ev_lock:
            self._repl_follower = False
            self._epoch += 1
            self._log(["E", self._epoch])
            now = self._clock()
            for l in self._leases.values():
                l.deadline = now + l.ttl
            for s in self._stripes:
                doomed = [k for k, kv in s.kv.items()
                          if kv.lease and kv.lease not in self._leases]
                for k in doomed:
                    self._delete_locked(k)
            return self._epoch

    # ---- KV --------------------------------------------------------------

    def _lazy_expire(self):
        """Per-op lease expiry: skip the scan entirely when the lease
        table is empty, and leave expiry to the sweeper when one is
        running — an unconditional whole-table scan per op (under the
        shared lease lock) was a measured hot-path cost at
        dispatch-plane rates, and with a sweeper it re-serialized the
        freshly striped ops.  Correctness holds either way: writes
        validate their own leases' deadlines (_check_lease), and an
        expired-but-unswept key lingering for one sweep interval is the
        same staleness any etcd client tolerates."""
        if self._leases and self._sweeper is None \
                and not self._repl_follower:
            self._expire_leases()

    def put(self, key: str, value: str, lease: int = 0) -> int:
        self._lazy_expire()
        self._validate_lease_arg(lease)
        with self._locked([key]):
            return self._put_locked(key, value, lease)

    def put_many(self, items: Sequence[Sequence[str]], lease: int = 0) -> int:
        """Bulk put under one striped acquisition — the dispatch plane
        writes whole planned windows at once.  ``items`` is
        [(key, value), ...]; the lease (if any) applies to every key."""
        self._lazy_expire()
        self._validate_lease_arg(lease)
        with self._locked([key for key, _v in items]):
            t0 = time.perf_counter_ns()
            rev = self._rev
            for key, value in items:
                rev = self._put_locked(key, value, lease)
            self._op_record("put_many", t0)
            return rev

    def _check_lease(self, lz: int) -> Lease:
        """Caller holds the lease lock.  An expired-but-unswept lease is
        as dead as a revoked one: the write paths no longer scan the
        whole table per op, so this O(1) deadline check at each op's
        validation point is what keeps a write from silently attaching
        to a lease the next sweep will kill (the old per-op scan raised
        KeyError in that window too)."""
        l = self._leases.get(lz)
        if l is None or l.deadline <= self._clock():
            raise KeyError(f"lease {lz} not found")
        return l

    def _validate_lease_arg(self, lease: int):
        if lease:
            with self._lease_lock:
                self._check_lease(lease)

    def _cow_save(self, key: str):
        """Staggered-snapshot copy-on-write: a mutation landing in a
        stripe the active snapshot has NOT yet imaged first saves the
        key's PRE-image (first touch only), so the image taken later
        reads as of the pinned revision.  Caller holds the key's stripe
        lock — the pin (which arms this under ALL stripe locks) and the
        imager (which flips ``imaged`` under this stripe's lock) both
        serialize against it, so the flag reads are race-free."""
        if not self._snap_active:
            return
        s = self._stripes[self._sidx(key)]
        if not s.imaged and key not in s.cow:
            s.cow[key] = s.kv.get(key)

    def _put_locked(self, key: str, value: str, lease: int) -> int:
        """Caller holds the key's stripe lock and has VALIDATED the
        lease (existence + deadline) at the op's entry; the existence
        re-check here only guards the mid-batch pop race, where failing
        is correct (the applied prefix dies with the lease anyway)."""
        self._cow_save(key)
        kvmap = self._stripes[self._sidx(key)].kv
        prev = kvmap.get(key)
        if lease or (prev and prev.lease):
            # only lease-touching puts pay the shared lease lock — an
            # unleased put over an unleased key (most mirror/state
            # writes) must not serialize behind a claim batch holding it
            with self._lease_lock:
                if lease:
                    new_lease = self._leases.get(lease)
                    if new_lease is None:
                        raise KeyError(f"lease {lease} not found")
                if prev and prev.lease and prev.lease != lease:
                    # etcd semantics: a put re-binds the key's lease
                    # attachment — the old lease must no longer own (and
                    # delete) this key.
                    old = self._leases.get(prev.lease)
                    if old is not None:
                        old.keys.discard(key)
                if lease:
                    new_lease.keys.add(key)
        with self._ev_lock:
            self._rev += 1
            kv = KV(key, value, prev.create_rev if prev else self._rev,
                    self._rev, lease)
            kvmap[key] = kv
            self._log(["p", key, value, lease])
            self._notify(Event(PUT, kv, prev))
            return self._rev

    def get(self, key: str) -> Optional[KV]:
        self._lazy_expire()
        with self._locked([key]):
            return self._stripes[self._sidx(key)].kv.get(key)

    def get_many(self, keys: Sequence[str]) -> List[Optional[KV]]:
        """Bulk point-get under one striped acquisition (one round trip
        over the wire) — agents batch their job-cache fills with this."""
        self._lazy_expire()
        keys = list(keys)
        with self._locked(keys):
            return [self._stripes[self._sidx(k)].kv.get(k) for k in keys]

    def get_prefix(self, prefix: str) -> List[KV]:
        self._lazy_expire()
        with self._locked(all_stripes=True):
            hits = [kv for s in self._stripes for k, kv in s.kv.items()
                    if k.startswith(prefix)]
            hits.sort(key=lambda kv: kv.key)
            return hits

    def get_prefix_page(self, prefix: str, start_after: str = "",
                        limit: int = 50_000) -> List[KV]:
        """One PAGE of a prefix listing: up to ``limit`` keys strictly
        after ``start_after``, in key order.  A million-key prefix as
        one reply is hundreds of MB serialized and a seconds-long GIL
        hold to parse client-side; pagination turns both into bounded
        slices (etcd's WithRange+WithLimit).  The page is a consistent
        snapshot; the WHOLE iteration is not — callers that page
        through a live keyspace get the same read-skew any etcd range
        pagination has, which every consumer here already tolerates
        (anti-entropy re-lists, leases expire)."""
        import heapq
        self._lazy_expire()
        with self._locked(all_stripes=True):
            # nsmallest keeps each page O(n log limit), not a full sort
            # of every matching key per page (O(pages x n log n) across
            # an iteration)
            hits = heapq.nsmallest(
                max(1, limit),
                (k for s in self._stripes for k in s.kv
                 if k.startswith(prefix) and k > start_after))
            return [self._stripes[self._sidx(k)].kv[k] for k in hits]

    def count_prefix(self, prefix: str) -> int:
        self._lazy_expire()
        with self._locked(all_stripes=True):
            return sum(1 for s in self._stripes for k in s.kv
                       if k.startswith(prefix))

    def delete(self, key: str) -> bool:
        self._lazy_expire()
        with self._locked([key]):
            return self._delete_locked(key)

    def _delete_locked(self, key: str) -> bool:
        """Caller holds the key's stripe lock."""
        self._cow_save(key)
        kvmap = self._stripes[self._sidx(key)].kv
        prev = kvmap.pop(key, None)
        if prev is None:
            return False
        if prev.lease:
            with self._lease_lock:
                l = self._leases.get(prev.lease)
                if l is not None:
                    l.keys.discard(key)
        with self._ev_lock:
            self._rev += 1
            tomb = KV(key, "", prev.create_rev, self._rev, 0)
            self._log(["d", key])
            self._notify(Event(DELETE, tomb, prev))
        return True

    def delete_prefix(self, prefix: str) -> int:
        self._lazy_expire()
        with self._locked(all_stripes=True):
            keys = [k for s in self._stripes for k in s.kv
                    if k.startswith(prefix)]
            for k in keys:
                self._delete_locked(k)
            return len(keys)

    def delete_many(self, keys: Sequence[str]) -> int:
        """Bulk delete under one striped acquisition — completion
        flushers (and the agents' buffered order-ack flush) retire whole
        batches of keys in one round trip."""
        self._lazy_expire()
        keys = list(keys)
        with self._locked(keys):
            t0 = time.perf_counter_ns()
            n = sum(1 for k in keys if self._delete_locked(k))
            self._op_record("delete_many", t0)
            return n

    # ---- txns ------------------------------------------------------------

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        """Txn If(create_rev(key)==0) Then(put) — the distributed lock
        acquire (reference client.go:95-109)."""
        self._lazy_expire()
        self._validate_lease_arg(lease)
        with self._locked([key]):
            if key in self._stripes[self._sidx(key)].kv:
                return False
            self._put_locked(key, value, lease)
            return True

    def put_if_mod_rev(self, key: str, value: str, mod_rev: int,
                       lease: int = 0) -> bool:
        """CAS on mod revision (reference client.go:44-65).  mod_rev 0 means
        'must not exist'."""
        self._lazy_expire()
        self._validate_lease_arg(lease)
        with self._locked([key]):
            cur = self._stripes[self._sidx(key)].kv.get(key)
            if mod_rev == 0:
                if cur is not None:
                    return False
            elif cur is None or cur.mod_rev != mod_rev:
                return False
            self._put_locked(key, value, lease)
            return True

    def claim(self, fence_key: str, fence_val: str, fence_lease: int = 0,
              order_key: str = "", proc_key: str = "", proc_val: str = "",
              proc_lease: int = 0) -> bool:
        """Atomic execution claim — the dispatch plane's per-order hot op.

        One round trip replaces the agent's fence ``put_if_absent`` +
        proc-registry put + order-key delete chain (the reference pays up
        to 3 etcd RPCs per fire: lock txn job.go:243-271, proc put
        proc.go:209-237, and its own cleanup).  Semantics:

        - fence_key already exists -> the claim LOSES: the order key is
          still consumed (another node ran this (job, second)), nothing
          else changes, returns False;
        - otherwise the fence is written (under fence_lease), the proc
          key (if given) is written under proc_lease, the order key (if
          given) is deleted, and the claim WINS: returns True.

        Both leases are validated before any mutation, so an expired
        lease raises KeyError without a half-applied claim.
        """
        self._lazy_expire()
        keys = [k for k in (fence_key, order_key, proc_key) if k]
        with self._locked(keys):
            t0 = time.perf_counter_ns()
            # the lease lock is held across the whole claim so a lease
            # validated here cannot expire between validation and use
            with self._lease_lock:
                for lz in (fence_lease, proc_lease if proc_key else 0):
                    if lz:
                        self._check_lease(lz)
                if fence_key in self._stripes[self._sidx(fence_key)].kv:
                    if order_key:
                        self._delete_locked(order_key)
                    self._op_record("claim", t0)
                    return False
                self._put_locked(fence_key, fence_val, fence_lease)
                if proc_key:
                    self._put_locked(proc_key, proc_val, proc_lease)
                if order_key:
                    self._delete_locked(order_key)
                self._op_record("claim", t0)
                return True

    def claim_many(self, items: Sequence[Sequence[str]],
                   fence_lease: int = 0,
                   proc_lease: int = 0) -> List[bool]:
        """Batched :meth:`claim` under one striped acquisition: ``items``
        is [(fence_key, fence_val, order_key, proc_key, proc_val), ...];
        the two leases are shared by the whole batch (agents pool their
        fence and proc keys on shared leases anyway).  Returns one
        win/lose bool per item — an agent's claim batcher turns a burst
        of due executions into a single store round trip."""
        self._lazy_expire()
        keys = [k for it in items if len(it) >= 5
                for k in (it[0], it[2], it[3]) if k]
        with self._locked(keys):
            t0 = time.perf_counter_ns()
            # malformed items yield per-item False WITHOUT aborting the
            # batch (never a half-applied batch + whole-batch error) —
            # bit-for-bit the native stored's behavior
            any_proc = any(len(it) >= 5 and it[3] for it in items)
            with self._lease_lock:
                for lz in (fence_lease, proc_lease if any_proc else 0):
                    if lz:
                        self._check_lease(lz)
                out = []
                for it in items:
                    if len(it) < 5:
                        out.append(False)
                        continue
                    fence_key, fence_val, order_key, proc_key, proc_val = \
                        it[:5]
                    if fence_key in self._stripes[self._sidx(fence_key)].kv:
                        if order_key:
                            self._delete_locked(order_key)
                        out.append(False)
                        continue
                    self._put_locked(fence_key, fence_val, fence_lease)
                    if proc_key:
                        self._put_locked(proc_key, proc_val, proc_lease)
                    if order_key:
                        self._delete_locked(order_key)
                    out.append(True)
            self._op_record("claim_many", t0)
            return out

    def _claim_bundle_locked(self, order_key: str,
                             items: Sequence[Sequence[str]],
                             fence_lease: int, proc_lease: int) -> List[bool]:
        """Shared claim_bundle body.  Caller holds every involved stripe
        lock AND the lease lock (leases already validated)."""
        out = []
        for it in items:
            if len(it) < 4:
                out.append(False)
                continue
            fence_key, fence_val, proc_key, proc_val = it[:4]
            if fence_key in self._stripes[self._sidx(fence_key)].kv:
                out.append(False)
                continue
            self._put_locked(fence_key, fence_val, fence_lease)
            if proc_key:
                self._put_locked(proc_key, proc_val, proc_lease)
            out.append(True)
        if order_key:
            self._delete_locked(order_key)
        return out

    @staticmethod
    def _bundle_keys(order_key, items) -> List[str]:
        keys = [order_key] if order_key else []
        for it in items:
            if len(it) >= 4:
                keys.append(it[0])
                if it[2]:
                    keys.append(it[2])
        return keys

    def claim_bundle(self, order_key: str,
                     items: Sequence[Sequence[str]],
                     fence_lease: int = 0,
                     proc_lease: int = 0) -> List[bool]:
        """Consume one coalesced (node, second) dispatch bundle in a
        single atomic op: per-job fence claims + proc registrations for
        the winners, then ONE delete of the bundle order key.  ``items``
        is [(fence_key, fence_val, proc_key, proc_val), ...] — proc_key
        may be "" (short-run suppression registers later via the delay
        monitor).  The bundle key is the scheduler's outstanding-capacity
        reservation for the whole bundle; deleting it here — in the same
        locked op that writes the winners' proc keys — means the
        reservation converts to proc-key accounting with no window in
        which capacity is either double-counted or leaked.  Losing items
        (fence already held: another node ran that (job, second)) change
        nothing but still count toward the bundle's consumption; the key
        is deleted regardless of the win/lose mix, exactly once.
        Malformed items yield per-item False without aborting the
        bundle.  Leases are validated before any mutation."""
        self._lazy_expire()
        with self._locked(self._bundle_keys(order_key, items)):
            t0 = time.perf_counter_ns()
            any_proc = any(len(it) >= 4 and it[2] for it in items)
            with self._lease_lock:
                for lz in (fence_lease, proc_lease if any_proc else 0):
                    if lz:
                        self._check_lease(lz)
                out = self._claim_bundle_locked(order_key, items,
                                                fence_lease, proc_lease)
            self._op_record("claim_bundle", t0)
            return out

    def claim_bundle_many(self, bundles: Sequence[Sequence],
                          fence_lease: int = 0,
                          proc_lease: int = 0) -> List[List[bool]]:
        """Consume SEVERAL coalesced bundles in one atomic op: ``bundles``
        is [(order_key, items), ...] with claim_bundle's item format; the
        two leases are shared by every bundle (agents pool fence and proc
        keys on shared leases).  Returns claim_bundle's win list per
        bundle, in order.  One catch-up drain that surfaces a backlog of
        due (node, second) bundles — the herd case — settles them all in
        a single store round trip instead of one RPC per bundle.
        Malformed bundles yield an empty win list without aborting the
        batch; leases are validated before any mutation."""
        self._lazy_expire()
        parsed: List[Optional[Tuple[str, Sequence]]] = []
        keys: List[str] = []
        for b in bundles:
            if len(b) < 2 or not isinstance(b[1], (list, tuple)):
                parsed.append(None)
                continue
            order_key, items = b[0], b[1]
            parsed.append((order_key, items))
            keys.extend(self._bundle_keys(order_key, items))
        with self._locked(keys):
            t0 = time.perf_counter_ns()
            any_proc = any(len(it) >= 4 and it[2]
                           for b in parsed if b is not None
                           for it in b[1])
            with self._lease_lock:
                for lz in (fence_lease, proc_lease if any_proc else 0):
                    if lz:
                        self._check_lease(lz)
                out: List[List[bool]] = []
                for b in parsed:
                    if b is None:
                        out.append([])
                        continue
                    out.append(self._claim_bundle_locked(
                        b[0], b[1], fence_lease, proc_lease))
            self._op_record("claim_bundle_many", t0)
            return out

    # ---- leases ----------------------------------------------------------

    def grant(self, ttl: float) -> int:
        with self._lease_lock:
            lid = self._next_lease
            self._next_lease += 1
            self._leases[lid] = Lease(lid, ttl, self._clock() + ttl)
            self._log(["g", lid, ttl, time.time() + ttl])
            return lid

    def keepalive(self, lease_id: int) -> bool:
        with self._lease_lock:
            l = self._leases.get(lease_id)
            # deadline counts even before the sweeper collects: an
            # expired lease must not be revivable (its keys are doomed)
            if l is None or l.deadline <= self._clock():
                return False
            l.deadline = self._clock() + l.ttl
            self._log(["k", lease_id, time.time() + l.ttl])
            return True

    def revoke(self, lease_id: int) -> bool:
        with self._lease_lock:
            l = self._leases.pop(lease_id, None)
            # lease removal logs as "x" (replay deletes attached keys
            # itself); the deletions below log their own "d" records
            if l is not None:
                self._log(["x", lease_id])
        if l is None:
            return False
        self._delete_keys(sorted(l.keys), only_lease=lease_id)
        return True

    def lease_ttl_remaining(self, lease_id: int) -> Optional[float]:
        with self._lease_lock:
            l = self._leases.get(lease_id)
            return None if l is None else l.deadline - self._clock()

    def _expire_leases(self):
        # cheap empty-table fast path: the common steady state for
        # stores carrying no leases.  Followers NEVER expire locally —
        # the leader ships the "x"/"d" records (repl_apply), otherwise
        # the replicas diverge on expiry timing.
        if not self._leases or self._repl_follower:
            return
        now = self._clock()
        with self._lease_lock:
            expired = [l for l in self._leases.values()
                       if l.deadline <= now]
            for l in expired:
                del self._leases[l.id]
                self._log(["x", l.id])
        # key deletion happens OUTSIDE the lease lock through the normal
        # striped path (lock order: stripes before lease) — a doomed
        # key's events and attachments behave exactly as a delete would
        for l in expired:
            self._delete_keys(sorted(l.keys), only_lease=l.id)

    def _delete_keys(self, keys: Sequence[str], only_lease: int = 0):
        """Striped bulk delete.  ``only_lease`` guards the expiry/revoke
        window: between popping a lease and reaching here, a writer can
        have re-created or re-bound one of its keys under a NEW lease —
        that key now belongs to the new owner and must survive (the old
        global lock made this interleaving impossible; the check
        restores its semantics)."""
        if not keys:
            return
        with self._locked(keys):
            for k in keys:
                if only_lease:
                    cur = self._stripes[self._sidx(k)].kv.get(k)
                    if cur is None or cur.lease != only_lease:
                        continue
                self._delete_locked(k)

    # ---- watch -----------------------------------------------------------

    def watch(self, prefix: str, start_rev: int = 0,
              max_backlog: Optional[int] = None,
              events: str = "") -> Watcher:
        """Watch a prefix.  With ``start_rev`` > 0, replay retained events
        with mod_rev >= start_rev first (etcd WithRev) — a reconnecting
        watcher resumes without losing deltas.  Raises
        :class:`CompactedError` if the bounded history no longer reaches
        back that far, and :class:`WatchLost` if the replay itself
        overflows ``max_backlog`` (re-list instead).  ``events="delete"``
        suppresses PUT pushes server-side (etcd's WithFilterPut): the
        filter applies to the replay too.

        Registration holds every stripe lock (plus the event lock), so
        no concurrent mutation can land between the replayed history and
        the live stream: the client sees one strictly ordered stream."""
        with self._locked(all_stripes=True), self._ev_lock:
            w = Watcher(self, prefix, start_rev or self._rev,
                        max_backlog=max_backlog or Watcher.MAX_BACKLOG,
                        events=events)
            if start_rev and start_rev <= self._rev:
                # every revision 1..rev emitted exactly one event, so the
                # replay is complete iff the ring still holds start_rev
                oldest = (self._history[0].kv.mod_rev if self._history
                          else self._rev + 1)
                if start_rev < oldest and oldest > 1:
                    raise CompactedError(
                        f"start_rev {start_rev} compacted "
                        f"(oldest retained {oldest})")
                for ev in self._history:
                    if (ev.kv.mod_rev >= start_rev
                            and ev.kv.key.startswith(prefix)):
                        w._emit(ev)
                if w.lost:   # replay alone overflowed: don't register a
                    raise WatchLost(   # dead watcher, tell the caller
                        f"watch {prefix!r} replay overflowed; re-list")
            self._watchers.append(w)
            return w

    def _remove_watcher(self, w: Watcher):
        with self._ev_lock:
            if w in self._watchers:
                self._watchers.remove(w)

    def _notify(self, ev: Event):
        """Caller holds the event lock: history append and watcher
        fan-out ride the revision assignment, which keeps every watch
        stream revision-ordered across stripes."""
        t0 = time.perf_counter_ns()
        self._history.append(ev)
        # copy: an overflowing watcher cancels itself (removes from the
        # list) from inside _emit
        for w in list(self._watchers):
            if ev.kv.key.startswith(w.prefix):
                w._emit(ev)
        self._op_record("watch_fanout", t0)
