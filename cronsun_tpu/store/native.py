"""Launcher for the native (C++) coordination store server.

``native/stored.cc`` implements the same wire protocol as
:class:`~cronsun_tpu.store.remote.StoreServer` with memstore semantics —
the production deployment runs it instead of the Python server (no GIL,
O(log n) prefix scans, per-connection outboxes so a slow watcher can't
stall mutations).  This module finds/builds the binary and manages it as
a child process with the same surface as StoreServer (host, port, stop).
"""

from __future__ import annotations

import os
import pathlib
import select
import shutil
import subprocess
import threading
import time
from typing import Callable, List, Optional

from .. import log

_NATIVE_DIR = pathlib.Path(__file__).resolve().parents[2] / "native"
_BINARY = "cronsun-stored"


def find_binary(build: bool = True) -> Optional[str]:
    """Locate the server binary: $CRONSUN_STORED, then the repo's
    native/ build, then $PATH.  With ``build``, compile it from source
    when the binary is missing or older than stored.cc."""
    env = os.environ.get("CRONSUN_STORED")
    if env and os.access(env, os.X_OK):
        return env
    cand = _NATIVE_DIR / _BINARY
    src = _NATIVE_DIR / "stored.cc"
    if src.exists() and build:
        stale = (not cand.exists()
                 or cand.stat().st_mtime < src.stat().st_mtime)
        if stale:
            try:
                subprocess.run(["make", "-C", str(_NATIVE_DIR)],
                               check=True, capture_output=True, timeout=120)
            except (subprocess.SubprocessError, OSError) as e:
                log.warnf("native store build failed: %s", e)
    if cand.exists() and os.access(cand, os.X_OK):
        return str(cand)
    return shutil.which(_BINARY)


class NativeStoreServer:
    """Run cronsun-stored as a child process; same lifecycle surface as
    the Python StoreServer.  ``port=0`` picks a free port (the server
    prints the resolved one on its READY line)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 binary: Optional[str] = None, history: int = 65536,
                 wal: Optional[str] = None, token: str = "",
                 extra_args: Optional[List[str]] = None,
                 ready_timeout: float = 10.0):
        self.binary = binary or find_binary()
        if self.binary is None:
            raise FileNotFoundError(
                "cronsun-stored not found (set $CRONSUN_STORED or build "
                "native/)")
        argv = [self.binary, "--host", host, "--port", str(port),
                "--history", str(history),
                "--die-with-parent"] + (extra_args or [])
        if wal:
            argv += ["--wal", wal]
        token_path = None
        if token:
            # hand the secret over in a 0600 file, not argv (argv is
            # world-readable via /proc/<pid>/cmdline); removed once the
            # child has read it
            import tempfile
            tfd, token_path = tempfile.mkstemp(prefix="cronsun-tok-")
            os.write(tfd, token.encode())
            os.close(tfd)
            argv += ["--token-file", token_path]
        # stderr merged into stdout so a startup failure (bind error …)
        # surfaces in the exception instead of vanishing
        try:
            self._proc = subprocess.Popen(
                argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
            self._stopping = False
            line = self._read_ready(ready_timeout)
        finally:
            if token_path:
                try:
                    os.unlink(token_path)
                except OSError:
                    pass
        addr = line.split(" ", 1)[1]
        self.host, port_s = addr.rsplit(":", 1)
        self.port = int(port_s)

    def _read_ready(self, timeout: float) -> str:
        """Bounded wait for the READY line; on failure, kill the child and
        raise with whatever it printed."""
        fd = self._proc.stdout.fileno()
        deadline = time.monotonic() + timeout
        lines: List[str] = []
        while time.monotonic() < deadline:
            r, _, _ = select.select([fd], [], [],
                                    max(0.0, deadline - time.monotonic()))
            if not r:
                break
            line = self._proc.stdout.readline()
            if not line:        # EOF: child exited
                break
            lines.append(line)
            if line.startswith("READY "):
                return line.strip()
        self._proc.kill()
        raise RuntimeError(
            f"native store failed to start within {timeout}s: "
            f"{''.join(lines).strip()!r}")

    def monitor(self, on_exit: Callable[[int], None]):
        """Watch the child; call ``on_exit(rc)`` if it dies without
        :meth:`stop` — so a supervising process doesn't sit healthy-looking
        in front of a dead store."""
        def run():
            rc = self._proc.wait()
            if not self._stopping:
                on_exit(rc)
        threading.Thread(target=run, daemon=True,
                         name="native-store-monitor").start()

    def start(self) -> "NativeStoreServer":
        return self     # already serving (READY consumed in __init__)

    def stop(self):
        self._stopping = True
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
