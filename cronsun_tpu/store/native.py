"""Launcher for the native (C++) coordination store server.

``native/stored.cc`` implements the same wire protocol as
:class:`~cronsun_tpu.store.remote.StoreServer` with memstore semantics —
the production deployment runs it instead of the Python server (no GIL,
O(log n) prefix scans, per-connection outboxes so a slow watcher can't
stall mutations).  Spawn/READY/monitor/stop plumbing is the shared
:mod:`cronsun_tpu.native_launcher`.
"""

from __future__ import annotations

from typing import List, Optional

from ..native_launcher import NativeProcess, find_binary as _find


def find_binary(build: bool = True) -> Optional[str]:
    return _find("cronsun-stored", "CRONSUN_STORED", build)


class NativeStoreServer(NativeProcess):
    """Run cronsun-stored as a child process; same lifecycle surface as
    the Python StoreServer.  ``port=0`` picks a free port (the server
    prints the resolved one on its READY line)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 binary: Optional[str] = None, history: int = 65536,
                 wal: Optional[str] = None, token: str = "",
                 stripes: int = 0, compact_wal_bytes: int = -1,
                 snapshot_staggered: bool = True,
                 extra_args: Optional[List[str]] = None,
                 ready_timeout: float = 10.0):
        binary = binary or find_binary()
        if binary is None:
            raise FileNotFoundError(
                "cronsun-stored not found (set $CRONSUN_STORED or build "
                "native/)")
        self.binary = binary
        argv = ["--host", host, "--port", str(port),
                "--history", str(history)] + (extra_args or [])
        if stripes > 0:
            argv += ["--stripes", str(stripes)]
        if wal:
            argv += ["--wal", wal]
        if compact_wal_bytes >= 0:
            # size-triggered WAL compaction threshold (checkpoint
            # plane); 0 disables it, negative keeps the server default
            argv += ["--compact-wal-bytes", str(compact_wal_bytes)]
        if not snapshot_staggered:
            # rollback switch: full-lock snapshot imaging (the PR 5
            # behavior, and the write-stall bench's baseline)
            argv += ["--snapshot-staggered", "0"]
        super().__init__(binary, argv, token=token,
                         ready_timeout=ready_timeout)
