"""Horizontal store sharding: a routing client over N ``stored`` shards.

PR 3's striping scaled the store WITHIN one process; every RPC still
funneled through one ``stored`` — one WAL, one event plane, one accept
loop — and aggregate drain plateaued there (~20.6k orders/s at 8
agents).  This module partitions the KEYSPACE across N independent
store processes, each a perfectly ordinary ``stored`` (same wire
protocol, same WAL + snapshot checkpoint format, just a smaller
keyspace), and gives every component a drop-in client with the exact
MemStore/RemoteStore surface.

Routing — deterministic, shared with ``native/agentd.cc`` bit-for-bit:

- :func:`shard_token` extracts a ROUTING TOKEN from the key so related
  keys co-locate by key design (the pjit partitioning move: shard by
  key, keep hot paths local):

  * ``lock/<job>/<sec>``, ``proc/<node>/<grp>/<job>/<pid>``,
    ``cmd/<grp>/<job>``, ``once/<grp>/<job>``, ``phase/<grp>/<job>/…``
    all route by the JOB — a fire's fence, proc key, and job document
    live on ONE shard, so the per-item fence+proc claim stays atomic
    and the bundle-resolve ``get_many`` groups exactly like the claims
    that follow it;
  * ``dispatch/<node>/…`` and ``node/<id>`` route by the NODE — an
    agent's order stream and liveness key live on one shard;
  * everything else routes by the full key.

- :func:`fnv1a` (64-bit FNV-1a over UTF-8) maps the token to a shard.
  Python's builtin ``hash`` (the intra-process stripe hash) is salted
  per process and can't agree across the fleet; FNV-1a is the same
  scheme made deterministic.

A coalesced (node, second) bundle's items therefore PARTITION by job
hash: :meth:`ShardedStore.claim_bundle` splits the bundle into one
sub-bundle per shard and fans them out CONCURRENTLY (wall-clock is the
slowest shard, not the sum), with the reservation-key delete ordered
LAST — a crash mid-bundle leaves the leased order key for redelivery
instead of losing members, exactly the PR 4 chunking contract.  The
(job, second) fences keep their global exactly-once meaning because a
fence key routes the same everywhere, whoever claims it.

Watches open one stream per shard and merge into a single
:class:`ShardedWatcher`: per-shard ordering is preserved (each shard's
events arrive in its revision order), cross-shard interleaving is
arbitrary (there is no global revision), and the merged stream carries
a PER-SHARD REVISION VECTOR (:meth:`ShardedWatcher.rev_vector`) for
resume.  Any shard's stream overflowing makes the merged stream lossy
— buffered tail first, then :class:`WatchLost` — the same re-list
contract consumers already implement.

Leases are granted on EVERY shard and exposed as one composite id; the
registry translating composite→per-shard ids is shared with
:meth:`ShardedStore.clone` children, so a lease granted on the main
client works from a publisher lane.  Composite ids are meaningful only
within the granting client (and its clones) — the server-side leases
themselves expire by TTL exactly as before.

The shard topology is pinned by a SHARD-MAP key on shard 0
(``<prefix>/shardmap``): the first client publishes ``{"n": N,
"hash": HASH_SCHEME}``, every later client verifies it, and a client
configured with a different shard count refuses to start instead of
silently scattering the keyspace under a second topology.

With ONE shard every operation passes through verbatim — no split, no
lease translation, no shard-map write: the 1-shard configuration is
behaviorally identical to a plain client.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

from .. import log
from ..core.breaker import BreakerBank, ShardDegradedError  # noqa: F401
# (ShardDegradedError re-exported: it is the error sharded-store
# callers catch around fail-fast claims)
from .memstore import CompactedError, Event, KV, LossyEventStream, \
    WatchLost

HASH_SCHEME = "fnv1a-token-v1"

_FNV_OFFSET = 0xcbf29ce484222325
_FNV_PRIME = 0x100000001b3
_MASK64 = (1 << 64) - 1


def fnv1a(s: str) -> int:
    """64-bit FNV-1a over UTF-8 bytes — deterministic across processes
    and languages (native/agentd.cc carries the same constants)."""
    h = _FNV_OFFSET
    for b in s.encode("utf-8"):
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def shard_token(key: str, prefix: str = "/cronsun") -> str:
    """Routing token for ``key`` (see module docstring for the
    co-location design).  Keys outside the keyspace prefix route by
    their full text — always deterministic, never an error."""
    pfx = prefix + "/"
    if not key.startswith(pfx):
        return key
    seg = key[len(pfx):].split("/")
    comp = seg[0]
    if comp in ("dispatch", "node") and len(seg) >= 2 and seg[1]:
        return "n:" + seg[1]
    if comp == "lock":
        if len(seg) >= 3 and seg[1] == "alone" and seg[2]:
            return "j:" + seg[2]
        if len(seg) >= 2 and seg[1]:
            return "j:" + seg[1]
    if comp == "proc" and len(seg) >= 4 and seg[3]:
        return "j:" + seg[3]
    if comp in ("cmd", "once", "phase") and len(seg) >= 3 and seg[2]:
        return "j:" + seg[2]
    return key


def shard_index(key: str, nshards: int, prefix: str = "/cronsun") -> int:
    if nshards <= 1:
        return 0
    if key == prefix + "/shardmap":
        return 0            # the topology pin lives on shard 0 by fiat
    return fnv1a(shard_token(key, prefix)) % nshards


def prefix_shard_token(pfx_str: str, prefix: str = "/cronsun") -> Optional[str]:
    """Routing token shared by EVERY key under ``pfx_str``, or None when
    keys under it can hash to different shards.  A segment counts only
    when the prefix CLOSES it with a '/' — ``…/dispatch/A`` also matches
    node "AB", so only ``…/dispatch/A/`` pins to token "n:A".  Lets
    prefix ops (watch / get_prefix / count_prefix / delete_prefix) route
    to ONE shard instead of fanning N ways: an agent's dispatch watch is
    one stream, not N-1 idle ones."""
    pfx = prefix + "/"
    if not pfx_str.startswith(pfx):
        return None
    seg = pfx_str[len(pfx):].split("/")

    def closed(i):              # segment i is complete (a '/' follows)
        return i < len(seg) - 1 and seg[i]

    comp = seg[0]
    if comp in ("dispatch", "node") and closed(1):
        return "n:" + seg[1]
    if comp == "lock":
        if closed(1) and seg[1] == "alone":
            return "j:" + seg[2] if closed(2) else None
        if closed(1):
            return "j:" + seg[1]
        return None
    if comp == "proc" and closed(3):
        return "j:" + seg[3]
    if comp in ("cmd", "once", "phase") and closed(2):
        return "j:" + seg[2]
    return None


def shard_map_key(prefix: str = "/cronsun") -> str:
    """The topology pin.  Lives on shard 0 BY FIAT (not by hash): a
    client must be able to read it knowing only the shard list."""
    return f"{prefix}/shardmap"


def breaker_env_deadline() -> float:
    """Per-shard RPC deadline from the environment; 0 disables the
    breaker (the default — single-host deployments and the tier-1
    suite see no behavior change)."""
    try:
        return float(os.environ.get("CRONSUN_SHARD_DEADLINE_S", "0") or 0)
    except ValueError:
        return 0.0


# server answers that are NOT shard-health failures: the RPC completed,
# the server just said no (missing lease, compacted watch history, a
# cancelled stream) — only transport errors and deadline overruns count
_HEALTHY_ERRORS = (KeyError, CompactedError, WatchLost)


class ShardedWatcher(LossyEventStream):
    """Merged view over one watch stream per shard.

    One forwarder thread per child drains that shard's stream into the
    shared queue: events from one shard arrive in that shard's revision
    order (the per-shard contract), cross-shard interleaving is
    arbitrary.  A child raising :class:`WatchLost` marks the MERGED
    stream lost — buffered tail first, then WatchLost, the standard
    re-list contract.  :meth:`rev_vector` snapshots each child's resume
    point; pass it back as ``start_rev`` to resume every shard's stream
    exactly where this one left off."""

    def __init__(self, prefix: str, children: Sequence, events: str = "",
                 shard_ids: Optional[Sequence[int]] = None,
                 nshards: int = 0,
                 start_revs: Optional[Sequence[int]] = None):
        super().__init__(prefix)
        self.events = events
        self._children = list(children)
        # a token-pinned prefix opens fewer streams than there are
        # shards; shard_ids maps child position -> GLOBAL shard index
        # so rev_vector() keeps the full-length resume contract
        self._ids = (list(shard_ids) if shard_ids is not None
                     else list(range(len(self._children))))
        # seed the resume tracker from the vector this watch resumed
        # at: a shard that delivers nothing before the next
        # rev_vector() snapshot must report ITS resume point back, not
        # regress to 0 ("resume live") and silently skip its backlog
        if start_revs is not None:
            self._revs = [rv - 1 if rv else 0 for rv in start_revs]
        else:
            self._revs = [0] * max(nshards, len(self._children))
        self._halted = False
        self._threads = []
        for i, ch in enumerate(self._children):
            t = threading.Thread(target=self._forward,
                                 args=(self._ids[i], ch),
                                 daemon=True, name="shard-watch-fwd")
            t.start()
            self._threads.append(t)

    def _halt(self):
        """One shard lost the stream: stop EVERY forwarder so the
        merged queue stops refilling.  The single-stream WatchLost
        guarantee ("buffered tail, then raise — never a silent starve")
        rests on the producer going quiet after loss; with live shards
        still feeding the queue, a busy consumer's drain() would keep
        returning non-empty batches and never surface the loss."""
        self.lost = True
        self._halted = True
        for ch in self._children:
            try:
                ch.close()
            except Exception:  # noqa: BLE001 — already dead
                pass

    def _forward(self, idx, child):
        while not self._closed and not self._halted:
            try:
                ev = child.get(timeout=0.25)
            except WatchLost:
                self._halt()
                self._q.put(None)
                return
            if ev is not None:
                self._q.put((idx, ev))
            elif getattr(child, "_closed", False):
                if child.lost:
                    self._halt()
                    self._q.put(None)
                return

    # the queue holds (shard_idx, event) so the per-shard resume
    # revision advances at CONSUME time — rev_vector() reflects what
    # the consumer has actually seen, not what forwarders buffered
    def get(self, timeout=None):
        ev = super().get(timeout=timeout)
        if ev is None:
            return None
        idx, ev = ev
        rev = getattr(ev.kv, "mod_rev", 0)
        if rev > self._revs[idx]:
            self._revs[idx] = rev
        return ev

    def drain(self) -> List[Event]:
        out = []
        for idx, ev in super().drain():
            rev = getattr(ev.kv, "mod_rev", 0)
            if rev > self._revs[idx]:
                self._revs[idx] = rev
            out.append(ev)
        return out

    def rev_vector(self) -> List[int]:
        """Per-shard RESUME revisions: pass this vector back as
        ``start_rev`` to resume every shard after the last event this
        consumer saw (inclusive-replay semantics, so entries are
        last_seen + 1; 0 where the shard has delivered nothing —
        resume live)."""
        return [rv + 1 if rv else 0 for rv in self._revs]

    def close(self):
        if self._closed:
            return
        self._closed = True
        for ch in self._children:
            try:
                ch.close()
            except Exception:  # noqa: BLE001 — already dead
                pass
        self._q.put(None)


class ShardedStore:
    """Routing client over N store shards with the full
    MemStore/RemoteStore surface — scheduler, agents, web, and noticer
    run unchanged against it.

    ``shards`` is a list of store clients (RemoteStore per shard in
    production; MemStore works too, which is what the conformance
    tests use).  Single-key ops route directly; multi-key ops split
    per shard and fan out concurrently on a small pool; claims keep
    their per-item atomicity on the fence's shard (see module
    docstring for the bundle ordering contract)."""

    def __init__(self, shards: Sequence, prefix: str = "/cronsun",
                 verify_map: bool = True, _parent: "ShardedStore" = None,
                 shard_deadline: Optional[float] = None,
                 breaker_fails: int = 3, breaker_cooldown: float = 1.0):
        if not shards:
            raise ValueError("ShardedStore needs at least one shard")
        self._raw = list(shards)       # unguarded clients (lifecycle)
        self.nshards = len(self._raw)
        self.prefix = prefix
        # per-shard brownout handling: with a deadline configured
        # (param, or CRONSUN_SHARD_DEADLINE_S), each shard client is
        # wrapped in a breaker guard — ops against an OPEN shard fail
        # fast, tolerant reads skip it with a loud shard_degraded
        # count, and the plane's latency is bounded by its healthy
        # shards.  deadline <= 0 (the default) disables everything:
        # self.shards IS self._raw and behavior is byte-identical.
        if shard_deadline is None:
            shard_deadline = breaker_env_deadline()
        self.shard_deadline = shard_deadline
        if _parent is not None:
            # clones (publisher lanes) share the parent's bank: shard
            # health is a property of the SHARD, not of the lane
            # observing it
            self._bank = _parent._bank
        else:
            self._bank = BreakerBank(self.nshards, shard_deadline,
                                     fail_threshold=breaker_fails,
                                     cooldown=breaker_cooldown,
                                     label="store shard")
            # a shard browning out should PAGE, not just count: an
            # OPEN transition writes a (rate-limited) notice key the
            # NoticerHost delivers — routed through this same client,
            # so it lands on a healthy shard immediately or on the
            # broken one as it heals (core/breaker.py arm_notices)
            self._bank.arm_notices(self, prefix)
        self._breakers = self._bank.breakers
        self.shards = self._bank.guards(self._raw,
                                        healthy_errors=_HEALTHY_ERRORS)
        # close() closes only shards this instance opened: a clone()
        # over shard clients with no clone() of their own (MemStore)
        # ALIASES the parent's shards, and closing those would kill the
        # parent's live watchers and WAL mid-flight
        self._owned = [True] * self.nshards
        self._pool = (ThreadPoolExecutor(
            max_workers=max(2, 2 * self.nshards) +
            (2 * self.nshards if shard_deadline > 0 else 0),
            thread_name_prefix="shard-fan") if self.nshards > 1 else None)
        if _parent is not None:
            # clones (publisher lanes) share the composite-lease
            # registry: a lease granted on the main client must work
            # from any lane
            self._lease_mu = _parent._lease_mu
            self._lease_map = _parent._lease_map
            self._lease_ctr = _parent._lease_ctr
        else:
            self._lease_mu = threading.Lock()
            self._lease_map: Dict[int, List[int]] = {}
            self._lease_ctr = itertools.count(1)
        if self.nshards > 1 and verify_map and _parent is None:
            self._pin_shard_map()

    # ---- routing ---------------------------------------------------------

    def _idx(self, key: str) -> int:
        return shard_index(key, self.nshards, self.prefix)

    def _shard(self, key: str):
        return self.shards[self._idx(key)]

    def _prefix_idx(self, pfx_str: str) -> Optional[int]:
        """Shard index when every key under ``pfx_str`` routes there
        (the prefix closes the routing token), else None — prefix ops
        use this to go single-shard instead of fanning N ways."""
        if self.nshards == 1:
            return 0
        tok = prefix_shard_token(pfx_str, self.prefix)
        return None if tok is None else fnv1a(tok) % self.nshards

    def _fan(self, fns):
        """Run thunks concurrently (one per shard touched); re-raises
        the first failure after all complete.  With one thunk — or one
        shard — runs inline."""
        fns = list(fns)
        if len(fns) == 1 or self._pool is None:
            return [fn() for fn in fns]
        futs = [self._pool.submit(fn) for fn in fns]
        out, first_err = [], None
        for f in futs:
            try:
                out.append(f.result())
            except BaseException as e:  # noqa: BLE001 — collected below
                out.append(None)
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return out

    def _tolerant(self, i: int, fn, default=None):
        """Partial-tolerant fan thunk (core.breaker.BreakerBank): an
        open breaker yields ``default`` (counted loudly) instead of
        failing the scatter-gather."""
        return self._bank.tolerant(i, fn, default=default)

    def breaker_snapshot(self) -> List[dict]:
        """Per-shard breaker state + degraded-read counts (rendered at
        /v1/metrics; the chaos bench reads it too).  Empty when the
        breaker is disabled."""
        return self._bank.snapshot()

    def _pin_shard_map(self):
        key = shard_map_key(self.prefix)
        want = {"n": self.nshards, "hash": HASH_SCHEME}
        s0 = self.shards[0]
        s0.put_if_absent(key, json.dumps(want, sort_keys=True))
        kv = s0.get(key)
        try:
            got = json.loads(kv.value) if kv else None
        except ValueError:
            got = None
        if not isinstance(got, dict) or got.get("n") != self.nshards \
                or got.get("hash") != HASH_SCHEME:
            raise RuntimeError(
                f"shard-map mismatch at {key}: store set was laid out "
                f"as {got!r}, this client is configured for {want!r} — "
                "refusing to scatter one keyspace under two topologies")

    # ---- leases ----------------------------------------------------------

    def _xlease(self, lease: int, idx: int) -> int:
        """Composite→per-shard lease id for shard ``idx``."""
        if not lease or self.nshards == 1:
            return lease
        with self._lease_mu:
            ids = self._lease_map.get(lease)
        if ids is None:
            raise KeyError(f"lease {lease} not found")
        return ids[idx]

    def grant(self, ttl: float) -> int:
        if self.nshards == 1:
            return self.shards[0].grant(ttl)
        # sequential with rollback (the C++ mirror's shape): a later
        # shard failing must not strand live TTL leases on the earlier
        # ones — callers retry grants in a loop, and each stranded set
        # would pin its keys for the full TTL.
        #
        # BROWNOUT tolerance: a shard whose breaker is OPEN gets the
        # server-impossible -1 sentinel as its leg instead of failing
        # the WHOLE composite — one browned-out shard must not take
        # every healthy shard's lease plane (fences, proc registry,
        # node liveness) down with it.  Writes that would use the -1
        # leg are refused by the open breaker anyway; once the shard
        # heals, -1 is rejected LOUDLY server-side and the caller's
        # rotate/regrant ladder mints a full composite (the PR 6
        # xlease contract — never silently unleased).
        ids: List[int] = []
        degraded = 0
        try:
            for s in self.shards:
                try:
                    ids.append(s.grant(ttl))
                except ShardDegradedError:
                    ids.append(-1)
                    degraded += 1
        except BaseException:
            for s, i in zip(self.shards, ids):
                if i == -1:
                    continue
                try:
                    s.revoke(i)
                except Exception:  # noqa: BLE001 — already failing
                    pass
            raise
        if degraded == self.nshards:
            raise ShardDegradedError(
                "every shard's breaker is open; no lease granted")
        with self._lease_mu:
            cid = next(self._lease_ctr)
            self._lease_map[cid] = ids
        return cid

    def keepalive(self, lease_id: int) -> bool:
        if self.nshards == 1:
            return self.shards[0].keepalive(lease_id)
        with self._lease_mu:
            ids = self._lease_map.get(lease_id)
        if ids is None:
            return False
        # a -1 leg (granted while that shard's breaker was open) has
        # nothing to keep alive; a leg whose shard is degraded NOW is
        # UNKNOWN — treated alive, because the caller's reaction to
        # False (revoke + regrant + re-put every key) would fail
        # against the same open breaker and thrash the healthy shards.
        # The degraded shard's leg may expire server-side meanwhile:
        # that shard's keys are its own bounded brownout loss, exactly
        # the fail-fast contract's blast radius.
        def one(s, i):
            if i == -1:
                return True
            try:
                return s.keepalive(i)
            except ShardDegradedError:
                return True
        oks = self._fan([lambda s=s, i=i: one(s, i)
                         for s, i in zip(self.shards, ids)])
        return all(oks)

    def revoke(self, lease_id: int) -> bool:
        if self.nshards == 1:
            return self.shards[0].revoke(lease_id)
        with self._lease_mu:
            ids = self._lease_map.pop(lease_id, None)
        if ids is None:
            return False

        def one(s, i):
            if i == -1:
                return False
            try:
                return s.revoke(i)
            except ShardDegradedError:
                return False   # leg expires by TTL on the open shard
        oks = self._fan([lambda s=s, i=i: one(s, i)
                         for s, i in zip(self.shards, ids)])
        return any(oks)

    def lease_ttl_remaining(self, lease_id: int) -> Optional[float]:
        if self.nshards == 1:
            return self.shards[0].lease_ttl_remaining(lease_id)
        with self._lease_mu:
            ids = self._lease_map.get(lease_id)
        if ids is None:
            return None
        def one(s, i):
            if i == -1:
                return None    # leg never granted (degraded shard)
            try:
                return s.lease_ttl_remaining(i)
            except ShardDegradedError:
                return None
        outs = self._fan([lambda s=s, i=i: one(s, i)
                          for s, i in zip(self.shards, ids)])
        live = [o for o in outs if o is not None]
        return min(live) if len(live) == len(outs) else None

    # ---- KV --------------------------------------------------------------

    def put(self, key: str, value: str, lease: int = 0) -> int:
        i = self._idx(key)
        return self.shards[i].put(key, value, lease=self._xlease(lease, i))

    def put_many(self, items, lease: int = 0) -> int:
        items = list(items)
        if self.nshards == 1:
            return self.shards[0].put_many(items, lease=lease)
        groups: Dict[int, list] = {}
        for it in items:
            groups.setdefault(self._idx(it[0]), []).append(it)
        revs = self._fan([
            lambda i=i, g=g: self.shards[i].put_many(
                g, lease=self._xlease(lease, i))
            for i, g in groups.items()])
        return max(revs) if revs else 0

    def get(self, key: str) -> Optional[KV]:
        return self._shard(key).get(key)

    def get_many(self, keys) -> List[Optional[KV]]:
        keys = list(keys)
        if self.nshards == 1:
            return self.shards[0].get_many(keys)
        groups: Dict[int, List[int]] = {}
        for pos, k in enumerate(keys):
            groups.setdefault(self._idx(k), []).append(pos)
        parts = self._fan([
            lambda i=i, ps=ps: self.shards[i].get_many(
                [keys[p] for p in ps])
            for i, ps in groups.items()])
        out: List[Optional[KV]] = [None] * len(keys)
        for ps, part in zip(groups.values(), parts):
            for p, kv in zip(ps, part):
                out[p] = kv
        return out

    def get_prefix(self, prefix: str) -> List[KV]:
        # STRICT: a breaker-open shard fails the whole scan fast (still
        # bounded latency — an error, not a stall).  Consumers that
        # diff a listing against local state and treat missing keys as
        # DELETIONS (the scheduler's resync, group scrubs) must never
        # silently receive a partial result; dashboards that can
        # tolerate one opt in via get_prefix_degraded.
        pi = self._prefix_idx(prefix)
        if pi is not None:
            return self.shards[pi].get_prefix(prefix)
        parts = self._fan([lambda s=s: s.get_prefix(prefix)
                           for s in self.shards])
        hits = [kv for part in parts for kv in part]
        hits.sort(key=lambda kv: kv.key)
        return hits

    def get_prefix_degraded(self, prefix: str) -> List[KV]:
        """Partial-tolerant prefix scan for DASHBOARD reads: a
        breaker-open shard's keys are simply absent, counted loudly as
        shard_degraded — one browned-out shard costs its own keys, not
        the whole view.  Never use where a missing key is interpreted
        as a deletion."""
        pi = self._prefix_idx(prefix)
        if pi is not None:
            run = self._tolerant(
                pi, lambda: self.shards[pi].get_prefix(prefix),
                default=[])
            return run()
        parts = self._fan([
            self._tolerant(i, lambda s=s: s.get_prefix(prefix))
            for i, s in enumerate(self.shards)])
        hits = [kv for part in parts if part for kv in part]
        hits.sort(key=lambda kv: kv.key)
        return hits

    def get_prefix_page(self, prefix: str, start_after: str = "",
                        limit: int = 50_000) -> List[KV]:
        pi = self._prefix_idx(prefix)
        if pi is not None:
            return self.shards[pi].get_prefix_page(prefix, start_after,
                                                   limit)
        import heapq
        parts = self._fan([
            lambda s=s: s.get_prefix_page(prefix, start_after, limit)
            for s in self.shards])
        return heapq.nsmallest(max(1, limit),
                               (kv for part in parts for kv in part),
                               key=lambda kv: kv.key)

    def get_prefix_paged(self, prefix: str, page: int = 50_000):
        # per-shard cursors: each shard's stream is already sorted, so
        # paging every shard independently and merging ships each key
        # exactly once (one global cursor advances only ~page/N per
        # shard per round, re-fetching the rest up to N times on the
        # scheduler's cold-load path)
        page = max(1, page)

        def shard_stream(s):
            if hasattr(s, "get_prefix_paged"):   # keeps RemoteStore's
                return s.get_prefix_paged(prefix, page)  # old-server fallback

            def gen():
                after = ""
                while True:
                    kvs = s.get_prefix_page(prefix, after, page)
                    yield from kvs
                    if len(kvs) < page:
                        return
                    after = kvs[-1].key
            return gen()

        pi = self._prefix_idx(prefix)
        if pi is not None:
            yield from shard_stream(self.shards[pi])
            return
        import heapq
        yield from heapq.merge(*(shard_stream(s) for s in self.shards),
                               key=lambda kv: kv.key)

    def count_prefix(self, prefix: str) -> int:
        pi = self._prefix_idx(prefix)
        if pi is not None:
            return self.shards[pi].count_prefix(prefix)
        return sum(self._fan([lambda s=s: s.count_prefix(prefix)
                              for s in self.shards]))

    def count_prefix_degraded(self, prefix: str) -> int:
        """Partial-tolerant count (see get_prefix_degraded): an open
        shard contributes 0, counted loudly."""
        pi = self._prefix_idx(prefix)
        if pi is not None:
            return self._tolerant(
                pi, lambda: self.shards[pi].count_prefix(prefix),
                default=0)()
        return sum(self._fan([
            self._tolerant(i, lambda s=s: s.count_prefix(prefix),
                           default=0)
            for i, s in enumerate(self.shards)]))

    def delete(self, key: str) -> bool:
        return self._shard(key).delete(key)

    def delete_prefix(self, prefix: str) -> int:
        pi = self._prefix_idx(prefix)
        if pi is not None:
            return self.shards[pi].delete_prefix(prefix)
        return sum(self._fan([lambda s=s: s.delete_prefix(prefix)
                              for s in self.shards]))

    def delete_many(self, keys) -> int:
        keys = list(keys)
        if self.nshards == 1:
            return self.shards[0].delete_many(keys)
        groups: Dict[int, list] = {}
        for k in keys:
            groups.setdefault(self._idx(k), []).append(k)
        return sum(self._fan([
            lambda i=i, g=g: self.shards[i].delete_many(g)
            for i, g in groups.items()]))

    # ---- txns ------------------------------------------------------------

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        i = self._idx(key)
        return self.shards[i].put_if_absent(
            key, value, lease=self._xlease(lease, i))

    def put_if_mod_rev(self, key: str, value: str, mod_rev: int,
                       lease: int = 0) -> bool:
        i = self._idx(key)
        return self.shards[i].put_if_mod_rev(
            key, value, mod_rev, lease=self._xlease(lease, i))

    # ---- claims ----------------------------------------------------------
    #
    # Per-item atomicity (fence + co-located proc) happens on the
    # FENCE's shard; a proc or order key that hashes elsewhere — rare
    # by key design, see module docstring — is applied around it:
    # remote proc puts for winners first, the order-key release LAST,
    # so a failure mid-way leaves the leased reservation for
    # redelivery and never a consumed order with unapplied members.

    def claim(self, fence_key: str, fence_val: str, fence_lease: int = 0,
              order_key: str = "", proc_key: str = "", proc_val: str = "",
              proc_lease: int = 0) -> bool:
        fi = self._idx(fence_key)
        order_local = bool(order_key) and self._idx(order_key) == fi
        proc_local = bool(proc_key) and self._idx(proc_key) == fi
        won = self.shards[fi].claim(
            fence_key, fence_val, self._xlease(fence_lease, fi),
            order_key if order_local else "",
            proc_key if proc_local else "",
            proc_val if proc_local else "",
            self._xlease(proc_lease, fi) if proc_local else 0)
        if won and proc_key and not proc_local:
            pi = self._idx(proc_key)
            self.shards[pi].put(proc_key, proc_val,
                                lease=self._xlease(proc_lease, pi))
        if order_key and not order_local:
            self._shard(order_key).delete(order_key)
        return won

    def claim_many(self, items, fence_lease: int = 0,
                   proc_lease: int = 0) -> List[bool]:
        items = [list(it) for it in items]
        if self.nshards == 1:
            return self.shards[0].claim_many(items, fence_lease,
                                             proc_lease)
        # split per fence shard; strip keys that hash elsewhere (they
        # are applied around the claim, below)
        groups: Dict[int, List[Tuple[int, list]]] = {}
        out: List[bool] = [False] * len(items)
        for pos, it in enumerate(items):
            if len(it) < 5:
                continue       # malformed: per-item False, like memstore
            fi = self._idx(it[0])
            sub = list(it)
            if sub[2] and self._idx(sub[2]) != fi:
                sub[2] = ""
            if sub[3] and self._idx(sub[3]) != fi:
                sub[3] = sub[4] = ""
            groups.setdefault(fi, []).append((pos, sub))
        parts = self._fan([
            lambda i=i, g=g: self.shards[i].claim_many(
                [sub for _p, sub in g],
                self._xlease(fence_lease, i),
                self._xlease(proc_lease, i))
            for i, g in groups.items()])
        proc_puts: Dict[int, list] = {}
        order_dels: Dict[int, list] = {}
        for (i, g), wins in zip(groups.items(), parts):
            for (pos, _sub), won in zip(g, wins):
                out[pos] = won
                it = items[pos]
                if it[2] and self._idx(it[2]) != i:
                    order_dels.setdefault(self._idx(it[2]),
                                          []).append(it[2])
                if won and it[3] and self._idx(it[3]) != i:
                    proc_puts.setdefault(self._idx(it[3]),
                                         []).append((it[3], it[4]))
        if proc_puts:
            self._fan([lambda i=i, ps=ps: self.shards[i].put_many(
                ps, lease=self._xlease(proc_lease, i))
                for i, ps in proc_puts.items()])
        if order_dels:
            self._fan([lambda i=i, ks=ks: self.shards[i].delete_many(ks)
                       for i, ks in order_dels.items()])
        return out

    def _split_bundle(self, order_key: str, items):
        """One bundle -> per-shard sub-bundles.  Returns (groups, oi,
        stripped) where groups maps shard -> [(item_pos, sub_item)],
        ``oi`` is the order key's shard (None without one), and
        ``stripped`` holds (pos, proc_key, proc_val) for proc keys that
        hash off their fence's shard — removed from the claim and, for
        winners, applied as a routed put AFTER it (the claim/claim_many
        contract: a won fence never silently loses its proc
        registration; by token design this edge is structurally rare)."""
        groups: Dict[int, List[Tuple[int, list]]] = {}
        stripped: List[Tuple[int, str, str]] = []
        for pos, it in enumerate(items):
            it = list(it)
            if len(it) < 4:
                # malformed items must still yield per-item False from
                # SOME shard — route them with the bundle's order key
                # (or shard 0) so the win-list length is preserved
                anchor = self._idx(order_key) if order_key else 0
                groups.setdefault(anchor, []).append((pos, it))
                continue
            fi = self._idx(it[0])
            if it[2] and self._idx(it[2]) != fi:
                stripped.append((pos, it[2], it[3]))
                it[2] = it[3] = ""
            groups.setdefault(fi, []).append((pos, it))
        oi = self._idx(order_key) if order_key else None
        return groups, oi, stripped

    def _put_stripped_procs(self, stripped, wins, proc_lease: int):
        """Routed puts for winners whose proc key hashed off the fence
        shard (post-claim, like claim()'s remote-proc path — the key is
        leased, so a crash here ages out instead of leaking)."""
        puts: Dict[int, list] = {}
        for pos, pk, pv in stripped:
            if wins[pos]:
                puts.setdefault(self._idx(pk), []).append((pk, pv))
        if puts:
            self._fan([lambda i=i, ps=ps: self.shards[i].put_many(
                ps, lease=self._xlease(proc_lease, i))
                for i, ps in puts.items()])

    def claim_bundle(self, order_key: str, items, fence_lease: int = 0,
                     proc_lease: int = 0) -> List[bool]:
        items = [list(it) for it in items]
        if self.nshards == 1:
            return self.shards[0].claim_bundle(order_key, items,
                                               fence_lease, proc_lease)
        groups, oi, stripped = self._split_bundle(order_key, items)
        out: List[bool] = [False] * len(items)

        def run(i, g, ok):
            wins = self.shards[i].claim_bundle(
                ok, [sub for _p, sub in g],
                self._xlease(fence_lease, i),
                self._xlease(proc_lease, i))
            for (pos, _sub), won in zip(g, wins):
                out[pos] = won
        # phase 1: every sub-bundle NOT carrying the reservation key,
        # concurrently; phase 2: the reservation release (the order
        # shard's sub-bundle, or a bare empty-bundle release) — LAST,
        # so a phase-1 failure leaves the leased key for redelivery
        self._fan([lambda i=i, g=g: run(i, g, "")
                   for i, g in groups.items() if i != oi])
        if oi is not None:
            if oi in groups:
                run(oi, groups[oi], order_key)
            else:
                self.shards[oi].claim_bundle(
                    order_key, [], self._xlease(fence_lease, oi),
                    self._xlease(proc_lease, oi))
        if stripped:
            self._put_stripped_procs(stripped, out, proc_lease)
        return out

    def claim_bundle_many(self, bundles, fence_lease: int = 0,
                          proc_lease: int = 0) -> List[List[bool]]:
        if self.nshards == 1:
            return self.shards[0].claim_bundle_many(list(bundles),
                                                    fence_lease,
                                                    proc_lease)
        out: List[List[bool]] = []
        # two per-shard claim_bundle_many waves over the WHOLE backlog:
        # wave 1 carries every order-less sub-bundle, wave 2 carries
        # the reservation releases — batching preserved, release-last
        # ordering preserved
        wave1: Dict[int, list] = {}
        wave2: Dict[int, list] = {}
        fills: List[Optional[Tuple[List[bool], list]]] = []
        strips: List[Tuple[List[bool], list]] = []
        for b in bundles:
            if len(b) < 2 or not isinstance(b[1], (list, tuple)):
                out.append([])      # malformed bundle: [] without abort
                fills.append(None)
                continue
            order_key, items = b[0], [list(it) for it in b[1]]
            wins: List[bool] = [False] * len(items)
            out.append(wins)
            groups, oi, stripped = self._split_bundle(order_key, items)
            if stripped:
                strips.append((wins, stripped))
            fills.append((wins, []))
            for i, g in groups.items():
                wave = wave2 if i == oi else wave1
                wave.setdefault(i, []).append(
                    (order_key if i == oi else "",
                     [sub for _p, sub in g]))
                fills[-1][1].append((wave is wave2, i, g))
            if oi is not None and oi not in groups:
                wave2.setdefault(oi, []).append((order_key, []))
                fills[-1][1].append((True, oi, []))

        def run_wave(wave):
            results = self._fan([
                lambda i=i, bs=bs: self.shards[i].claim_bundle_many(
                    bs, self._xlease(fence_lease, i),
                    self._xlease(proc_lease, i))
                for i, bs in wave.items()])
            # distribute each shard's per-sub-bundle win lists back to
            # the originating bundles, in submission order per shard
            cursors = {i: iter(r) for i, r in
                       zip(wave.keys(), results)}
            return cursors

        for is_second in (False, True):
            wave = wave2 if is_second else wave1
            if not wave:
                continue
            cursors = run_wave(wave)
            for fill in fills:
                if fill is None:
                    continue
                wins, placements = fill
                for w2, i, g in placements:
                    if w2 != is_second:
                        continue
                    sub_wins = next(cursors[i])
                    for (pos, _sub), won in zip(g, sub_wins):
                        wins[pos] = won
        for wins, stripped in strips:
            self._put_stripped_procs(stripped, wins, proc_lease)
        return out

    # ---- watch -----------------------------------------------------------

    def watch(self, prefix: str, start_rev=0, events: str = ""):
        if self.nshards == 1:
            return self.shards[0].watch(prefix, start_rev=start_rev or 0,
                                        events=events)
        if isinstance(start_rev, (list, tuple)):
            if len(start_rev) != self.nshards:
                raise ValueError(
                    f"revision vector has {len(start_rev)} entries for "
                    f"{self.nshards} shards")
            revs = list(start_rev)
        elif start_rev:
            raise ValueError(
                "a sharded watch resumes from a per-shard revision "
                "vector (ShardedWatcher.rev_vector()), not a scalar")
        else:
            revs = [0] * self.nshards
        # a token-pinned prefix (an agent's dispatch/<node>/ stream)
        # lives on ONE shard: open one stream, not N-1 idle ones; the
        # merged watcher still answers a full-length rev vector
        pi = self._prefix_idx(prefix)
        ids = list(range(self.nshards)) if pi is None else [pi]
        opened = []
        try:
            for i in ids:
                opened.append(self.shards[i].watch(
                    prefix, start_rev=revs[i] or 0, events=events))
        except BaseException:
            for w in opened:
                try:
                    w.close()
                except Exception:  # noqa: BLE001 — already dead
                    pass
            raise
        return ShardedWatcher(prefix, opened, events=events,
                              shard_ids=ids, nshards=self.nshards,
                              start_revs=revs)

    # ---- ops / checkpoint plane -----------------------------------------

    def op_stats(self) -> dict:
        """Per-op stats MERGED across shards (counts/total summed,
        max_ms maxed) — same shape as a single store's."""
        parts = self.op_stats_shards()
        if len(parts) == 1:
            return parts[0]
        merged: Dict[str, dict] = {}
        for part in parts:
            for op, ent in part.items():
                m = merged.setdefault(op, {"count": 0, "total_ms": 0.0,
                                           "max_ms": 0.0})
                m["count"] += ent.get("count", 0)
                m["total_ms"] = round(
                    m["total_ms"] + ent.get("total_ms", 0.0), 3)
                m["max_ms"] = max(m["max_ms"], ent.get("max_ms", 0.0))
        return merged

    def op_stats_shards(self) -> List[dict]:
        """Per-SHARD op stats, shard order — /v1/metrics renders these
        with a ``shard`` label when more than one is present.  A
        degraded shard reports ``{}`` (tolerant: metrics scraping must
        not stall behind a browned-out shard)."""
        return self._fan([
            self._tolerant(i, lambda s=s: s.op_stats(), default={})
            for i, s in enumerate(self.shards)])

    def snapshot(self) -> List[int]:
        """Snapshot every shard (per-shard WAL + snapshot sidecar, the
        PR 5 format unchanged); returns the per-shard revision vector."""
        if self.nshards == 1:
            return self.shards[0].snapshot()
        return self._fan([lambda s=s: s.snapshot() for s in self.shards])

    def rev(self):
        """Scalar revision only exists for one shard; a sharded store
        returns the per-shard vector (checkpoint consumers that need a
        scalar are disabled on sharded stores)."""
        if self.nshards == 1:
            return self.shards[0].rev()
        return self.rev_vector()

    def rev_vector(self) -> List[int]:
        return self._fan([lambda s=s: s.rev() for s in self.shards])

    # ---- lifecycle -------------------------------------------------------

    def clone(self) -> "ShardedStore":
        """Fresh connections to every shard sharing THIS client's
        composite-lease registry (publisher lanes).  A shard client
        with no clone() of its own (MemStore) is ALIASED — the clone's
        close() must leave it alone, or closing a publisher lane would
        kill the parent's live watchers and WAL."""
        kids = [s.clone() if hasattr(s, "clone") else s
                for s in self._raw]
        c = ShardedStore(kids, prefix=self.prefix, verify_map=False,
                         _parent=self,
                         shard_deadline=self.shard_deadline)
        c._owned = [kid is not s for kid, s in zip(kids, self._raw)]
        return c

    def start_sweeper(self, interval: float = 0.2):
        for s in self.shards:
            s.start_sweeper(interval)

    def close(self):
        for own, s in zip(self._owned, self._raw):
            if not own:
                continue        # aliased parent shard (see clone())
            try:
                s.close()
            except Exception as e:  # noqa: BLE001 — best-effort teardown
                log.warnf("shard close failed: %s", e)
        if self._pool is not None:
            self._pool.shutdown(wait=False)


def verify_single_store(store, prefix: str = "/cronsun"):
    """Topology pin for a SINGLE-address client: a stale one-store
    config pointed at shard 0 of a multi-shard layout must refuse
    (it would fence every job on one shard and race the rest of the
    fleet for (job, second) fences), not silently serve.  Read-only —
    an un-sharded set never writes the pin, so its behavior is
    unchanged."""
    key = shard_map_key(prefix)
    kv = store.get(key)
    if kv is None:
        return
    try:
        got = json.loads(kv.value)
    except ValueError:
        got = None
    if not isinstance(got, dict) or got.get("n") != 1:
        raise RuntimeError(
            f"shard-map mismatch at {key}: store set was laid out as "
            f"{got!r}, this client is configured for a single store — "
            "refusing to scatter one keyspace under two topologies")


def connect_sharded(addrs: Sequence[str], prefix: str = "/cronsun",
                    timeout: float = 120.0, token: str = "",
                    sslctx=None, tls_hostname: str = ""):
    """Connect a routing client to a shard set.  One address returns a
    plain RemoteStore (byte-identical single-store behavior); several
    return a ShardedStore that pins/verifies the shard map.

    Each shard entry may be an ``addr1|addr2|addr3`` REPLICA GROUP
    (replication plane, repl/): the shard's client becomes a
    ReplicaGroupStore that discovers the group's leader and rotates on
    leader loss through the breaker/backoff ladders.  A group with an
    empty member ("a|,b", "a||b") refuses HERE, at parse time — an
    empty address would otherwise surface as a confusing dial error
    mid-rotation."""
    from .remote import RemoteStore
    conns = []
    try:
        for addr in addrs:
            if "|" in addr:
                members = [m.strip() for m in addr.split("|")]
                if any(not m for m in members):
                    raise ValueError(
                        f"replica group {addr!r} has an empty member "
                        "(want addr1|addr2|...; no doubled, leading, "
                        "or trailing '|')")
                from ..repl.client import ReplicaGroupStore
                conns.append(ReplicaGroupStore(
                    members, timeout=timeout, token=token,
                    sslctx=sslctx, tls_hostname=tls_hostname))
                continue
            host, _, port = addr.rpartition(":")
            conns.append(RemoteStore(host or "127.0.0.1", int(port),
                                     timeout=timeout, token=token,
                                     sslctx=sslctx,
                                     tls_hostname=tls_hostname))
    except BaseException:
        for c in conns:
            c.close()
        raise
    if len(conns) == 1:
        try:
            verify_single_store(conns[0], prefix)
        except BaseException:
            conns[0].close()
            raise
        return conns[0]
    return ShardedStore(conns, prefix=prefix)
